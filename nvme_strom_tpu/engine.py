"""Core data-path engine: sources, buffers, chunk planner, async task table.

This is the capability heart of the framework — everything the reference's
kernel module does (`kmod/nvme_strom.c`), rebuilt as an in-process engine:

* **eligibility check** — ``check_file`` (reference ``ioctl_check_file``,
  kmod/nvme_strom.c:188-583): O_DIRECT capability probe, fs classification,
  block size, NUMA node, DMA request cap.
* **sources** — plain files, PostgreSQL-style segmented relations, and
  RAID-0-striped member sets, all resolving logical ranges to physical
  extents (the in-kernel ``strom_get_block`` + ``strom_raid0_map_sector``
  resolution, :174-186, :823-910, moved to userspace).
* **chunk planner** — page-cache arbitration (hot chunks take the write-back
  path, reference :1639-1663, probed here with ``mincore``) and merging of
  physically-contiguous reads into up to ``dma_max_size`` requests
  (reference merge condition :1473-1505).
* **async task table** — one task per memcpy command; 512-slot hash with
  per-slot condition variables (so spurious wakeups are real and *counted*,
  reference ``nr_wrong_wakeup`` :1303-1304); per-request refcounting; first
  error latched; **failed tasks retained until reaped by a wait or by
  session close** (reference design memo :612-626, reap at :2138-2166).
* **stats** — every stage timed into the count+clock registry (SS5.1).

Two interchangeable I/O backends execute the planned requests: the native
C++ engine (io_uring, ``nvme_strom_tpu._native``) and a portable thread-pool
fallback defined here.  Both consume the same plan, so they are
differentially testable.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno as _errno
import mmap
import os
import random
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

from .api import (BufferInfo, DmaTaskState, ErrorClass, FileInfo, FsKind,
                  MemCopyResult, StromError)
from .config import config
from . import blockmap
from .fault import (DirtyExtentJournal, HealthState, MemberHealthMachine,
                    RetryPolicy)
from .log import pr_info, pr_warn
from .eligibility import probe_backing
from .stats import stats
from .trace import recorder as _trace
from .autotune import AutoTuner
from .tiering import extent_space as _tiers
from .integrity import domain as _integrity, Scrubber as _Scrubber
from . import numa as _numa

#: live sessions, for the stat exporter's pre-publish fold (weak: the
#: registry must never keep a closed session alive)
import weakref as _weakref

_live_sessions: "_weakref.WeakSet" = _weakref.WeakSet()


def _fold_live_native_stats() -> None:
    for s in list(_live_sessions):
        try:
            if getattr(s, "_native", None) is not None \
                    and not s._closed:
                s._fold_native_stats()
        except Exception:   # noqa: BLE001 — observability, not control
            pass
from .stripe import StripeMap

__all__ = [
    "check_file", "Source", "PlainSource", "SegmentedSource", "StripedSource",
    "DmaBuffer", "Session", "Request", "plan_requests", "open_source",
    "plan_shard_ownership",
]

PAGE_SIZE = mmap.PAGESIZE
_libc = ctypes.CDLL(None, use_errno=True)

# statfs magics (reference checks these at kmod/nvme_strom.c:477-486)
_EXT4_SUPER_MAGIC = 0xEF53
_XFS_SUPER_MAGIC = 0x58465342


def _fs_magic(path: str) -> int:
    """f_type from statfs(2)."""
    class _Statfs(ctypes.Structure):
        _fields_ = [("f_type", ctypes.c_long), ("f_bsize", ctypes.c_long),
                    ("_pad", ctypes.c_byte * 256)]
    buf = _Statfs()
    if _libc.statfs(os.fsencode(path), ctypes.byref(buf)) != 0:
        return 0
    return buf.f_type & 0xFFFFFFFF


def _probe_odirect(path: str) -> bool:
    try:
        fd = os.open(path, os.O_RDONLY | os.O_DIRECT)
    except OSError:
        return False
    os.close(fd)
    return True


def check_file(path: str, *, dma_max_size: Optional[int] = None,
               strict: Optional[bool] = None,
               sysfs_root: str = "/sys") -> FileInfo:
    """CHECK_FILE: classify *path* for the direct-load path.

    Reference semantics (`kmod/nvme_strom.c:188-583`): read permission, fs
    identity, blocksize <= PAGE_SIZE, file at least one page (inline files
    excluded), raw-NVMe-or-RAID0 backing, NUMA node, DMA64, request cap.

    The TPU engine's hard requirement is an O_DIRECT-capable regular file;
    the backing-device verdict (``backing_supported`` / ``backing_reason``,
    from :229-438's raw-NVMe/md-RAID0 walk redone over sysfs) is always
    reported, and with ``strict=True`` (or config ``require_nvme_backing``)
    an unverified backing makes the file UNSUPPORTED outright — the
    reference's behavior, where a SATA or network fs could never be
    green-lit for the fast path."""
    st = os.stat(path)
    if not os.access(path, os.R_OK):
        raise StromError(_errno.EACCES, f"no read permission: {path}")
    magic = _fs_magic(path)
    if magic == _EXT4_SUPER_MAGIC:
        kind = FsKind.EXT4
    elif magic == _XFS_SUPER_MAGIC:
        kind = FsKind.XFS
    elif _probe_odirect(path):
        kind = FsKind.OTHER_DIRECT
    else:
        kind = FsKind.UNSUPPORTED
    if kind in (FsKind.EXT4, FsKind.XFS) and not _probe_odirect(path):
        kind = FsKind.UNSUPPORTED
    backing = probe_backing(path, sysfs_root=sysfs_root)
    if strict is None:
        strict = config.get("require_nvme_backing")
    # strict policy is a separate verdict, NOT an fs_kind clobber: fs_kind
    # stays an honest fact so cached probes + a live policy check compose.
    # The predicate itself lives in FileInfo.strict_eligible (backing
    # verified AND dma64) so tools and planner share one definition.
    policy_rejected = bool(strict and not (backing.supported
                                           and backing.support_dma64))
    # reference excludes files smaller than one page (inline data risk,
    # kmod/nvme_strom.c:503-518)
    if st.st_size < PAGE_SIZE:
        kind = FsKind.UNSUPPORTED
    cap = dma_max_size or config.get("dma_max_size")
    if backing.dma_max_size:
        # min(hw ceiling, admin soft limit), resolved by the classifier
        # (:297-314 analog) — no second walk of the real /sys here, so
        # fake-tree probes stay hermetic
        cap = min(cap, backing.dma_max_size)
    # numa -1 is a *verdict* for RAID0 spanning nodes (kmod :322-326) and
    # honest "unknown" otherwise; consumers guard negative nodes
    return FileInfo(path=path, file_size=st.st_size, fs_kind=kind,
                    logical_block_size=backing.logical_block_size or 512,
                    dma_max_size=cap,
                    numa_node_id=backing.numa_node_id,
                    support_dma64=backing.support_dma64,
                    n_members=max(1, len(backing.members)),
                    stripe_chunk_size=backing.stripe_chunk_size,
                    backing_kind=backing.kind,
                    backing_supported=backing.supported,
                    backing_reason=backing.reason,
                    policy_rejected=policy_rejected)


# ---------------------------------------------------------------------------
# Sources
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Extent:
    """Physically contiguous piece of a logical range on one member fd."""

    member: int
    file_off: int
    length: int
    logical_off: int


class Source:
    """A logical byte stream resolvable to physical extents.

    Read-oriented by default; opened with ``writable=True`` it also
    carries the RAM→SSD write legs (a capability the read-only reference
    lacks — its engine only builds NVMe READ commands,
    kmod/nvme_strom.c:1136-1224)."""

    size: int
    block_size: int
    writable: bool = False

    def extents(self, offset: int, length: int) -> List[Extent]:
        raise NotImplementedError

    def member_fds(self) -> List[int]:
        """O_DIRECT fds, one per member."""
        raise NotImplementedError

    def mirror_of(self, member: int) -> Optional[int]:
        """Member holding a byte-identical replica of *member* (same
        member offsets), or None when the source has no redundancy.
        Striped sources opened with ``mirror='paired'`` override this;
        it is the basis for degraded-mode striping and hedged reads."""
        return None

    def cached_fraction(self, offset: int, length: int) -> float:
        """Fraction of the range resident in the host page cache
        (reference probes with find_lock_page, kmod/nvme_strom.c:1639-1645;
        here with mincore(2))."""
        return 0.0

    # -- hot-data signal (the PageDirty analog) ----------------------------
    # The reference scores a dirty page at threshold+1 — ONE dirty page
    # tips the whole chunk to write-back (kmod/nvme_strom.c:1639-1645),
    # because a dirty page makes the on-disk block stale and a direct read
    # would either return stale data or stall on a forced flush.  Userspace
    # cannot see PageDirty directly, so the signal is rebuilt from two
    # sides: an explicit hint API for writers that know their hot ranges,
    # plus (where /proc/kpageflags is readable) a best-effort probe.

    def hint_hot_range(self, offset: int, length: int) -> None:
        """Declare [offset, offset+length) hot (being written / recently
        written): chunks overlapping it take the write-back path instead
        of forcing a flush stall on the direct path."""
        if length <= 0:
            return
        hints = getattr(self, "_hot_hints", None)
        if hints is None:
            hints = self._hot_hints = []
        hints.append((offset, offset + length))

    def clear_hot_hints(self) -> None:
        self._hot_hints = []

    def hot_fraction(self, offset: int, length: int) -> float:
        """Fraction of the range covered by hot hints (subclasses may add
        measured dirtiness).  Any value > 0 routes the chunk write-back,
        mirroring the reference's one-dirty-page rule."""
        hints = getattr(self, "_hot_hints", None)
        if not hints or length <= 0:
            return 0.0
        covered = 0
        for h0, h1 in hints:
            lo, hi = max(offset, h0), min(offset + length, h1)
            if hi > lo:
                covered += hi - lo  # hints may overlap; fraction is advisory
        return min(covered / length, 1.0)

    def residency(self, spans: Sequence[Tuple[int, int]]
                  ) -> List[Tuple[float, float]]:
        """Per-span ``(cached_fraction, hot_fraction)`` for a batch of
        ``(offset, length)`` ranges — the cache-arbitration probe for one
        whole task.  The default defers to the scalar probes so subclass
        overrides (test fakes, forced verdicts) keep deciding arbitration;
        real file sources override this with a single batched mincore(2)
        scan to keep the probe off the submission critical path."""
        return [(self.cached_fraction(o, l), self.hot_fraction(o, l))
                for o, l in spans]

    def read_buffered(self, offset: int, dest: memoryview) -> None:
        """Page-cache copy path (reference memcpy_pgcache_to_ubuffer,
        kmod/nvme_strom.c:1344-1401)."""
        raise NotImplementedError

    def read_member_buffered(self, member: int, file_off: int, dest: memoryview) -> None:
        """Buffered read addressed by (member, member offset) — used for
        misaligned tails that O_DIRECT cannot express."""
        raise NotImplementedError

    def read_member_direct(self, member: int, file_off: int, dest: memoryview) -> None:
        """O_DIRECT read of one planned request (the async-engine read leg).
        Overridable by test fakes for latency/fault injection."""
        fd = self.member_fds()[member]
        if fd < 0:
            raise StromError(_errno.EINVAL, "member has no O_DIRECT fd")
        done, length = 0, len(dest)
        while done < length:
            n = os.preadv(fd, [dest[done:length]], file_off + done)
            if n <= 0:
                raise StromError(_errno.EIO, f"short direct read at {file_off + done}")
            done += n

    def read_member_direct_v(self, member: int, file_off: int,
                             dests: Sequence[memoryview]) -> None:
        """Vectored O_DIRECT read: ONE file-contiguous span scattered into
        several destination segments (the coalesced form of stripe-adjacent
        extents — reference request merging, kmod/nvme_strom.c:1473-1505).

        When a subclass (or test fake) overrides the scalar read leg, fall
        back to per-segment scalar reads so latency/fault injection still
        sees every segment; the real source issues a single preadv."""
        if type(self).read_member_direct is not Source.read_member_direct:
            off = file_off
            for d in dests:
                self.read_member_direct(member, off, d)
                off += len(d)
            return
        fd = self.member_fds()[member]
        if fd < 0:
            raise StromError(_errno.EINVAL, "member has no O_DIRECT fd")
        remaining = list(dests)
        pos = file_off
        while remaining:
            n = os.preadv(fd, remaining, pos)
            if n <= 0:
                raise StromError(_errno.EIO, f"short direct read at {pos}")
            pos += n
            while remaining and n >= len(remaining[0]):
                n -= len(remaining[0])
                remaining.pop(0)
            if n:
                remaining[0] = remaining[0][n:]

    # -- write legs (RAM→SSD; requires writable=True) ----------------------
    def member_buffered_fds(self) -> List[int]:
        raise NotImplementedError

    def _check_writable(self) -> None:
        if not self.writable:
            raise StromError(_errno.EBADF, "source opened read-only; "
                             "open_source(..., writable=True)")

    def write_member_direct(self, member: int, file_off: int, src: memoryview) -> None:
        """O_DIRECT write of one planned request (the async write leg)."""
        self._check_writable()
        fd = self.member_fds()[member]
        if fd < 0:
            raise StromError(_errno.EINVAL, "member has no O_DIRECT fd")
        done, length = 0, len(src)
        while done < length:
            n = os.pwritev(fd, [src[done:length]], file_off + done)
            if n <= 0:
                raise StromError(_errno.EIO, f"short direct write at {file_off + done}")
            done += n

    def write_member_buffered(self, member: int, file_off: int, src: memoryview) -> None:
        """Buffered write — misaligned pieces O_DIRECT cannot express."""
        self._check_writable()
        fd = self.member_buffered_fds()[member]
        done, length = 0, len(src)
        while done < length:  # partial buffered writes are legal; loop
            n = os.pwritev(fd, [src[done:length]], file_off + done)
            if n <= 0:
                raise StromError(_errno.EIO,
                                 f"short buffered write at {file_off + done}")
            done += n

    def sync(self) -> None:
        """fsync every member (durability for the buffered write legs)."""
        for fd in self.member_buffered_fds():
            os.fsync(fd)

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# mincore(2) defines only bit 0 of each residency byte; translate through
# this table before counting so reserved high bits can never skew a scan
_MINCORE_LSB = bytes((i & 1) for i in range(256))


class _FileMember:
    """One underlying file: direct fd + buffered fd + mmap for cache probe."""

    def __init__(self, path: str, writable: bool = False):
        self.path = path
        self.size = os.stat(path).st_size
        self.writable = writable
        mode = os.O_RDWR if writable else os.O_RDONLY
        try:
            self.fd_direct = os.open(path, mode | os.O_DIRECT)
        except OSError:
            self.fd_direct = -1
        self.fd_buffered = os.open(path, mode)
        self._mm: Optional[mmap.mmap] = None
        self._mm_addr = 0
        self._mincore_buf = None     # per-member scratch, grown on demand
        self._mincore_cap = 0

    def mm(self) -> Optional[mmap.mmap]:
        if self._mm is None and self.size > 0:
            # MAP_PRIVATE read-write: pages stay page-cache-backed (we never
            # write), and ctypes can take the address for mincore(2)
            self._mm = mmap.mmap(self.fd_buffered, self.size,
                                 flags=mmap.MAP_PRIVATE,
                                 prot=mmap.PROT_READ | mmap.PROT_WRITE)
            self._mm_addr = ctypes.addressof(ctypes.c_char.from_buffer(self._mm))
        return self._mm

    def _mincore_scratch(self, npages: int):
        """Grow-and-return the member's shared mincore(2) residency
        vector, sized for at least *npages* entries.  Arbitration probes
        every chunk of every read: one scratch per member instead of an
        allocation per call — callers consume the result before the next
        probe on this member, and only the first npages entries are live."""
        if npages > self._mincore_cap:
            self._mincore_cap = max(npages, self._mincore_cap * 2, 256)
            self._mincore_buf = (ctypes.c_ubyte * self._mincore_cap)()
        return self._mincore_buf

    def _mincore_vec(self, offset: int, length: int):
        """(residency bytevec, start, npages) for the page-aligned range."""
        mm = self.mm()
        if mm is None or length <= 0:
            return None, 0, 0
        start = offset & ~(PAGE_SIZE - 1)
        end = min((offset + length + PAGE_SIZE - 1) & ~(PAGE_SIZE - 1), self.size)
        npages = max((end - start + PAGE_SIZE - 1) // PAGE_SIZE, 1)
        vec = self._mincore_scratch(npages)
        rc = _libc.mincore(ctypes.c_void_p(self._mm_addr + start),
                           ctypes.c_size_t(end - start), vec)
        if rc != 0:
            return None, 0, 0
        return vec, start, npages

    def cached_fraction(self, offset: int, length: int) -> float:
        vec, _start, npages = self._mincore_vec(offset, length)
        if vec is None:
            return 0.0
        # vec is the shared scratch — only the first npages entries are live
        resident = ctypes.string_at(vec, npages).translate(_MINCORE_LSB).count(1)
        return resident / npages

    def cached_spans(self, spans: Sequence[Tuple[int, int]]
                     ) -> List[Tuple[float, bool]]:
        """Per-span ``(cached_fraction, any_resident)`` from ONE mincore(2)
        over the enclosing range.  Arbitration probes every chunk of every
        task; batching turns 2 syscalls + a Python scan per chunk into one
        syscall + bytes ops per task (~5ms off a 128-chunk submit)."""
        if not spans:
            return []
        mm = self.mm()
        if mm is None:
            return [(0.0, False)] * len(spans)
        lo = min(o for o, _ in spans) & ~(PAGE_SIZE - 1)
        end = min(max(o + l for o, l in spans), self.size)
        npages = max((end - lo + PAGE_SIZE - 1) // PAGE_SIZE, 1)
        vec = self._mincore_scratch(npages)
        rc = _libc.mincore(ctypes.c_void_p(self._mm_addr + lo),
                           ctypes.c_size_t(end - lo), vec)
        if rc != 0:
            return [(0.0, False)] * len(spans)
        raw = ctypes.string_at(vec, npages).translate(_MINCORE_LSB)
        out = []
        for o, l in spans:
            p0 = ((o & ~(PAGE_SIZE - 1)) - lo) // PAGE_SIZE
            p1 = (min(o + l, self.size) - lo + PAGE_SIZE - 1) // PAGE_SIZE
            res = raw[p0:p1].count(1)
            out.append((res / max(p1 - p0, 1), res > 0))
        return out

    def dirty_fraction(self, offset: int, length: int) -> float:
        """Best-effort PageDirty probe (kmod/nvme_strom.c:1643 analog)
        via /proc/self/pagemap -> /proc/kpageflags (KPF_DIRTY).

        Only pages mincore reports resident are touched (mapping an
        already-resident page into our tables does not perturb the cache);
        unreadable proc files degrade to 0.0 — the hint API is then the
        only dirty signal."""
        vec, start, npages = self._mincore_vec(offset, length)
        if vec is None:
            return 0.0
        raw = ctypes.string_at(vec, npages)
        resident = [i for i, b in enumerate(raw) if b & 1]
        if not resident:
            return 0.0
        try:
            pm = os.open("/proc/self/pagemap", os.O_RDONLY)
        except OSError:
            return 0.0
        try:
            try:
                kf = os.open("/proc/kpageflags", os.O_RDONLY)
            except OSError:
                return 0.0
            try:
                dirty = 0
                for i in resident:
                    va = self._mm_addr + start + i * PAGE_SIZE
                    # fault the (resident) page into our tables so pagemap
                    # shows its PFN; a read fault never dirties it
                    ctypes.c_ubyte.from_address(va).value
                    ent = os.pread(pm, 8, (va // PAGE_SIZE) * 8)
                    if len(ent) != 8:
                        continue
                    word = int.from_bytes(ent, "little")
                    if not word >> 63:  # not present
                        continue
                    pfn = word & ((1 << 55) - 1)
                    if pfn == 0:
                        continue
                    flags_b = os.pread(kf, 8, pfn * 8)
                    if len(flags_b) != 8:
                        continue
                    if (int.from_bytes(flags_b, "little") >> 4) & 1:  # KPF_DIRTY
                        dirty += 1
                return dirty / npages
            finally:
                os.close(kf)
        finally:
            os.close(pm)

    def close(self) -> None:
        if self._mm is not None:
            try:
                self._mm.close()
            except BufferError:
                pass  # a ctypes view still pins it; dropped with the process
            self._mm = None
        if self.fd_direct >= 0:
            os.close(self.fd_direct)
            self.fd_direct = -1
        if self.fd_buffered >= 0:
            os.close(self.fd_buffered)
            self.fd_buffered = -1


class PlainSource(Source):
    """A single regular file."""

    def __init__(self, path: str, block_size: int = 512,
                 writable: bool = False):
        self._m = _FileMember(path, writable)
        self.path = path
        self.size = self._m.size
        self.block_size = block_size
        self.writable = writable

    def extents(self, offset: int, length: int) -> List[Extent]:
        if offset < 0 or offset + length > self.size:
            raise StromError(_errno.EINVAL,
                            f"range [{offset},{offset+length}) outside file of {self.size}")
        return [Extent(0, offset, length, offset)]

    def member_fds(self) -> List[int]:
        return [self._m.fd_direct]

    def member_buffered_fds(self) -> List[int]:
        return [self._m.fd_buffered]

    def cached_fraction(self, offset: int, length: int) -> float:
        return self._m.cached_fraction(offset, length)

    def hot_fraction(self, offset: int, length: int) -> float:
        # explicit hints plus measured page dirtiness, whichever is louder
        hinted = super().hot_fraction(offset, length)
        if hinted >= 1.0:
            return hinted
        return max(hinted, self._m.dirty_fraction(offset, length))

    def residency(self, spans: Sequence[Tuple[int, int]]
                  ) -> List[Tuple[float, float]]:
        # one batched mincore for the whole task — but only when the scalar
        # probes are OURS: a subclass that overrides either one (forced
        # verdicts in test fakes) still owns arbitration via the default
        if (type(self).cached_fraction is not PlainSource.cached_fraction
                or type(self).hot_fraction is not PlainSource.hot_fraction):
            return super().residency(spans)
        out = []
        for (off, ln), (frac, any_res) in zip(spans, self._m.cached_spans(spans)):
            hot = Source.hot_fraction(self, off, ln)   # hint coverage
            if hot < 1.0 and any_res:
                # dirtiness requires residency: skip the /proc probe on
                # ranges the batched scan showed fully cold
                hot = max(hot, self._m.dirty_fraction(off, ln))
            out.append((frac, hot))
        return out

    def read_buffered(self, offset: int, dest: memoryview) -> None:
        n = os.preadv(self._m.fd_buffered, [dest], offset)
        if n != len(dest):
            raise StromError(_errno.EIO, f"short buffered read {n} != {len(dest)}")

    def read_member_buffered(self, member: int, file_off: int, dest: memoryview) -> None:
        n = os.preadv(self._m.fd_buffered, [dest], file_off)
        if n != len(dest):
            raise StromError(_errno.EIO, "short buffered read")

    def close(self) -> None:
        self._m.close()


class SegmentedSource(Source):
    """PostgreSQL-style segmented relation: logically one stream split across
    fixed-size segment files (reference mirrors md.c's MdfdVec per-segment fd
    table, pgsql/nvme_strom.c:124-130,692-714)."""

    def __init__(self, paths: Sequence[str], segment_size: int, block_size: int = 512,
                 writable: bool = False):
        if segment_size <= 0:
            raise StromError(_errno.EINVAL, "segment_size must be positive")
        self.members = [_FileMember(p, writable) for p in paths]
        for m in self.members[:-1]:
            if m.size != segment_size:
                raise StromError(_errno.EINVAL,
                                f"non-final segment {m.path} has size {m.size} != {segment_size}")
        self.segment_size = segment_size
        self.size = sum(m.size for m in self.members)
        self.block_size = block_size
        self.writable = writable

    def extents(self, offset: int, length: int) -> List[Extent]:
        if offset < 0 or offset + length > self.size:
            raise StromError(_errno.EINVAL, "range outside segmented relation")
        out: List[Extent] = []
        pos, rem = offset, length
        while rem > 0:
            seg, soff = divmod(pos, self.segment_size)
            take = min(self.segment_size - soff, rem)
            out.append(Extent(seg, soff, take, pos))
            pos += take
            rem -= take
        return out

    def member_fds(self) -> List[int]:
        return [m.fd_direct for m in self.members]

    def member_buffered_fds(self) -> List[int]:
        return [m.fd_buffered for m in self.members]

    def cached_fraction(self, offset: int, length: int) -> float:
        total, weight = 0.0, 0
        for e in self.extents(offset, length):
            total += self.members[e.member].cached_fraction(e.file_off, e.length) * e.length
            weight += e.length
        return total / weight if weight else 0.0

    def read_buffered(self, offset: int, dest: memoryview) -> None:
        done = 0
        for e in self.extents(offset, len(dest)):
            n = os.preadv(self.members[e.member].fd_buffered,
                          [dest[done:done + e.length]], e.file_off)
            if n != e.length:
                raise StromError(_errno.EIO, "short buffered read")
            done += e.length

    def read_member_buffered(self, member: int, file_off: int, dest: memoryview) -> None:
        n = os.preadv(self.members[member].fd_buffered, [dest], file_off)
        if n != len(dest):
            raise StromError(_errno.EIO, "short buffered read")

    def close(self) -> None:
        for m in self.members:
            m.close()


class StripedSource(Source):
    """RAID-0 striped member set resolved with :class:`StripeMap`."""

    def __init__(self, paths: Sequence[str], stripe_chunk_size: int,
                 block_size: int = 512, writable: bool = False,
                 mirror: Optional[str] = None):
        if mirror is None:
            mirror = str(config.get("mirror"))
        # mirror='paired' + writable is first-class since ISSUE 11: the
        # engine fans each aligned write leg out to the pair partner
        # (mirror-coherent writes), so written stripes keep the degraded-
        # mode read guarantees instead of silently losing their replica
        self.members = [_FileMember(p, writable) for p in paths]
        self.map = StripeMap([m.size for m in self.members],
                             stripe_chunk_size, mirror=mirror)
        self.size = self.map.total_size
        self.block_size = block_size
        self.stripe_chunk_size = stripe_chunk_size
        self.writable = writable

    def mirror_of(self, member: int) -> Optional[int]:
        return self.map.mirror_of(member)

    def extents(self, offset: int, length: int) -> List[Extent]:
        return [Extent(e.member, e.member_offset, e.length, e.logical_offset)
                for e in self.map.map_range(offset, length)]

    def member_fds(self) -> List[int]:
        return [m.fd_direct for m in self.members]

    def member_buffered_fds(self) -> List[int]:
        return [m.fd_buffered for m in self.members]

    def cached_fraction(self, offset: int, length: int) -> float:
        total, weight = 0.0, 0
        for e in self.extents(offset, length):
            total += self.members[e.member].cached_fraction(e.file_off, e.length) * e.length
            weight += e.length
        return total / weight if weight else 0.0

    def read_buffered(self, offset: int, dest: memoryview) -> None:
        for e in self.extents(offset, len(dest)):
            rel = e.logical_off - offset
            n = os.preadv(self.members[e.member].fd_buffered,
                          [dest[rel:rel + e.length]], e.file_off)
            if n != e.length:
                raise StromError(_errno.EIO, "short buffered read")

    def read_member_buffered(self, member: int, file_off: int, dest: memoryview) -> None:
        n = os.preadv(self.members[member].fd_buffered, [dest], file_off)
        if n != len(dest):
            raise StromError(_errno.EIO, "short buffered read")

    def close(self) -> None:
        for m in self.members:
            m.close()


def open_source(spec: Union[str, Sequence[str]], *,
                stripe_chunk_size: Optional[int] = None,
                segment_size: Optional[int] = None,
                block_size: Optional[int] = None,
                writable: bool = False,
                mirror: Optional[str] = None) -> Source:
    """Open a plain, striped, or segmented source from a path spec."""
    if isinstance(spec, str):
        info = check_file(spec)
        return PlainSource(spec, block_size or info.logical_block_size,
                           writable)
    paths = list(spec)
    if stripe_chunk_size:
        return StripedSource(paths, stripe_chunk_size, block_size or 512,
                             writable, mirror=mirror)
    if segment_size:
        return SegmentedSource(paths, segment_size, block_size or 512,
                               writable)
    raise StromError(_errno.EINVAL,
                    "multi-path source needs stripe_chunk_size or segment_size")


# ---------------------------------------------------------------------------
# DMA buffers
# ---------------------------------------------------------------------------

class DmaBuffer:
    """Pinned, page-aligned host buffer (hugepage-backed when available).

    Analog of the reference's hugepage DMA buffer (`kmod/pmemmap.c:497-649`)
    and the pgsql NUMA-aware pool chunks (`pgsql/nvme_strom.c:1454-1526`):
    anonymous mmap, MAP_HUGETLB attempted first, then mlock'd so the kernel
    cannot migrate pages mid-I/O."""

    def __init__(self, length: int, *, numa_node: int = -1, pin: Optional[bool] = None):
        if length <= 0:
            raise StromError(_errno.EINVAL, "buffer length must be positive")
        length = (length + PAGE_SIZE - 1) & ~(PAGE_SIZE - 1)
        self.length = length
        self.numa_node = numa_node
        self.hugepages = False
        mm = None
        flags = mmap.MAP_PRIVATE | mmap.MAP_ANONYMOUS
        if hasattr(mmap, "MAP_HUGETLB") and length % (2 << 20) == 0:
            try:
                mm = mmap.mmap(-1, length, flags=flags | mmap.MAP_HUGETLB)
                self.hugepages = True
            except OSError:
                mm = None
        if mm is None:
            mm = mmap.mmap(-1, length, flags=flags)
        self._mm = mm
        self.addr = ctypes.addressof(ctypes.c_char.from_buffer(mm))
        self.pinned = False
        if pin if pin is not None else config.get("pin_memory"):
            self.pinned = _libc.mlock(ctypes.c_void_p(self.addr),
                                      ctypes.c_size_t(length)) == 0
        # prefault so first DMA doesn't eat page faults (reference prefaults
        # its shm pool, pgsql/nvme_strom.c:1500-1510)
        mm[0:length:PAGE_SIZE] = b"\0" * len(range(0, length, PAGE_SIZE))
        self._close_cbs: List = []
        self._cb_lock = threading.Lock()
        self._closing = False

    def on_close(self, cb) -> bool:
        """Arrange for *cb* to run when this buffer is closed (BEFORE the
        munmap) — how a session keeps io_uring fixed-buffer registrations
        exactly coextensive with the mapping (a registration outliving the
        mmap would alias whatever lands at the address next).  Returns
        False when the buffer is already closed/closing: the caller must
        run its cleanup itself."""
        with self._cb_lock:
            if self._mm is None or self._closing:
                return False
            self._close_cbs.append(cb)
            return True

    def remove_close_cb(self, cb) -> None:
        """Detach a close callback (a closing Session removes its hooks so
        long-lived pool buffers don't accumulate dead-session closures)."""
        with self._cb_lock:
            try:
                self._close_cbs.remove(cb)
            except ValueError:
                pass

    def view(self) -> memoryview:
        return memoryview(self._mm)

    def close(self) -> None:
        with self._cb_lock:
            if self._mm is None or self._closing:
                return
            self._closing = True
            cbs, self._close_cbs = self._close_cbs, []
        for cb in cbs:
            try:
                cb()
            except Exception:
                pass
        if self.pinned:
            _libc.munlock(ctypes.c_void_p(self.addr), ctypes.c_size_t(self.length))
        try:
            self._mm.close()
        except BufferError:
            pass
        with self._cb_lock:
            self._mm = None

    def __del__(self):  # pragma: no cover - GC backstop
        # a registered-but-never-closed buffer must still release its
        # io_uring registration BEFORE the mmap finalizer unmaps the range
        # (a stale fixed slot over a recycled VA would alias silently)
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Chunk planner
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Request:
    """One merged I/O request (<= dma_max_size bytes, one member — or up
    to coalesce_limit when the second merge pass ran)."""

    member: int
    file_off: int
    length: int
    dest_off: int
    buffered: bool = False   # misaligned tail falls back to buffered read
    # stripe-coalesced vectored read: when non-empty, the (file-contiguous)
    # span scatters into these (dest_off, length) segments — dest_off above
    # is then the first segment's offset and length the span total
    dest_segs: Tuple[Tuple[int, int], ...] = ()
    # NVMe passthrough lane (PR 19): blockmap-resolved DEVICE byte offset
    # when this request rides the raw-command path; None rides O_DIRECT.
    # Set only by the plan-time per-extent split, never by plan_requests.
    passthru_off: Optional[int] = None


def plan_requests(source: Source, chunk_entries: Sequence[Tuple[int, int]],
                  chunk_size: int, dest_base: int, *,
                  dma_max_size: Optional[int] = None,
                  dest_segment_shift: Optional[int] = None,
                  coalesce_limit: Union[int, Dict[int, int], None] = None
                  ) -> List[Request]:
    """Merge chunk reads into large requests.

    *chunk_entries* is ``[(chunk_id, dest_slot), ...]``; chunk ``cid`` covers
    logical bytes ``[cid*chunk_size, ...+chunk_size)`` (clamped to source
    size) and lands at ``dest_base + dest_slot*chunk_size``.

    Merge conditions mirror the reference (`kmod/nvme_strom.c:1473-1505`):
    same member, file-contiguous, destination-contiguous, merged length
    <= ``dma_max_size``, and never across a destination segment boundary when
    ``dest_segment_shift`` is given (the reference splits at GPU BAR segment /
    hugepage boundaries; a virtually-contiguous host buffer needs no split).
    Misaligned head/tail pieces (non-block-multiple file tail) are planned as
    buffered reads since O_DIRECT cannot express them.

    ``coalesce_limit`` (opt-in) runs a SECOND merge pass beyond the
    dma_max cap: file-contiguous neighbours within one member merge up to
    that many bytes, turning into vectored reads (:attr:`Request.dest_segs`)
    when their destinations are scattered by stripe interleave.  Without it
    the output honours the classic ``length <= dma_max_size`` invariant.
    A ``{member: limit}`` dict applies a per-member cap (the per-device
    adaptive sizers, PR 5); members missing from the dict don't coalesce.
    """
    cap = dma_max_size or config.get("dma_max_size")
    bs = max(source.block_size, 512)
    pieces: List[Request] = []
    for cid, slot in chunk_entries:
        base = cid * chunk_size
        length = min(chunk_size, source.size - base)
        if length <= 0:
            raise StromError(_errno.EINVAL, f"chunk {cid} beyond EOF")
        dest = dest_base + slot * chunk_size
        for e in source.extents(base, length):
            rel = e.logical_off - base
            aligned = (e.file_off % bs == 0 and e.length % bs == 0
                       and (dest + rel) % bs == 0)
            # split oversized extents at the request cap — every request the
            # engine issues is <= dma_max_size (kmod cap, nvme_strom.c:139-146)
            # — and at destination segment boundaries when requested
            off = 0
            while off < e.length:
                take = min(cap, e.length - off)
                if dest_segment_shift is not None:
                    seg_end = (((dest + rel + off) >> dest_segment_shift) + 1)                         << dest_segment_shift
                    take = min(take, seg_end - (dest + rel + off))
                pieces.append(Request(e.member, e.file_off + off, take,
                                      dest + rel + off, buffered=not aligned))
                off += take
    # merge pass
    out: List[Request] = []
    for r in pieces:
        if out:
            p = out[-1]
            if (p.member == r.member and not p.buffered and not r.buffered
                    and p.file_off + p.length == r.file_off
                    and p.dest_off + p.length == r.dest_off
                    and p.length + r.length <= cap
                    and (dest_segment_shift is None
                         or (p.dest_off >> dest_segment_shift)
                         == ((r.dest_off + r.length - 1) >> dest_segment_shift))):
                out[-1] = Request(p.member, p.file_off, p.length + r.length,
                                  p.dest_off)
                continue
        out.append(r)
    if coalesce_limit:
        if isinstance(coalesce_limit, dict):
            if any(v > cap for v in coalesce_limit.values()):
                out = _coalesce_requests(out, coalesce_limit,
                                         dest_segment_shift)
        elif coalesce_limit > cap:
            out = _coalesce_requests(out, coalesce_limit, dest_segment_shift)
    return out


def _coalesce_requests(reqs: List[Request], limit: Union[int, Dict[int, int]],
                       dest_segment_shift: Optional[int]) -> List[Request]:
    """Second merge pass (the reference's request-merge window applied
    beyond the per-command cap, kmod/nvme_strom.c:1473-1505): direct
    requests that are file-contiguous WITHIN one member merge up to
    *limit* bytes even when the stripe interleave scatters their
    destinations.  Dest-contiguous merges stay plain requests (a single
    big read the native engine executes unchanged — nstpu_req.len is
    64-bit); a destination gap turns the merge into a vectored read
    carried in :attr:`Request.dest_segs`.

    Requests read into disjoint destination ranges, so pulling a later
    request forward into an earlier one never reorders observable
    writes.  *limit* may be a ``{member: limit}`` dict — each member's
    run then merges under its own cap (per-member adaptive sizing)."""
    caps = limit if isinstance(limit, dict) else None
    out: List[Request] = []
    last: dict = {}  # member -> index in out of its last direct request
    for r in reqs:
        idx = last.get(r.member)
        if idx is not None and not r.buffered:
            lim = caps.get(r.member, 0) if caps is not None else limit
            p = out[idx]
            if (p.file_off + p.length == r.file_off
                    and p.length + r.length <= lim):
                segs = p.dest_segs or ((p.dest_off, p.length),)
                d, ln = segs[-1]
                if d + ln == r.dest_off and (
                        dest_segment_shift is None
                        or (d >> dest_segment_shift)
                        == ((r.dest_off + r.length - 1)
                            >> dest_segment_shift)):
                    segs = segs[:-1] + ((d, ln + r.length),)
                else:
                    segs = segs + ((r.dest_off, r.length),)
                out[idx] = Request(p.member, p.file_off,
                                   p.length + r.length, p.dest_off,
                                   dest_segs=segs if len(segs) > 1 else ())
                continue
        out.append(r)
        if r.buffered:
            # a buffered piece breaks the member's run: merging across it
            # would submit the direct span before the sync copy lands
            last.pop(r.member, None)
        else:
            last[r.member] = len(out) - 1
    return out


class AdaptiveChunkSizer:
    """Adaptive coalesced-request cap (the SSD-side analog of
    hbm.staging.AdaptiveH2DDepth): holds the effective merge cap at
    ``limit`` (optimistic start — large requests are what close the
    vs-raw-O_DIRECT gap), halves it toward ``floor`` whenever a request's
    observed service time blows the latency budget (an oversized request
    monopolizes its ring and starves the submission window), and doubles
    it back after ``decay_after`` consecutive in-budget completions."""

    #: per-request service-time budget; at NVMe-class bandwidth even a
    #: 64 MiB request completes well inside this, so shrink only fires
    #: when the device is genuinely slow at the current size
    LAT_BUDGET_NS = 100_000_000

    def __init__(self, floor: int, limit: int, decay_after: int = 4):
        self.floor = max(int(floor), 1)
        self.limit = max(int(limit), self.floor)
        self.decay_after = decay_after
        self._eff = self.limit
        self._streak = 0

    @property
    def effective(self) -> int:
        return self._eff

    def observe(self, service_ns: int) -> None:
        if service_ns > self.LAT_BUDGET_NS:
            self._streak = 0
            if self._eff > self.floor:
                self._eff = max(self._eff >> 1, self.floor)
        else:
            self._streak += 1
            if self._streak >= self.decay_after and self._eff < self.limit:
                self._eff = min(self._eff << 1, self.limit)
                self._streak = 0


def reorder_chunks(raw: "np.ndarray", chunk_size: int,
                   got_ids: Sequence[int],
                   want_ids: Sequence[int]) -> "np.ndarray":
    """Rearrange a chunk-strided buffer from the engine's completion order
    (direct-I/O chunks fronted, write-back chunks tailed — the reference's
    chunk_ids contract, kmod/nvme_strom.h:99-101) back to the caller's
    requested order.  Returns *raw* unchanged when the orders already
    match, else an owned copy."""
    import numpy as np
    got = list(got_ids)
    want = list(want_ids)
    if got == want:
        return raw
    pos = {cid: j for j, cid in enumerate(want)}
    blocks = raw.reshape(len(got), chunk_size)
    ordered = np.empty_like(blocks)
    ordered[[pos[c] for c in got]] = blocks
    return ordered.reshape(raw.shape)


def read_chunk_ids(sess: "Session", source: Source,
                   chunk_ids: Sequence[int], chunk_size: int,
                   buf_handle: int, buf_view: memoryview) -> "np.ndarray":
    """One synchronous read of *chunk_ids* through a mapped pinned
    buffer, returned in CALLER order — the submit/wait/reorder protocol
    shared by the point-lookup fetch and the checkpoint restore (one
    copy, so a fix to the read protocol lands everywhere)."""
    import numpy as np
    ids = [int(c) for c in chunk_ids]
    res = sess.memcpy_ssd2ram(source, buf_handle, ids, chunk_size)
    sess.memcpy_wait(res.dma_task_id)
    return reorder_chunks(
        np.frombuffer(buf_view[:len(ids) * chunk_size], np.uint8),
        chunk_size, res.chunk_ids, ids)


def plan_shard_ownership(source: Source, chunk_ids: Sequence[int],
                         chunk_size: int, n_hosts: int
                         ) -> Dict[int, List[int]]:
    """Partition a chunk list by host ownership for the multi-host
    sharded loader (ISSUE 17): host -> the chunks whose first extent
    lives on a member that host's local NVMe set holds, under the
    :func:`..stripe.host_of` member%n_hosts map.  Each host then submits
    ONLY its own list through its own engine session, so a striped
    deployment divides the file across per-host device queues the way
    the reference divides it across one host's md-RAID-0 members
    (`kmod/nvme_strom.c:823-910`).

    Single-member (plain/segmented-to-one-fd) sources have no placement
    to follow, so the split degrades to contiguous near-equal chunk
    ranges — still disjoint and exhaustive, which is all the gather
    step needs.  Every input chunk lands in exactly one host's list;
    hosts owning no member of a narrow stripe get empty lists.
    """
    from .stripe import host_of
    n_hosts = max(int(n_hosts), 1)
    ids = [int(c) for c in chunk_ids]
    owned: Dict[int, List[int]] = {h: [] for h in range(n_hosts)}
    n_members = len(source.member_fds())
    if n_members < 2 or n_hosts < 2:
        if n_hosts < 2:
            owned[0] = ids
            return owned
        # contiguous near-equal ranges: host h takes ids[h*q+...:...]
        q, r = divmod(len(ids), n_hosts)
        pos = 0
        for h in range(n_hosts):
            take = q + (1 if h < r else 0)
            owned[h] = ids[pos:pos + take]
            pos += take
        return owned
    for cid in ids:
        off = cid * chunk_size
        length = min(chunk_size, max(source.size - off, 0))
        if length <= 0:
            owned[host_of(0, n_hosts)].append(cid)
            continue
        member = source.extents(off, length)[0].member
        owned[host_of(member, n_hosts)].append(cid)
    return owned


# ---------------------------------------------------------------------------
# Async task table
# ---------------------------------------------------------------------------

_N_TASK_SLOTS = 512  # reference uses 512 hash slots (kmod/nvme_strom.c:639-644)


class DmaTask:
    __slots__ = ("task_id", "state", "errno_", "errmsg", "pending", "frozen",
                 "result", "t_submit", "buf_handle", "deadline", "expired",
                 "verify_src", "verify_dest", "verify_reqs", "trace_id",
                 "cache_fill", "cache_invalidate", "write_verify", "passthru")

    def __init__(self, task_id: int, deadline_s: float = 0.0):
        self.task_id = task_id
        self.state = DmaTaskState.RUNNING
        self.errno_ = 0
        self.errmsg = ""
        self.pending = 1       # creator's reference (dropped when frozen)
        self.frozen = False    # set after the submission loop; no new refs
        self.result: Optional[MemCopyResult] = None
        self.t_submit = time.monotonic_ns()
        self.buf_handle: Optional[int] = None
        # zero-copy checksum plan: native-executed direct requests whose
        # verification runs AT WAIT TIME on the retired slot (off the
        # submission critical path) instead of inline in a pool thread
        self.verify_src: Optional[Source] = None
        self.verify_dest: Optional[memoryview] = None
        self.verify_reqs: Optional[List[Request]] = None
        # watchdog deadline (monotonic seconds; 0 = none) — overdue tasks
        # are latched ETIMEDOUT so memcpy_wait can never hang (PR 1)
        self.deadline = (time.monotonic() + deadline_s) if deadline_s > 0 \
            else 0.0
        self.expired = False   # set by the watchdog; chunks check and bail
        self.trace_id = 0      # nonzero when the flight recorder sampled
        #                        this task (trace.recorder.task_begin)
        # residency-cache work deferred to wait time (ISSUE 9): miss
        # extents to install from the healed destination, and written
        # extents to re-invalidate once the write has retired
        self.cache_fill: Optional[tuple] = None
        self.cache_invalidate: Optional[tuple] = None
        # write_verify (ISSUE 11): (sink, reqs, src view) for the wait-time
        # read-back crc32c check on retired write tasks
        self.write_verify: Optional[tuple] = None
        # NVMe passthrough channel (PR 19): set when this task carries
        # blockmap-resolved requests; the pool's direct leg serves their
        # passthru_off through it, falling back down the fault ladder
        self.passthru = None


def _resolve_passthru_dev() -> Optional[str]:
    """NVMe char device for the passthrough rung: exact path from env
    NSTPU_PASSTHRU_DEV, else the first match of config passthru_dev_glob
    (absent on CI hosts — the ladder then refuses with reason 'nodev')."""
    dev = os.environ.get("NSTPU_PASSTHRU_DEV")
    if dev:
        return dev
    import glob as _glob
    matches = sorted(_glob.glob(str(config.get("passthru_dev_glob"))))
    return matches[0] if matches else None


def _member_path(source, member: int) -> Optional[str]:
    """Filesystem path of one stripe member, or None when the source has
    no path-bearing member (RAM fakes) — blockmap needs a real path."""
    members = getattr(source, "members", None)
    if members:
        if 0 <= member < len(members):
            p = getattr(members[member], "path", None)
            return str(p) if p else None
        return None
    m = getattr(source, "_m", None)
    p = getattr(m, "path", None) if m is not None and member == 0 else None
    return str(p) if p else None


class _NativePassthruChannel:
    """Channel marker for the REAL passthrough rung: requests carrying a
    blockmap-resolved ``passthru_off`` are flagged NSTPU_REQ_PASSTHRU on
    the native submit and become URING_CMD NVMe READs in the engine
    (csrc/strom_engine.cc); ``pool_ok=False`` because the Python pool has
    no char-device access — its fallback legs use plain O_DIRECT."""

    pool_ok = False
    native = True

    def __init__(self, lba_shift: int):
        self.lba_shift = lba_shift
        self.lba_size = 1 << lba_shift


def _passthru_left_lane(task, r) -> None:
    """A blockmap-resolved extent is being served OFF the passthrough
    lane (mirror/buffered recovery rung, or a hedge win): count the lane
    exit so the lane's effectiveness stays observable."""
    stats.add("nr_passthru_fallback")
    if _trace.active and task.trace_id:
        _trace.instant("passthru_fallback", tid=task.trace_id,
                       member=r.member, offset=r.file_off,
                       length=r.length, args={"reason": "ladder"})


class Session:
    """Engine session: buffer registry + task table + error-retention domain.

    Maps the reference's ioctl-fd lifecycle onto an object: failed DMA tasks
    are retained for reaping by a later wait and force-reaped when the
    session closes (reference ``strom_proc_release``, kmod/nvme_strom.c:
    2138-2166)."""

    def __init__(self, *, max_workers: Optional[int] = None,
                 io_backend: Optional[str] = None):
        self._buffers: Dict[int, Tuple[object, BufferInfo]] = {}
        # Condition, not bare Lock: unmap_buffer waits on it and _put_buffer
        # signals, mirroring the refcount+wakeup drain of the driver
        # revocation callback (kmod/pmemmap.c:149-208) with no sleep-poll
        self._buf_lock = threading.Condition(threading.Lock())
        self._next_handle = 1
        self._next_task = 1
        # zero-cooperation observability (round 5): any process opening
        # a Session becomes visible to `tpu_stat -l` / `-p PID` without
        # opting in, the way every workload shows in the reference's
        # /proc counters (utils/nvme_stat.c:168-175); STROM_STAT_EXPORT=0
        # gates it off
        stats.default_export_start()
        _live_sessions.add(self)
        stats.add_export_hook(_fold_live_native_stats)
        # flight recorder (PR 7): trace_policy is read here, once — event
        # sites then cost one `_trace.active` branch when tracing is off
        _trace.configure()
        # unified extent space (ISSUE 20): one configure for the whole
        # capacity hierarchy — tier_ram_bytes/tier_hbm_bytes are read
        # here and every tier transition is rewired; hit/miss sites then
        # cost one `_tiers.lookup_active` branch when all tiers are off
        _tiers.configure()
        # resident-data integrity domain (ISSUE 16): `integrity` is read
        # here; fill/verify sites cost one `_integrity.active` branch off
        _integrity.configure()
        self._slots: List[Dict[int, DmaTask]] = [dict() for _ in range(_N_TASK_SLOTS)]
        self._slot_cv = [threading.Condition() for _ in range(_N_TASK_SLOTS)]
        self._id_lock = threading.Lock()
        nworkers = max_workers or min(config.get("queue_depth"), 32)
        self._pool = ThreadPoolExecutor(max_workers=nworkers,
                                        thread_name_prefix="strom-io")
        self._closed = False
        self._abandon_native = False
        self._members_used: set = set()  # members seen by native submits
        # io_uring fixed-buffer registrations: id(backing) -> slot (-1 =
        # attempted, unsupported).  The PRP-pool analog: register once,
        # every request into the region skips per-request page pinning.
        self._fixed_regs: Dict[int, int] = {}
        self._fixed_lock = threading.Lock()
        # fault-tolerance layer (PR 1): retry policy, per-member health,
        # and the task watchdog
        self._retry = RetryPolicy.from_config()
        self._member_health = MemberHealthMachine()
        self._retry_rng = random.Random(os.getpid() ^ id(self))
        # mirror-coherent writes (ISSUE 11): extents a degraded member
        # missed, replayed mirror->rejoiner by the canary thread before
        # the health machine lets the member back to HEALTHY
        self._resync = DirtyExtentJournal()
        self._member_health.attach_resync(self._resync)
        # resilience tier (PR 6): striped sources seen by submits, probed
        # by the background canary thread while any member is FAILED or
        # REJOINING (weak: canaries must never keep a closed source alive)
        self._canary_sources: "_weakref.WeakSet" = _weakref.WeakSet()
        self._canary_buf = None
        self._canary_stop = threading.Event()
        self._canary = threading.Thread(target=self._canary_loop,
                                        daemon=True,
                                        name="strom-canary")
        self._canary.start()
        # background scrubber (ISSUE 16): walks resident extents of all
        # tiers verifying stored crc32c, rate-limited by
        # scrub_bytes_per_sec (re-read each tick, canary-style); idles on
        # one Event wait per tick while disabled
        self._scrubber = _Scrubber(self)
        # self-driving data path (ISSUE 18): the per-session controller.
        # `autotune`/`readahead` are read at its construction (configure()
        # convention); hot paths test `self._tuner.enabled`/`.ra_active`
        # — one predicted branch each when off.  It also hosts the PR 4/5
        # adaptive chunk sizers as its chunk-cap policy, so there is
        # exactly one writer of the effective cap; the alias below keeps
        # the sizer dict reachable under its historical name (tests,
        # _fold_native_stats).  The thread starts at the end of __init__,
        # once the engine/backend choice is final.
        self._tuner = AutoTuner(self)
        # adaptive chunk sizing (PR 4, per-member since PR 5): one sizer
        # per stripe member so the effective request cap converges per
        # DEVICE — a slow member shrinks its own merges without throttling
        # healthy siblings.  Created lazily on the first adaptive memcpy;
        # single-file sources live under member 0.
        self._chunk_sizers: Dict[int, AdaptiveChunkSizer] = \
            self._tuner.chunk_sizers
        # lane scale-out (PR 5): the engine starts single-lane and is
        # rebuilt with one queue pair per stripe member at the first
        # striped submit (one-shot); swapped-out engines stay alive until
        # close() so in-flight waits complete against the engine that
        # accepted them
        self._lane_lock = threading.Lock()
        self._lanes_sized = False
        self._old_engines: List[object] = []
        # per-member executor lanes for the Python fallback path
        self._member_pools: Dict[int, ThreadPoolExecutor] = {}
        self._watchdog_stop = threading.Event()
        self._watchdog = threading.Thread(target=self._watchdog_loop,
                                          daemon=True,
                                          name="strom-task-watchdog")
        self._watchdog.start()
        # native engine: the GIL-free executor for planned request batches
        self._native = None
        self._passthru_dev: Optional[str] = None
        self._pt_channel: Optional[_NativePassthruChannel] = None
        want = io_backend or config.get("io_backend")
        fallback_ok = bool(config.get("io_fallback"))
        if want != "python":
            from . import _native as _nat
            if _nat.native_available():
                # NSTPU_RINGS env keeps working as the experiment
                # override; the config var is the durable setting.
                # Malformed values fall back (the C side's atol was
                # just as tolerant) — a typo must not kill Session().
                try:
                    rings = int(os.environ.get("NSTPU_RINGS", ""))
                except ValueError:
                    rings = int(config.get("engine_rings"))
                # engine_backend (PR 19) picks the rung when the legacy
                # io_backend var left the choice to the ladder; an explicit
                # io_backend=io_uring/threadpool keeps its pre-v4 meaning
                # (no passthru probe at all — bit-for-bit the old path)
                eng_backend = config.get("engine_backend")
                if want in ("io_uring", "threadpool"):
                    native_want = want
                else:
                    native_want = {"auto": "auto",
                                   "passthru": "nvme_passthru",
                                   "uring": "io_uring",
                                   "threadpool": "threadpool"}[eng_backend]
                if native_want in ("auto", "nvme_passthru"):
                    self._passthru_dev = _resolve_passthru_dev()
                try:
                    self._native = _nat.NativeEngine(
                        native_want, config.get("queue_depth"), rings=rings,
                        passthru_dev=self._passthru_dev)
                except (StromError, KeyError) as e:
                    # degrade one tier at a time: a refused passthru rung
                    # falls back to the AUTO ladder (refusal counted), an
                    # io_uring setup failure falls back to the native
                    # threadpool, a dead native engine falls back to the
                    # Python pool (io_fallback gates all; explicit
                    # non-auto without fallback keeps fail-fast)
                    if native_want == "nvme_passthru" and fallback_ok:
                        stats.add("nr_passthru_fallback")
                        if _trace.active:
                            _trace.instant("passthru_fallback",
                                           args={"reason": "create_failed"})
                        pr_warn("nvme passthru backend refused (%s); "
                                "falling back down the ladder", e)
                        try:
                            self._native = _nat.NativeEngine(
                                "auto", config.get("queue_depth"),
                                rings=rings,
                                passthru_dev=self._passthru_dev)
                        except StromError:
                            pass
                    elif want == "io_uring" and fallback_ok:
                        stats.add("nr_backend_fallback")
                        pr_warn("io_uring setup failed (%s); falling back "
                                "to threadpool backend", e)
                        try:
                            self._native = _nat.NativeEngine(
                                "threadpool", config.get("queue_depth"),
                                rings=rings)
                        except StromError:
                            pass
                    if self._native is None and want != "auto" \
                            and not fallback_ok:
                        raise
                if self._native is not None:
                    self._count_passthru_reason(_nat, native_want)
            elif want != "auto":
                if not fallback_ok:
                    raise StromError(
                        _errno.ENOSYS,
                        f"io_backend={want} requires the native engine")
                stats.add("nr_backend_fallback")
                pr_warn("io_backend=%s unavailable (no native engine); "
                        "falling back to python path", want)
        self.backend_name = (self._native.backend_name if self._native
                             else "python")
        stats.set_backend(self.backend_name)
        if _trace.active and self._native is not None:
            # per-lane native event ring: device submit->complete windows
            # are MEASURED by the engine and drained into the recorder
            self._native.trace_enable(True)
        self._tuner.start()
        pr_info("session open: backend=%s workers=%d",
                self.backend_name, nworkers)

    # -- NVMe passthrough lane (PR 19) -------------------------------------
    def _count_passthru_reason(self, nat, native_want: str) -> None:
        """Resolve how the engine ladder's passthrough rung landed.  A
        live rung gets the native channel (requests are then flagged
        through URING_CMD lanes); a refusal on a ladder that INCLUDED the
        rung is counted per reason.  Ladders that never had the rung
        (explicit io_uring/threadpool) count NOTHING — the
        zero-passthru-counters guarantee of engine_backend=uring|threadpool."""
        if native_want not in ("auto", "nvme_passthru"):
            return
        reason = self._native.passthru_reason()
        if reason is None:       # pre-v4 library: the rung does not exist
            return
        if reason == 0:
            # second probe for the LBA geometry the split math needs; the
            # engine already validated the format, so a failure here only
            # means "no split", never wrong SLBA math
            shift = None
            if self._passthru_dev:
                probed = nat.passthru_probe(self._passthru_dev)
                if isinstance(probed, int) and probed >= 9:
                    shift = probed
            if shift is not None:
                self._pt_channel = _NativePassthruChannel(shift)
            return
        name = nat.PASSTHRU_REASONS.get(reason, "nodev")
        stats.add("nr_passthru_refusal_" + name)
        if _trace.active:
            _trace.instant("passthru_fallback", args={"reason": name})

    def _passthru_channel(self, source):
        """The passthrough channel a task on ``source`` splits through:
        None when engine_backend pins a lower rung (zero-counters
        guarantee: off = bit-for-bit today's path), else the source's own
        channel (the CI emulator attaches one), else the native channel
        when the engine came up on the passthrough rung."""
        if config.get("engine_backend") in ("uring", "threadpool"):
            return None
        chan = getattr(source, "passthru_channel", None)
        if chan is not None:
            return chan
        return self._pt_channel

    def _passthru_split(self, task: DmaTask, source: Source,
                        reqs: List[Request], chan,
                        mirror_remap: Dict[int, int]) -> List[Request]:
        """Split planned requests onto the passthrough lane (the PR 9
        hit/miss split, per extent): each plain direct request whose span
        blockmap-resolves to LBA-aligned device ranges becomes one
        sub-request per physical extent carrying ``passthru_off``;
        everything else — buffered tails, vectored stripe merges,
        mirror-remapped members, unresolvable/ineligible spans — rides
        the O_DIRECT lanes of the SAME task untouched."""
        out: List[Request] = []
        lba = chan.lba_size
        for r in reqs:
            if r.buffered or r.dest_segs or r.passthru_off is not None \
                    or r.member in mirror_remap:
                out.append(r)
                continue
            path = _member_path(source, r.member)
            runs = blockmap.resolve_split(path, r.file_off, r.length, lba) \
                if path is not None else [(r.file_off, r.length, None)]
            if all(dev is None for (_f, _l, dev) in runs):
                stats.add("nr_passthru_refused_extent")
                if _trace.active and task.trace_id:
                    _trace.instant("passthru_refuse", tid=task.trace_id,
                                   member=r.member, offset=r.file_off,
                                   length=r.length)
                out.append(r)
                continue
            for foff, ln, dev_off in runs:
                doff = r.dest_off + (foff - r.file_off)
                if dev_off is None:
                    stats.add("nr_passthru_refused_extent")
                    if _trace.active and task.trace_id:
                        _trace.instant("passthru_refuse",
                                       tid=task.trace_id, member=r.member,
                                       offset=foff, length=ln)
                else:
                    stats.add("bytes_passthru", ln)
                out.append(Request(member=r.member, file_off=foff,
                                   length=ln, dest_off=doff,
                                   passthru_off=dev_off))
        return out

    # -- buffer registry (MAP/UNMAP/LIST/INFO analogs) ---------------------
    def alloc_dma_buffer(self, length: int, *, numa_node: int = -1) -> Tuple[int, DmaBuffer]:
        """ALLOC_DMA_BUFFER — declared but unimplemented in the reference
        (kmod/nvme_strom.c:2199-2201 returns -ENOTSUPP); implemented here."""
        buf = DmaBuffer(length, numa_node=numa_node)
        handle = self.map_buffer(buf.view(), kind="pinned_host", backing=buf)
        return handle, buf

    def map_buffer(self, view: memoryview, *, kind: str = "user",
                   backing: object = None, device: Optional[str] = None) -> int:
        view = view.cast("B")
        if (kind == "pinned_host" and self._native is not None
                and isinstance(backing, DmaBuffer)):
            self._register_fixed(backing)
        with self._buf_lock:
            handle = self._next_handle
            self._next_handle += 1
            info = BufferInfo(handle=handle, length=len(view), page_size=PAGE_SIZE,
                              n_pages=(len(view) + PAGE_SIZE - 1) // PAGE_SIZE,
                              owner_uid=os.getuid(), refcount=0, kind=kind,
                              device=device)
            self._buffers[handle] = ((view, backing), info)
        return handle

    def _register_fixed(self, backing: "DmaBuffer") -> None:
        """Register *backing* as an io_uring fixed buffer, once per buffer
        per session; the registration is released by the buffer's own
        close (so it can never outlive the mapping and alias a reuse of
        the address range).  Failed attempts are cached as slot -1 but
        still evicted on buffer close — ``id()`` recycles after GC, and a
        sticky sentinel would silently deny a NEW buffer the fast path."""
        key = id(backing)
        with self._fixed_lock:
            if key in self._fixed_regs:
                return
            slot = self._native.buf_register(backing.addr, backing.length)
            cb = lambda: self._unregister_fixed(key)  # noqa: E731
            self._fixed_regs[key] = (-1 if slot is None else slot,
                                     backing, cb)
        if not backing.on_close(cb):
            # buffer closed between register and hook-up: release now
            self._unregister_fixed(key)

    def _unregister_fixed(self, key: int) -> None:
        with self._fixed_lock:
            entry = self._fixed_regs.pop(key, None)
        if entry and entry[0] >= 0 and self._native is not None:
            try:
                self._native.buf_unregister(entry[0])
            except Exception:   # engine already closed: kernel freed it
                pass

    def _get_buffer(self, handle: int, need: int = 0) -> memoryview:
        with self._buf_lock:
            try:
                (view, _backing), info = self._buffers[handle]
            except KeyError:
                raise StromError(_errno.ENOENT, f"no mapped buffer {handle}") from None
            # UID ownership check (reference kmod/pmemmap.c:104-105,375-376)
            if info.owner_uid != os.getuid():
                raise StromError(_errno.EPERM, "buffer owned by another uid")
            if need > info.length:
                raise StromError(_errno.ERANGE,
                                f"buffer {handle} too small: {need} > {info.length}")
            self._buffers[handle] = ((view, _backing),
                                     BufferInfo(**{**info.__dict__,
                                                   "refcount": info.refcount + 1}))
            return view

    def _put_buffer(self, handle: int) -> None:
        with self._buf_lock:
            if handle in self._buffers:
                (vb, info) = self._buffers[handle]
                info = BufferInfo(**{**info.__dict__,
                                     "refcount": info.refcount - 1})
                self._buffers[handle] = (vb, info)
                if info.refcount == 0:
                    self._buf_lock.notify_all()

    def unmap_buffer(self, handle: int, *, wait: bool = True,
                     timeout: float = 30.0) -> None:
        """Blocks until in-flight DMA drains, like the driver revocation
        callback (kmod/pmemmap.c:149-208)."""
        deadline = time.monotonic() + timeout
        with self._buf_lock:
            while True:
                if handle not in self._buffers:
                    raise StromError(_errno.ENOENT, f"no mapped buffer {handle}")
                _, info = self._buffers[handle]
                if info.refcount == 0:
                    del self._buffers[handle]
                    return
                if not wait:
                    raise StromError(_errno.EBUSY, f"buffer {handle} has in-flight DMA")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise StromError(_errno.ETIMEDOUT, f"buffer {handle} busy")
                self._buf_lock.wait(remaining)

    def list_buffers(self) -> List[int]:
        with self._buf_lock:
            return sorted(self._buffers)

    def info_buffer(self, handle: int) -> BufferInfo:
        with self._buf_lock:
            try:
                return self._buffers[handle][1]
            except KeyError:
                raise StromError(_errno.ENOENT, f"no mapped buffer {handle}") from None

    # -- task table --------------------------------------------------------
    def _slot_of(self, task_id: int) -> int:
        return task_id % _N_TASK_SLOTS

    def _create_task(self) -> DmaTask:
        with self._id_lock:
            tid = self._next_task
            self._next_task += 1
        task = DmaTask(tid, deadline_s=float(config.get("task_deadline_s")))
        if _trace.active:
            task.trace_id = _trace.task_begin(tid)
        s = self._slot_of(tid)
        with self._slot_cv[s]:
            self._slots[s][tid] = task
        return task

    def _watchdog_loop(self) -> None:
        """Latch ETIMEDOUT on tasks RUNNING past their deadline (PR 1).

        The reference can only hang forever when DMA never completes
        (its wait is interruptible but the task stays RUNNING); here the
        watchdog force-fails overdue tasks — waiters wake immediately,
        not-yet-started chunks see the latched error and cancel, and
        in-flight native waits abandon (``_await_native``)."""
        while not self._watchdog_stop.wait(0.05):
            now = time.monotonic()
            expired: List[str] = []
            for s, cv in enumerate(self._slot_cv):
                with cv:
                    for task in self._slots[s].values():
                        if (task.state is not DmaTaskState.RUNNING
                                or not task.deadline
                                or now <= task.deadline):
                            continue
                        task.expired = True
                        if task.errno_ == 0:
                            task.errno_ = _errno.ETIMEDOUT
                            task.errmsg = (
                                f"dma task {task.task_id} exceeded its "
                                f"{config.get('task_deadline_s')}s deadline "
                                f"({task.pending} chunks outstanding)")
                            stats.add("nr_task_timeout")
                            if _trace.active and task.trace_id:
                                _trace.instant(
                                    "task_timeout", tid=task.trace_id,
                                    args={"pending": task.pending})
                        # latch FAILED now (pending chunks drain later and
                        # cannot flip it back: errno_ is already set)
                        task.state = DmaTaskState.FAILED
                        cv.notify_all()
                        expired.append(task.errmsg)
            for msg in expired:   # outside the locks: slow stderr must
                pr_warn("watchdog: %s", msg)   # not stall completions

    def _canary_loop(self) -> None:
        """Background canary prober (PR 6): every ``canary_interval_s``,
        members the health machine flags (FAILED: detect recovery;
        REJOINING: advance warmup without client traffic) get one small
        direct read against each registered striped source.  A FAILED
        member that answers moves to REJOINING; warmup successes ramp a
        REJOINING member back to HEALTHY through the token bucket instead
        of a recovery cliff."""
        while True:
            interval = float(config.get("canary_interval_s"))
            if self._canary_stop.wait(interval if interval > 0 else 0.5):
                return
            if interval <= 0:
                continue
            cands = self._member_health.canary_candidates()
            if not cands:
                continue
            # dirty-extent resync first (ISSUE 11): drain what a
            # REJOINING member owes before the probes below advance its
            # warmup — the machine refuses HEALTHY while bytes are owed,
            # so ordering is a latency nicety, not a correctness hinge
            self._resync_replay(cands)
            for src in list(self._canary_sources):
                nmem = len(getattr(src, "members", ()))
                for m in cands:
                    if m >= nmem or self._canary_stop.is_set():
                        continue
                    self._canary_probe(src, m)

    def _canary_probe(self, source: Source, member: int) -> None:
        """One canary: a small direct read at member offset 0 (O_DIRECT
        needs an aligned buffer, so the scratch page is mmap-backed)."""
        try:
            size = getattr(source.members[member], "size", 0)
            blk = max(int(getattr(source, "block_size", 512)), 512)
            length = min(PAGE_SIZE, size // blk * blk)
            if length <= 0:
                return
            if self._canary_buf is None:
                self._canary_buf = mmap.mmap(-1, PAGE_SIZE)
            source.read_member_direct(
                member, 0, memoryview(self._canary_buf)[:length])
        except (StromError, OSError) as e:
            if getattr(e, "errno", None) == _errno.EBADF:
                return   # source closed under the prober: not a verdict
            self._member_health.record_canary(member, False)
        except Exception:
            return       # a broken probe must never kill the thread
        else:
            self._member_health.record_canary(member, True)

    def _scrub_refill(self, source: Optional[Source], base: int,
                      length: int) -> Optional[bytes]:
        """Scrub heal (ISSUE 16): re-read one resident extent's bytes
        from SSD through the normal submit path — the full fault ladder
        (retry/hedge/mirror/checksum re-read) heals them, and the
        wait-time cache_fill hook reinstalls the extent under the same
        key (the corrupt entry was already dropped, so the read is a
        clean miss).  Returns the healed bytes, or None when the source
        is gone or the extent no longer maps onto its chunk grid."""
        if source is None or getattr(source, "closed", False):
            return None
        size = getattr(source, "size", 0)
        # recover the chunk grid from (base, length): a full chunk is its
        # own pow2 grid; a tail chunk's grid is the smallest pow2 that
        # both covers it and divides base
        cs = length
        if cs & (cs - 1):
            cs = 1 << (length - 1).bit_length()
        while cs < size and base % cs:
            cs <<= 1
        if cs <= 0 or base % cs or min(cs, size - base) != length:
            return None
        handle = None
        try:
            handle, buf = self.alloc_dma_buffer(max(length, PAGE_SIZE))
            res = self.memcpy_ssd2ram(source, handle, [base // cs], cs)
            self.memcpy_wait(res.dma_task_id)
            return bytes(buf.view()[:length])
        except (StromError, OSError):
            return None
        finally:
            if handle is not None:
                try:
                    self.unmap_buffer(handle)
                except StromError:  # pragma: no cover - closing session
                    pass

    def _journal_skipped(self, sink: Source, member: int, file_off: int,
                         length: int, trace_id: int = 0) -> None:
        """Record an extent a degraded member missed (the write landed
        only on its mirror partner) in the resync journal."""
        self._resync.record(sink, member, file_off, length)
        if _trace.active:
            _trace.instant("resync_skip", tid=trace_id,
                           member=member, offset=file_off, length=length)

    def _resync_replay(self, members: Sequence[int]) -> None:
        """Replay journaled dirty extents onto REJOINING members:
        read-from-mirror -> write-to-rejoiner, throttled by the member's
        rejoin token bucket (the resync budget).  Runs on the canary
        thread; a replay failure re-journals the extent and debits the
        failing member, so debt never silently evaporates."""
        health = self._member_health
        jrn = self._resync
        for member in members:
            if member not in jrn.members():
                continue
            if health.state(member) is not HealthState.REJOINING:
                continue
            for ref in jrn.sink_refs(member):
                sink = ref()
                if sink is None:
                    continue
                mirror = sink.mirror_of(member)
                if mirror is None:    # mirror map changed under the debt:
                    jrn.drop_sink(ref)  # nothing to replay from
                    continue
                while not self._canary_stop.is_set():
                    if not health.take_rejoin_token(member):
                        break          # budget spent; next canary tick
                    ext = jrn.take_extent(ref, member)
                    if ext is None:
                        break
                    off, length = ext
                    if not self._replay_extent(sink, mirror, member,
                                               off, length):
                        break

    def _replay_extent(self, sink: Source, mirror: int, member: int,
                       file_off: int, length: int) -> bool:
        """One resync extent: mirror's bytes -> rejoiner.  Aligned spans
        ride the direct legs; misaligned (buffered-leg) debt rides the
        buffered legs.  Returns False when replay must pause."""
        t0 = time.monotonic_ns()
        # per-extent anonymous scratch (page-aligned, so the direct legs
        # accept it); its cost is noise next to the replayed I/O, and a
        # local avoids sharing a cached buffer across threads
        sz = max(length, PAGE_SIZE)
        sz = (sz + PAGE_SIZE - 1) // PAGE_SIZE * PAGE_SIZE
        scratch = mmap.mmap(-1, sz)
        mv = memoryview(scratch)[:length]
        try:
            return self._replay_extent_into(
                sink, mirror, member, file_off, length, mv, t0)
        finally:
            mv.release()
            scratch.close()

    def _replay_extent_into(self, sink: Source, mirror: int, member: int,
                            file_off: int, length: int, buf: memoryview,
                            t0: int) -> bool:
        bs = max(int(getattr(sink, "block_size", 512)), 512)
        aligned = file_off % bs == 0 and length % bs == 0
        try:
            if aligned:
                sink.read_member_direct(mirror, file_off, buf)
            else:
                sink.read_member_buffered(mirror, file_off, buf)
        except (StromError, OSError) as e:
            if getattr(e, "errno", None) == _errno.EBADF:
                return False   # sink closed under the replay
            se = e if isinstance(e, StromError) else \
                StromError(e.errno or _errno.EIO, str(e))
            self._member_health.record_failure(
                mirror, fatal=se.error_class is ErrorClass.PERSISTENT)
            stats.member_error(mirror)
            self._resync.put_back(sink, member, file_off, length)
            return False
        except Exception:
            self._resync.put_back(sink, member, file_off, length)
            return False
        try:
            if aligned:
                sink.write_member_direct(member, file_off, buf)
            else:
                sink.write_member_buffered(member, file_off, buf)
        except (StromError, OSError) as e:
            if getattr(e, "errno", None) == _errno.EBADF:
                return False
            se = e if isinstance(e, StromError) else \
                StromError(e.errno or _errno.EIO, str(e))
            self._member_health.record_failure(
                member, fatal=se.error_class is ErrorClass.PERSISTENT)
            stats.member_error(member)
            self._resync.put_back(sink, member, file_off, length)
            return False
        except Exception:
            self._resync.put_back(sink, member, file_off, length)
            return False
        stats.add("nr_resync_extent")
        stats.member_add(member, length, time.monotonic_ns() - t0)
        if _trace.active:
            _trace.span("resync", t0, time.monotonic_ns(), member=member,
                        offset=file_off, length=length,
                        args={"mirror": mirror})
        return True

    def _task_get(self, task: DmaTask) -> None:
        s = self._slot_of(task.task_id)
        with self._slot_cv[s]:
            assert not task.frozen, "get on frozen dtask (use-after-submit)"
            task.pending += 1

    def _task_put(self, task: DmaTask, err: Optional[StromError] = None) -> None:
        s = self._slot_of(task.task_id)
        latched = None
        with self._slot_cv[s]:
            if err is not None and task.errno_ == 0:
                # first error wins (reference strom_put_dma_task, :770-776)
                task.errno_ = err.errno
                task.errmsg = str(err)
                latched = err
            task.pending -= 1
            done = task.pending == 0
            if done:
                task.state = (DmaTaskState.FAILED if task.errno_
                              else DmaTaskState.DONE)
                stats.count_clock("ssd2dev", time.monotonic_ns() - task.t_submit)
                self._slot_cv[s].notify_all()
        if latched is not None:
            if _trace.active and task.trace_id:
                _trace.instant("task_failed", tid=task.trace_id,
                               args={"errno": latched.errno,
                                     "error": str(latched)[:160]})
            # outside the lock: a slow stderr must not stall completions
            pr_warn("dma task %d latched error: %s", task.task_id, latched)
        if done and task.buf_handle is not None:
            self._put_buffer(task.buf_handle)

    def memcpy_wait(self, task_id: int, timeout: Optional[float] = None) -> MemCopyResult:
        """MEMCPY_WAIT: block until the task completes; reap it.

        Raises :class:`StromError` with the latched first error for failed
        tasks (which are *retained* until this reap or session close).  The
        waiter loop mirrors the reference's spurious-wakeup handling
        (``strom_dma_task_wait``, kmod/nvme_strom.c:1230-1316), counting
        wrong wakeups."""
        t0 = time.monotonic_ns()
        s = self._slot_of(task_id)
        cv = self._slot_cv[s]
        deadline = None if timeout is None else time.monotonic() + timeout
        with cv:
            while True:
                task = self._slots[s].get(task_id)
                if task is None:
                    raise StromError(_errno.ENOENT, f"unknown dma task {task_id}")
                if task.state in (DmaTaskState.DONE, DmaTaskState.FAILED):
                    del self._slots[s][task_id]  # reap
                    break
                remain = None if deadline is None else deadline - time.monotonic()
                if remain is not None and remain <= 0:
                    raise StromError(_errno.ETIMEDOUT, f"dma task {task_id} timeout")
                if not cv.wait(remain):
                    raise StromError(_errno.ETIMEDOUT, f"dma task {task_id} timeout")
                if task.state == DmaTaskState.RUNNING:
                    stats.add("nr_wrong_wakeup")
        stats.count_clock("ioctl_memcpy_wait", time.monotonic_ns() - t0)
        if _trace.active and task.trace_id:
            _trace.span("wait", t0, time.monotonic_ns(), tid=task.trace_id,
                        args=({"errno": task.errno_} if task.errno_ else None))
        if task.errno_:
            if _trace.active:
                # the flight-recorder moment: dump what the engine did in
                # the window before this task latched (bounded per process)
                _trace.dump_on_failure(
                    f"task {task_id} errno {task.errno_}")
            raise StromError(task.errno_, task.errmsg or "async DMA failed")
        if task.verify_reqs:
            # zero-copy landing: the native engine read straight into the
            # caller's (staging) buffer, so checksum verification runs
            # HERE on the retired slot — off the submission critical path
            # — with the same re-read-then-latch-EBADMSG ladder the pool
            # path applies inline (mismatches heal via read_member_direct,
            # so fault injection on that leg still exercises the ladder)
            for r in task.verify_reqs:
                self._verify_request_checksums(task.verify_src, r,
                                               task.verify_dest)
        if task.cache_fill is not None:
            # demand-fault fills run HERE, on the retired task: the
            # destination bytes have been healed by the full fault
            # ladder (retry/hedge/mirror/checksum re-read), so a
            # degraded member still populates the hierarchy via its
            # surviving legs — and a latched failure never fills
            skey, fills, fdest, lscale, src_ref, spec = task.cache_fill
            task.cache_fill = None
            for base, length, doff in fills:
                tf0 = time.monotonic_ns()
                if _tiers.fault_fill(skey, base, length,
                                     fdest[doff:doff + length],
                                     logical_length=int(length * lscale),
                                     source_ref=src_ref, speculative=spec) \
                        and _trace.active and task.trace_id:
                    _trace.span("cache_fill", tf0, time.monotonic_ns(),
                                tid=task.trace_id, offset=base,
                                length=length)
        if task.cache_invalidate is not None:
            # re-run the write path's invalidation after the write has
            # retired: a racing read may have re-filled a written extent
            # from pre-write bytes between submit and completion
            skey, extents = task.cache_invalidate
            task.cache_invalidate = None
            _tiers.invalidate_extents(skey, extents)
        if task.write_verify is not None:
            # write_verify (ISSUE 11): read each retired write leg back
            # and compare crc32c against the submitted bytes — a torn or
            # misdirected write surfaces HERE, at the durability boundary,
            # instead of on some future read.  Runs on the reaped slot,
            # off the submission critical path, like verify_reqs above.
            wsink, wreqs, wsrc = task.write_verify
            task.write_verify = None
            self._verify_writes(wsink, wreqs, wsrc, task)
        assert task.result is not None
        return task.result

    def pending_tasks(self) -> List[int]:
        out: List[int] = []
        for s, cv in enumerate(self._slot_cv):
            with cv:
                out.extend(self._slots[s])
        return sorted(out)

    # -- memcpy commands ---------------------------------------------------
    def memcpy_ssd2ram(self, source: Source, buf_handle: int,
                       chunk_ids: Sequence[int], chunk_size: int, *,
                       dest_offset: int = 0,
                       wb_buffer: Optional[memoryview] = None,
                       speculative: bool = False) -> MemCopyResult:
        """MEMCPY_SSD2RAM/SSD2GPU submit path.

        Plans + submits asynchronously, returning a :class:`MemCopyResult`
        whose ``chunk_ids`` is the reordered array (direct-I/O chunks first,
        page-cache write-back chunks at the tail — reference contract
        kmod/nvme_strom.h:99-101).  When *wb_buffer* is given, write-back
        chunks are copied there (tail-packed) instead of the destination,
        exactly the SSD2GPU contract where the caller performs the
        RAM->device copy itself (kmod/nvme_strom.c:1647-1663); otherwise they
        are copied straight into the destination (SSD2RAM behaviour,
        :1926-1934).

        ``speculative`` marks a readahead prefetch (ISSUE 18): the task
        skips the residency-tier hit split (a prefetch of resident data
        has nothing to do), does not train the readahead predictor, and
        its wait-time cache fills carry provenance so ARC's ghost lists
        stay blind to speculation."""
        t0 = time.monotonic_ns()
        if self._closed:
            raise StromError(_errno.EBADF, "session closed")
        if chunk_size <= 0 or (chunk_size & (chunk_size - 1)):
            raise StromError(_errno.EINVAL, f"chunk_size {chunk_size} must be pow2")
        chunk_ids = list(chunk_ids)
        n = len(chunk_ids)
        if n == 0:
            raise StromError(_errno.EINVAL, "no chunks")
        # exact-size destinations (zero-copy landing, tail slots): a
        # single-chunk task only needs the chunk's TRUE length, which may
        # be a partial tail shorter than chunk_size
        need = dest_offset + n * chunk_size
        if n == 1:
            tail = min(chunk_size, source.size - chunk_ids[0] * chunk_size)
            if tail > 0:
                need = dest_offset + tail
        dest = self._get_buffer(buf_handle, need=need)
        task = self._create_task()
        if _trace.active and task.trace_id:
            _trace.instant("submit", tid=task.trace_id, ts_ns=t0,
                           length=n * chunk_size,
                           args={"task": task.task_id, "chunks": n})
        cache_hits: List[tuple] = []  # (cid, base, length, lease)
        try:
            spans_all: List[Tuple[int, int]] = []
            for cid in chunk_ids:
                base = cid * chunk_size
                length = min(chunk_size, source.size - base)
                if length <= 0:
                    raise StromError(_errno.EINVAL, f"chunk {cid} beyond EOF")
                spans_all.append((base, length))
            if self._tuner.ra_active and not speculative:
                # readahead training tap (ISSUE 18): every demand span
                # feeds the per-source predictor — including spans the
                # hit split below serves entirely from cache, so a
                # cache-warm stream keeps its pattern model current
                self._tuner.observe_submit(source, chunk_size, chunk_ids)
            # --- residency-tier split (ISSUE 9) ---------------------------
            # hits take a pinned lease and are served by memcpy below —
            # no submission, no mincore probe; only the misses go on to
            # page-cache arbitration and the member lanes
            skey = None
            miss_ids, spans = chunk_ids, spans_all
            if _tiers.lookup_active and not speculative:
                skey = _tiers.source_key(source)
                miss_ids, spans = [], []
                nr_hbm = 0
                for cid, (base, length) in zip(chunk_ids, spans_all):
                    # ONE top-down lookup over the unified space
                    # (ISSUE 20): the HBM tier outranks RAM — a device-
                    # resident extent costs one device→dest copy and
                    # never touches a host slab
                    hit = _tiers.lookup(skey, base, length)
                    if hit is not None:
                        lease, tname = hit
                        hbm = tname == "hbm"
                        if hbm:
                            nr_hbm += 1
                        cache_hits.append((cid, base, length, lease, hbm))
                    else:
                        miss_ids.append(cid)
                        spans.append((base, length))
                if nr_hbm:
                    stats.add("nr_hbm_hit", nr_hbm)
                if len(cache_hits) > nr_hbm:
                    stats.add("nr_cache_hit", len(cache_hits) - nr_hbm)
                if cache_hits:
                    stats.add("bytes_cache_hit",
                              sum(h[2] for h in cache_hits))
                if miss_ids:
                    stats.add("nr_cache_miss", len(miss_ids))
                if not _tiers.fill_active:
                    skey = None  # no RAM tier: nothing to fill at wait
            elif _tiers.fill_active:
                # speculative prefetch (ISSUE 18): no hit split — the
                # issue loop already peeked residency — but the misses
                # must still demand-fault into the RAM tier at wait time
                skey = _tiers.source_key(source)

            # --- cache arbitration (write-back vs direct) -----------------
            threshold = config.get("cache_threshold")
            arbitrate = config.get("cache_arbitration")
            direct_ids: List[int] = []
            wb_ids: List[int] = []
            if arbitrate and miss_ids:
                # one batched residency probe for the whole task (real file
                # sources fold it into a single mincore scan); hot/dirty
                # data is decisive, not weighted: the reference scores one
                # dirty page at threshold+1 (:1643), because a direct read
                # of a dirty range either stalls on a forced flush or reads
                # stale blocks
                for cid, (cached, hot) in zip(miss_ids,
                                              source.residency(spans)):
                    if hot > 0.0 or cached > threshold:
                        wb_ids.append(cid)
                    else:
                        direct_ids.append(cid)
            else:
                direct_ids = list(miss_ids)
            # hits tail-pack after the write-back slots so the result's
            # RAM-sourced region stays one contiguous tail
            # (MemCopyResult contract: ssd chunks first)
            new_order = direct_ids + wb_ids + [h[0] for h in cache_hits]
            nr_ssd = len(direct_ids)

            # --- plan + submit direct requests (sliding window) -----------
            # the chunk list is planned and submitted in slices of
            # submit_window chunks: the first slice's I/O is in flight
            # while later slices are still being planned, so queue
            # occupancy never drains at a chunk-plan boundary (the
            # reference keeps every device queue full the same way,
            # kmod/nvme_strom.c:1136-1224)
            # the native engine executes batches GIL-free when the source
            # reads through plain fds (test fakes that override the read
            # leg take the Python path so injection still works); with
            # checksum_verify on, verification moves to wait time on the
            # retired zero-copy slot instead of disabling the native path
            use_native = (self._native is not None and direct_ids
                          and type(source).read_member_direct
                          is Source.read_member_direct)
            if use_native:
                self._ensure_member_lanes(source)
            if len(getattr(source, "members", ())) > 1:
                # resilience tier (PR 6): striped sources become canary
                # targets so FAILED members are re-probed in background
                self._canary_sources.add(source)
            dma_max = int(config.get("dma_max_size"))
            if self._tuner.enabled:
                # effective-knob indirection (ISSUE 18): with the
                # controller on, the tuned per-member cap owns the
                # request split/merge size on both paths (still inside
                # dma_max_size's declared bounds)
                dma_max = self._tuner.dma_cap(dma_max)
            # coalescing beyond dma_max is the native-queue saturation
            # lever; the pool path keeps classic per-extent planning so
            # fault injection and the retry ladder see every extent
            climit = int(config.get("coalesce_limit")) if use_native else 0
            if climit and config.get("chunk_adaptive"):
                nmem_src = len(getattr(source, "members", ())) or 1
                if nmem_src > 1:
                    climit = {m: self._adaptive_cap(dma_max, climit, member=m)
                              for m in range(nmem_src)}
                else:
                    climit = self._adaptive_cap(dma_max, climit)
            verify = bool(config.get("checksum_verify"))
            window = max(int(config.get("submit_window")), 1)
            if self._tuner.enabled:
                window = max(self._tuner.submit_window(window), 1)
            entries = [(cid, i) for i, cid in enumerate(direct_ids)]
            fds = source.member_fds() if use_native else None
            # degraded-mode striping on the native path (PR 6): extents of
            # a member the health machine routes away (QUARANTINED/FAILED)
            # are submitted against the mirror partner's fd — and lane —
            # at direct speed, instead of collapsing to the buffered path
            mirror_remap: Dict[int, int] = {}
            if use_native:
                for m in range(len(fds)):
                    if self._member_health.routes_away(m):
                        mir = source.mirror_of(m)
                        if mir is not None and \
                                not self._member_health.routes_away(mir):
                            mirror_remap[m] = mir
            # NVMe passthrough split (PR 19): one channel per task; each
            # planned window then splits per extent below.  The channel
            # must match the executing path — native tasks need the real
            # URING_CMD rung, pool tasks need a pool-capable (emulator)
            # channel with Python-side command service.
            pt_chan = self._passthru_channel(source) if direct_ids else None
            if pt_chan is not None:
                pt_ok = getattr(pt_chan, "native", False) if use_native \
                    else getattr(pt_chan, "pool_ok", False)
                if not pt_ok:
                    pt_chan = None
            if pt_chan is not None:
                task.passthru = pt_chan
            native_failed = False
            for w in range(0, len(entries), window):
                tp0 = time.monotonic_ns()
                with stats.stage("setup_prps"):
                    reqs = plan_requests(source, entries[w:w + window],
                                         chunk_size, dest_offset,
                                         coalesce_limit=climit or None)
                if _trace.active and task.trace_id:
                    _trace.span("plan", tp0, time.monotonic_ns(),
                                tid=task.trace_id,
                                args={"window": w // window,
                                      "requests": len(reqs)})
                if pt_chan is not None:
                    reqs = self._passthru_split(task, source, reqs,
                                                pt_chan, mirror_remap)
                if not use_native or native_failed:
                    self._submit_pool_requests(task, source, reqs, dest)
                    continue
                native_reqs = []
                native_members = []
                native_rs = []
                native_pt = []
                for r in reqs:
                    if r.buffered or fds[r.member] < 0:
                        # misaligned tails: synchronous buffered copy, like
                        # the reference's in-ioctl page-cache memcpy —
                        # accounted like the pool path so per-member stats
                        # agree regardless of which branch executed
                        tb = time.monotonic_ns()
                        source.read_member_buffered(
                            r.member, r.file_off,
                            dest[r.dest_off:r.dest_off + r.length])
                        stats.member_add(r.member, r.length,
                                         time.monotonic_ns() - tb)
                        stats.count_clock("submit_dma", 0)
                        stats.add("total_dma_length", r.length)
                        if verify:
                            # sync legs verify here: they never reach the
                            # wait-time hook (only native_rs do)
                            self._verify_request_checksums(source, r, dest)
                    elif r.dest_segs:
                        # vectored (stripe-coalesced) reads split back into
                        # per-segment submissions for the native engine —
                        # its deep per-ring queue already holds them all;
                        # the vectored form pays off on the preadv pool path
                        m_eff = mirror_remap.get(r.member, r.member)
                        if m_eff != r.member:
                            stats.add("nr_mirror_read")
                            if _trace.active and task.trace_id:
                                _trace.instant(
                                    "mirror_read", tid=task.trace_id,
                                    member=r.member, offset=r.file_off,
                                    length=r.length,
                                    args={"mirror": m_eff})
                        foff = r.file_off
                        for dseg, lseg in r.dest_segs:
                            native_reqs.append((fds[m_eff], foff, lseg,
                                                dseg))
                            native_members.append(m_eff)
                            native_pt.append(False)
                            foff += lseg
                        native_rs.append(r)
                    else:
                        m_eff = mirror_remap.get(r.member, r.member)
                        if m_eff != r.member:
                            stats.add("nr_mirror_read")
                            if _trace.active and task.trace_id:
                                _trace.instant(
                                    "mirror_read", tid=task.trace_id,
                                    member=r.member, offset=r.file_off,
                                    length=r.length,
                                    args={"mirror": m_eff})
                        if r.passthru_off is not None:
                            # raw-command lane: the engine reads the char
                            # device at the blockmap-resolved offset; the
                            # member fd rides along for bookkeeping only
                            native_reqs.append((fds[m_eff], r.passthru_off,
                                                r.length, r.dest_off))
                            native_pt.append(True)
                        else:
                            native_reqs.append((fds[m_eff], r.file_off,
                                                r.length, r.dest_off))
                            native_pt.append(False)
                        native_members.append(m_eff)
                        native_rs.append(r)
                if not native_reqs:
                    continue
                try:
                    self._members_used.update(native_members)
                    addr = ctypes.addressof(
                        ctypes.c_char.from_buffer(dest))
                    # capture the engine: a concurrent lane scale-out may
                    # swap self._native, and the wait must run against
                    # the engine that accepted the batch
                    nat = self._native
                    if any(native_pt):
                        nid = nat.submit(addr, native_reqs,
                                         members=native_members,
                                         passthru=native_pt)
                    else:
                        nid = nat.submit(addr, native_reqs,
                                         members=native_members)
                    if _trace.active and task.trace_id:
                        _trace.instant(
                            "native_submit", tid=task.trace_id,
                            length=sum(q[2] for q in native_reqs),
                            args={"requests": len(native_reqs),
                                  "batch": nid})
                    self._task_get(task)
                    try:
                        self._pool.submit(self._await_native, task, nat, nid)
                    except BaseException as e:
                        self._task_put(task, StromError(
                            _errno.ESHUTDOWN, str(e)))
                        raise
                    if verify:
                        if task.verify_reqs is None:
                            task.verify_src = source
                            task.verify_dest = dest
                            task.verify_reqs = []
                        task.verify_reqs.extend(native_rs)
                except StromError as e:
                    # native submit failure degrades to the Python
                    # pool path instead of failing the whole memcpy
                    # (tentpole degradation tier 3); later windows skip
                    # straight to the pool
                    if not config.get("io_fallback"):
                        raise
                    stats.add("nr_backend_fallback")
                    pr_warn("native submit failed (%s); batch falls "
                            "back to the python pool path", e)
                    native_failed = True
                    self._submit_pool_requests(task, source, native_rs,
                                               dest)

            # --- write-back copies (synchronous, like the in-ioctl memcpy;
            #     AFTER direct submission so the device queue fills first
            #     and these page-cache copies overlap in-flight direct I/O)
            for i, cid in enumerate(wb_ids):
                slot = nr_ssd + i
                base = cid * chunk_size
                length = min(chunk_size, source.size - base)
                target = wb_buffer if wb_buffer is not None else dest
                off = (dest_offset if wb_buffer is None else 0) + slot * chunk_size
                tw0 = time.monotonic_ns()
                source.read_buffered(base, target[off:off + length])
                if _trace.active and task.trace_id:
                    _trace.span("writeback", tw0, time.monotonic_ns(),
                                tid=task.trace_id, offset=base,
                                length=length)

            # --- residency-tier hit serving (tail-packed after the
            #     write-back slots): memcpy out of the pinned slab, no
            #     submission — a fully-resident task reaches here with
            #     nothing submitted at all
            j = 0
            while cache_hits:
                cid, base, length, lease, hbm = cache_hits.pop(0)
                slot = nr_ssd + len(wb_ids) + j
                j += 1
                target = wb_buffer if wb_buffer is not None else dest
                off = (dest_offset if wb_buffer is None else 0) \
                    + slot * chunk_size
                th0 = time.monotonic_ns()
                try:
                    if not lease.copy_into(target[off:off + length]):
                        # invalidated between lookup and serve: the
                        # write that staled the slab wins — read fresh
                        source.read_buffered(base,
                                             target[off:off + length])
                finally:
                    lease.release()
                if _trace.active and task.trace_id:
                    _trace.span("cache_hit", th0, time.monotonic_ns(),
                                tid=task.trace_id, offset=base,
                                length=length,
                                args=({"tier": "hbm"} if hbm else None))

            # --- record the miss fills, consumed at wait time once the
            #     fault ladder has healed the destination bytes (direct
            #     chunks land in `dest` even when wb_buffer is given)
            if skey is not None and direct_ids:
                fills = []
                for i, cid in enumerate(direct_ids):
                    base = cid * chunk_size
                    fills.append((base,
                                  min(chunk_size, source.size - base),
                                  dest_offset + i * chunk_size))
                task.cache_fill = (skey, fills, dest,
                                   getattr(source, "logical_scale", 1.0),
                                   _weakref.ref(source), speculative)
        except BaseException:
            while cache_hits:  # leases not yet served: unpin them
                cache_hits.pop()[3].release()
            self._task_put(task, StromError(_errno.ECANCELED, "submit aborted"))
            # reference waits out in-flight DMA on submit error (:1781-1784)
            try:
                self.memcpy_wait(task.task_id, timeout=30.0)
            except StromError:
                pass
            self._put_buffer(buf_handle)
            raise
        result = MemCopyResult(dma_task_id=task.task_id, nr_chunks=n,
                               nr_ssd2dev=nr_ssd, nr_ram2dev=n - nr_ssd,
                               chunk_ids=new_order)
        task.result = result
        # freeze: submission loop done, no further refs (reference :1766-1767)
        sidx = self._slot_of(task.task_id)
        with self._slot_cv[sidx]:
            task.frozen = True
        task.buf_handle = buf_handle
        self._task_put(task)  # drop creator ref; releases the buffer ref on completion
        stats.count_clock("ioctl_memcpy_submit", time.monotonic_ns() - t0)
        return result

    # SSD->device is the same submit path; the HBM leg lives in hbm.staging.
    memcpy_ssd2dev = memcpy_ssd2ram

    def memcpy_ram2ssd(self, sink: Source, buf_handle: int,
                       chunk_ids: Sequence[int], chunk_size: int, *,
                       src_offset: int = 0) -> MemCopyResult:
        """RAM→SSD write submit path (exceeds the read-only reference).

        Buffer slot *i* (``src_offset + i*chunk_size``) is written to sink
        chunk ``chunk_ids[i]``.  Planning reuses the read-side merge logic
        (same extents, same ≤dma_max requests, buffered legs for
        misaligned pieces); writes are always direct — there is no cache
        to arbitrate against.  Aligned legs run GIL-free on the native
        engine (IORING_OP_WRITE) when available, mirroring the read path;
        misaligned tails take a synchronous buffered write.  Durability of
        buffered legs needs a ``sink.sync()`` after the wait."""
        t0 = time.monotonic_ns()
        if self._closed:
            raise StromError(_errno.EBADF, "session closed")
        if chunk_size <= 0 or (chunk_size & (chunk_size - 1)):
            raise StromError(_errno.EINVAL, f"chunk_size {chunk_size} must be pow2")
        sink._check_writable()
        chunk_ids = list(chunk_ids)
        n = len(chunk_ids)
        if n == 0:
            raise StromError(_errno.EINVAL, "no chunks")
        src = self._get_buffer(buf_handle, need=src_offset + n * chunk_size)
        task = self._create_task()
        try:
            # passthrough coherency (PR 19): a write-back may relocate
            # extents (CoW filesystems); drop the cached file->LBA maps at
            # the same site the resident cache invalidates, so the next
            # passthrough split re-resolves against post-write reality
            blockmap.invalidate_source(sink)
            if _tiers.lookup_active:
                # write-back coherency (ISSUE 9): ONE invalidation
                # contract over the whole hierarchy (ISSUE 20) — drop
                # every tier's resident extents the write touches before
                # any byte moves, and again at wait time
                # (task.cache_invalidate) in case a racing read
                # re-filled from pre-write bytes mid-flight
                wkey = _tiers.source_key(sink)
                extents = [(cid * chunk_size, chunk_size)
                           for cid in chunk_ids]
                _tiers.invalidate_extents(wkey, extents)
                task.cache_invalidate = (wkey, extents)
            with stats.stage("setup_prps"):
                reqs = plan_requests(sink, [(cid, i) for i, cid in enumerate(chunk_ids)],
                                     chunk_size, src_offset)
            if len(getattr(sink, "members", ())) > 1:
                # written striped sinks become canary targets too
                # (ISSUE 11): the canary thread replays their dirty-extent
                # resync journal while a degraded member rejoins
                self._canary_sources.add(sink)
            if config.get("write_verify"):
                # wait-time read-back verification rides the retired task
                task.write_verify = (sink, list(reqs), src)
            # GIL-free write leg, mirroring the read path's native branch
            # (fakes overriding the write leg keep the Python path so
            # fault injection still works)
            use_native = (self._native is not None and reqs
                          and type(sink).write_member_direct
                          is Source.write_member_direct)
            pool_reqs = list(reqs) if not use_native else []
            if use_native:
                self._ensure_member_lanes(sink)
                fds = sink.member_fds()
                health = self._member_health
                native_reqs = []
                native_members = []
                native_rs = []      # unique planned requests riding native
                n_mirror_legs = 0
                for r in reqs:
                    mirror = sink.mirror_of(r.member)
                    if r.buffered or fds[r.member] < 0 or \
                            (mirror is not None and fds[mirror] < 0):
                        # misaligned tails (and legs without a direct fd)
                        # ride the pool ladder (ISSUE 11) — transient
                        # retry, cancellation-on-latch and mirror fan-out
                        # instead of the old unpoliced synchronous write
                        pool_reqs.append(r)
                        continue
                    # mirror-coherent fan-out: each aligned leg lands on
                    # primary + pair partner; a member the health machine
                    # routes away is skipped and journaled for resync
                    legs = [(r.member, None)]
                    if mirror is not None:
                        away_p = health.routes_away(r.member)
                        away_m = health.routes_away(mirror)
                        if away_p and not away_m:
                            self._journal_skipped(sink, r.member,
                                                  r.file_off, r.length,
                                                  task.trace_id)
                            legs = [(mirror, r.member)]
                        elif away_m and not away_p:
                            self._journal_skipped(sink, mirror,
                                                  r.file_off, r.length,
                                                  task.trace_id)
                        else:
                            legs.append((mirror, r.member))
                    native_rs.append(r)
                    for m, covered in legs:
                        if covered is not None:
                            n_mirror_legs += 1
                            if _trace.active and task.trace_id:
                                _trace.instant("mirror_write",
                                               tid=task.trace_id,
                                               member=covered,
                                               offset=r.file_off,
                                               length=r.length,
                                               args={"mirror": m})
                        native_reqs.append((fds[m], r.file_off,
                                            r.length, r.dest_off))
                        native_members.append(m)
                if native_reqs:
                    try:
                        self._members_used.update(native_members)
                        addr = ctypes.addressof(
                            ctypes.c_char.from_buffer(src))
                        nat = self._native
                        nid = nat.submit(addr, native_reqs,
                                         write=True,
                                         members=native_members)
                        self._task_get(task)
                        try:
                            self._pool.submit(
                                self._await_native, task, nat, nid,
                                (sink, native_rs, src, n_mirror_legs))
                        except BaseException as e:
                            self._task_put(task, StromError(
                                _errno.ESHUTDOWN, str(e)))
                            raise
                    except StromError as e:
                        if not config.get("io_fallback"):
                            raise
                        stats.add("nr_backend_fallback")
                        pr_warn("native write submit failed (%s); batch "
                                "falls back to the python pool path", e)
                        pool_reqs.extend(native_rs)
            for r in pool_reqs:
                self._task_get(task)
                cur = stats.gauge_add("cur_dma_count", 1)
                stats.gauge_max("max_dma_count", cur)
                stats.count_clock("submit_dma", 0)
                stats.add("total_dma_length", r.length)
                try:
                    self._pool.submit(self._do_write_request, task, sink, r, src)
                except BaseException as e:
                    stats.gauge_add("cur_dma_count", -1)
                    self._task_put(task, StromError(_errno.ESHUTDOWN, str(e)))
                    raise
        except BaseException:
            self._task_put(task, StromError(_errno.ECANCELED, "submit aborted"))
            try:
                self.memcpy_wait(task.task_id, timeout=30.0)
            except StromError:
                pass
            self._put_buffer(buf_handle)
            raise
        result = MemCopyResult(dma_task_id=task.task_id, nr_chunks=n,
                               nr_ssd2dev=n, nr_ram2dev=0,
                               chunk_ids=chunk_ids)
        task.result = result
        sidx = self._slot_of(task.task_id)
        with self._slot_cv[sidx]:
            task.frozen = True
        task.buf_handle = buf_handle
        self._task_put(task)
        stats.count_clock("ioctl_memcpy_submit", time.monotonic_ns() - t0)
        return result

    def _do_write_request(self, task: DmaTask, sink: Source,
                          r: Request, src: memoryview) -> None:
        if task.errno_:
            stats.add("nr_chunk_cancelled")
            stats.gauge_add("cur_dma_count", -1)
            self._task_put(task, None)
            return
        err = self._write_request_resilient(task, sink, r, src)
        stats.gauge_add("cur_dma_count", -1)
        self._task_put(task, err)

    def _write_request_resilient(self, task: DmaTask, sink: Source,
                                 r: Request, src: memoryview
                                 ) -> Optional[StromError]:
        """One write request through the full ladder (ISSUE 11, the
        write-side peer of :meth:`_read_direct_resilient`): paired sinks
        fan out to primary + mirror partner — both must land before the
        task retires; a member the health machine routes away (or that
        fails mid-stream and latches off the direct path) degrades the
        write to mirror-only with the missed extent journaled for rejoin
        resync.  Returns the error to latch, or None."""
        err: Optional[StromError] = None
        t0 = time.monotonic_ns()
        try:
            piece = src[r.dest_off:r.dest_off + r.length]
            mirror = sink.mirror_of(r.member)
            if mirror is None:
                self._write_leg(task, sink, r, r.member, piece)
            else:
                err = self._write_mirrored(task, sink, r, mirror, piece)
        except StromError as e:
            err = e
        except BaseException as e:
            err = StromError(_errno.EIO, f"unexpected write failure: {e!r}")
        finally:
            elapsed = time.monotonic_ns() - t0
            if _trace.active and task.trace_id:
                eargs: dict = {"write": True}
                if r.buffered:
                    eargs["buffered"] = True
                if err is not None:
                    eargs["errno"] = err.errno
                _trace.span("extent", t0, t0 + elapsed, tid=task.trace_id,
                            member=r.member, offset=r.file_off,
                            length=r.length, args=eargs)
        return err

    def _write_leg(self, task: DmaTask, sink: Source, r: Request,
                   member: int, piece: memoryview) -> None:
        """One write leg with transient retry; failures debit the health
        machine with the read-side taxonomy (ENOSPC/EDQUOT/EROFS are
        PERSISTENT: first-error latch, never a retry storm) and successes
        feed latency into suspect detection + the member's adaptive
        sizer, so write-only traffic drives the ladder too."""
        health = self._member_health
        attempt = 0
        t0 = time.monotonic_ns()
        try:
            while True:
                try:
                    if r.buffered:
                        sink.write_member_buffered(member, r.file_off,
                                                   piece)
                    else:
                        sink.write_member_direct(member, r.file_off,
                                                 piece)
                    break
                except (StromError, OSError) as e:
                    se = e if isinstance(e, StromError) else \
                        StromError(e.errno or _errno.EIO, str(e))
                    # transient write errors retry under the same policy;
                    # no buffered degradation (a half-direct half-buffered
                    # write would need a sync to be durable)
                    if not se.transient or r.buffered \
                            or attempt >= self._retry.attempts \
                            or task.errno_:
                        health.record_failure(
                            member,
                            fatal=se.error_class is ErrorClass.PERSISTENT)
                        stats.member_error(member)
                        raise se
                    stats.add("nr_io_retry")
                    stats.add("nr_write_retry")
                    stats.member_error(member, retried=True)
                    if _trace.active and task.trace_id:
                        _trace.instant("retry", tid=task.trace_id,
                                       member=member,
                                       args={"attempt": attempt + 1,
                                             "errno": se.errno,
                                             "write": True})
                    self._retry.sleep(attempt, self._retry_rng)
                    attempt += 1
        finally:
            elapsed = time.monotonic_ns() - t0
            stats.member_add(member, r.length, elapsed)
        if not r.buffered:
            stats.observe_latency(elapsed)
            health.observe_latency(member, elapsed)
            # write latencies feed the member's adaptive sizer too —
            # created here under the same config gates as the read
            # planner, so write-only traffic still shapes the next
            # native plan's coalescing cap
            if config.get("chunk_adaptive"):
                climit = int(config.get("coalesce_limit"))
                if climit:
                    self._adaptive_cap(int(config.get("dma_max_size")),
                                       climit, member)
            szr = self._chunk_sizers.get(member)
            if szr is not None:
                szr.observe(elapsed)
        health.record_success(member)

    def _write_mirrored(self, task: DmaTask, sink: Source, r: Request,
                        mirror: int, piece: memoryview
                        ) -> Optional[StromError]:
        """Mirror fan-out for one request on a paired sink.  Both legs
        must land for a clean retire; a leg whose member routes away is
        skipped up front and journaled, and a leg that fails mid-stream
        *and* leaves its member routed away (quarantined/failed) degrades
        the same way — the stream stays alive on the surviving replica.
        A failure on a member still serving the direct path latches:
        swallowing it would leave readable stale bytes with no resync
        owner."""
        health = self._member_health
        away_p = health.routes_away(r.member)
        away_m = health.routes_away(mirror)
        do_p = do_m = True
        if away_p and not away_m:
            self._journal_skipped(sink, r.member, r.file_off, r.length,
                                  task.trace_id)
            do_p = False
        elif away_m and not away_p:
            self._journal_skipped(sink, mirror, r.file_off, r.length,
                                  task.trace_id)
            do_m = False
        p_err = m_err = None
        if do_p:
            try:
                self._write_leg(task, sink, r, r.member, piece)
            except StromError as e:
                p_err = e
        if do_m:
            tm = time.monotonic_ns()
            try:
                self._write_leg(task, sink, r, mirror, piece)
            except StromError as e:
                m_err = e
            else:
                stats.add("nr_mirror_write")
                if _trace.active and task.trace_id:
                    _trace.span("mirror_write", tm, time.monotonic_ns(),
                                tid=task.trace_id, member=r.member,
                                offset=r.file_off, length=r.length,
                                args={"mirror": mirror})
        if p_err is not None and m_err is None and do_m \
                and health.routes_away(r.member):
            self._journal_skipped(sink, r.member, r.file_off, r.length,
                                  task.trace_id)
            p_err = None
        if m_err is not None and p_err is None and do_p \
                and health.routes_away(mirror):
            self._journal_skipped(sink, mirror, r.file_off, r.length,
                                  task.trace_id)
            m_err = None
        return p_err or m_err

    def _verify_writes(self, sink: Source, reqs: List[Request],
                       src: memoryview, task: DmaTask) -> None:
        """write_verify (ISSUE 11): read every retired write leg back
        and compare crc32c against the submitted bytes.  Legs whose
        member routes away were degraded + journaled for resync (the
        bytes there are known-stale until replay), so they are skipped;
        everything else must match or EBADMSG (CORRUPTION) raises — a
        torn or misdirected write caught at the durability boundary
        instead of on some future read."""
        from .scan.heap import crc32c
        health = self._member_health
        scratch: Optional[mmap.mmap] = None
        try:
            for r in reqs:
                want = crc32c(src[r.dest_off:r.dest_off + r.length])
                members = [r.member]
                mirror = sink.mirror_of(r.member)
                if mirror is not None:
                    members.append(mirror)
                for m in members:
                    if health.routes_away(m):
                        continue
                    if r.buffered:
                        back = bytearray(r.length)
                        sink.read_member_buffered(m, r.file_off,
                                                  memoryview(back))
                        got = crc32c(back)
                    else:
                        if scratch is None or len(scratch) < r.length:
                            if scratch is not None:
                                scratch.close()
                            sz = -(-r.length // mmap.PAGESIZE) \
                                * mmap.PAGESIZE
                            scratch = mmap.mmap(-1, sz)
                        mv = memoryview(scratch)[:r.length]
                        try:
                            sink.read_member_direct(m, r.file_off, mv)
                            got = crc32c(mv)
                        finally:
                            # release before any raise: an exported view
                            # would make scratch.close() throw and mask
                            # the verification error
                            mv.release()
                    stats.add("bytes_verify_reread", r.length)
                    if got != want:
                        stats.add("nr_write_verify_fail")
                        if _trace.active and task.trace_id:
                            _trace.instant("csum_fail", tid=task.trace_id,
                                           member=m, offset=r.file_off,
                                           length=r.length,
                                           args={"write_verify": True})
                        raise StromError(
                            _errno.EBADMSG,
                            f"write_verify: crc32c mismatch on member {m}"
                            f" at file offset {r.file_off} ({r.length} "
                            f"bytes): wrote {want:#010x}, read back "
                            f"{got:#010x}")
        finally:
            if scratch is not None:
                scratch.close()

    def _do_request(self, task: DmaTask, source: Source,
                    r: Request, dest: memoryview) -> None:
        if task.errno_:
            # task already failed (first-error latch or watchdog expiry):
            # cancel this chunk instead of reading into a buffer whose
            # waiter has already been woken with an error
            stats.add("nr_chunk_cancelled")
            stats.gauge_add("cur_dma_count", -1)
            self._task_put(task, None)
            return
        err: Optional[StromError] = None
        t0 = time.monotonic_ns()
        try:
            if r.buffered:
                piece = dest[r.dest_off:r.dest_off + r.length]
                source.read_member_buffered(r.member, r.file_off, piece)
            else:
                self._read_direct_resilient(task, source, r, dest)
        except StromError as e:
            err = e
        except OSError as e:
            err = StromError(e.errno or _errno.EIO, str(e))
        except BaseException as e:  # any failure must latch, never silently DONE
            err = StromError(_errno.EIO, f"{type(e).__name__}: {e}")
        finally:
            elapsed = time.monotonic_ns() - t0
            stats.member_add(r.member, r.length, elapsed)
            if _trace.active and task.trace_id:
                eargs = {}
                if r.buffered:
                    eargs["buffered"] = True
                if err is not None:
                    eargs["errno"] = err.errno
                _trace.span("extent", t0, t0 + elapsed, tid=task.trace_id,
                            member=r.member, offset=r.file_off,
                            length=r.length, args=eargs or None)
            if not r.buffered:
                stats.observe_latency(elapsed)
                if err is None:
                    # health-machine latency feed (PR 6): per-member p99
                    # drift past suspect_ratio x the stripe median marks
                    # the member SUSPECT (hedge-eligible)
                    self._member_health.observe_latency(r.member, elapsed)
                szr = self._chunk_sizers.get(r.member)
                if szr is not None:
                    szr.observe(elapsed)
            stats.gauge_add("cur_dma_count", -1)
            self._task_put(task, err)

    def _read_direct_resilient(self, task: DmaTask, source: Source,
                               r: Request, dest: memoryview) -> None:
        """One direct-read extent with the full recovery ladder (PR 1,
        extended PR 6): members the health machine routes away serve from
        their mirror partner at direct speed (degraded-mode striping),
        falling back to the buffered path; TRANSIENT errors retry under
        the RetryPolicy (backoff + jitter) then degrade mirror-first;
        PERSISTENT errors drive the member to FAILED and fail over the
        same way, so a mid-task fail-stop stays byte-identical; with
        ``hedge_policy`` armed, a plain extent still in flight past the
        hedge latch races a mirror/buffered hedge leg, first completion
        wins; optional crc32c verification re-reads on mismatch and
        latches a CORRUPTION error after ``checksum_retries`` failed
        heals.

        Coalesced (vectored) requests read all destination segments in one
        preadv; the recovery ladder treats the whole vectored extent as one
        unit, exactly as a plain extent."""
        health = self._member_health
        mirror = source.mirror_of(r.member)
        if r.dest_segs:
            views = [dest[d:d + l] for d, l in r.dest_segs]

            def _direct() -> None:
                source.read_member_direct_v(r.member, r.file_off, views)

            def _mirror_read() -> None:
                source.read_member_direct_v(mirror, r.file_off, views)

            def _buffered() -> None:
                foff = r.file_off
                for v in views:
                    source.read_member_buffered(r.member, foff, v)
                    foff += len(v)
        else:
            piece = dest[r.dest_off:r.dest_off + r.length]
            # passthrough lane (PR 19): a blockmap-resolved sub-request's
            # direct leg issues the raw NVMe READ through the task's
            # channel; every recovery rung below (mirror, buffered) leaves
            # the lane and counts the exit — the ladder itself is UNCHANGED
            pt = task.passthru if (r.passthru_off is not None and
                                   getattr(task.passthru, "pool_ok", False)) \
                else None

            if pt is not None:
                def _direct() -> None:
                    pt.read(r.member, r.file_off, r.passthru_off, piece)
                    stats.add("nr_passthru_dma")
            else:
                def _direct() -> None:
                    source.read_member_direct(r.member, r.file_off, piece)

            def _mirror_read() -> None:
                if pt is not None:
                    _passthru_left_lane(task, r)
                source.read_member_direct(mirror, r.file_off, piece)

            def _buffered() -> None:
                if pt is not None:
                    _passthru_left_lane(task, r)
                source.read_member_buffered(r.member, r.file_off, piece)

        fallback_ok = bool(config.get("io_fallback"))

        def _try_mirror() -> bool:
            """Degraded-mode striping: serve the extent from the pair
            partner at direct speed.  A mirror failure counts against the
            mirror and falls through to the next rung of the ladder."""
            if mirror is None or not health.allow_direct(mirror):
                return False
            tm = time.monotonic_ns()
            try:
                _mirror_read()
            except (StromError, OSError) as e:
                me = e if isinstance(e, StromError) else \
                    StromError(e.errno or _errno.EIO, str(e))
                health.record_failure(
                    mirror, fatal=me.error_class is ErrorClass.PERSISTENT)
                stats.member_error(mirror)
                return False
            stats.add("nr_mirror_read")
            if _trace.active and task.trace_id:
                # attributed to the member being covered FOR, so the
                # degraded read shows on the failing member's track
                _trace.span("mirror_read", tm, time.monotonic_ns(),
                            tid=task.trace_id, member=r.member,
                            offset=r.file_off, length=r.length,
                            args={"mirror": mirror})
            health.record_success(mirror)
            health.observe_latency(mirror, time.monotonic_ns() - tm)
            return True

        done = False
        if (mirror is not None or fallback_ok) \
                and not health.allow_direct(r.member):
            # routed away (QUARANTINED/FAILED, or REJOINING beyond its
            # warmup tokens): mirror at direct speed first, buffered next
            if _trace.active and task.trace_id:
                _trace.instant("route_away", tid=task.trace_id,
                               member=r.member, offset=r.file_off,
                               length=r.length)
            if _try_mirror():
                done = True
            elif fallback_ok:
                stats.add("nr_io_fallback")
                _buffered()
                done = True
        if not done and not r.dest_segs:
            hd = health.hedge_delay_s(r.member)
            if hd is not None and self._tuner.enabled:
                # effective-knob indirection (ISSUE 18): the tuned
                # per-member latch replaces the static hedge_ms floor;
                # the policy decision (None = hedging off) stays with
                # the health machine
                hd = self._tuner.hedge_delay(r.member, hd)
            if hd is not None and len(getattr(source, "members", ())) > 1:
                done = self._read_hedged(task, source, r, piece, hd, mirror)
        attempt = 0
        while not done:
            try:
                _direct()
                health.record_success(r.member)
                break
            except (StromError, OSError) as e:
                se = e if isinstance(e, StromError) else \
                    StromError(e.errno or _errno.EIO, str(e))
                if not se.transient:
                    # fail-stop: the member is gone.  Its mirror keeps the
                    # task alive at direct speed (byte identity across
                    # mid-task member loss); otherwise latch the error.
                    health.record_failure(
                        r.member,
                        fatal=se.error_class is ErrorClass.PERSISTENT)
                    stats.member_error(r.member)
                    if _try_mirror():
                        break
                    raise se
                health.record_failure(r.member)
                # stop burning attempts once the task already failed or
                # expired — the result can no longer be delivered
                if attempt < self._retry.attempts and not task.errno_:
                    stats.add("nr_io_retry")
                    stats.member_error(r.member, retried=True)
                    if _trace.active and task.trace_id:
                        _trace.instant("retry", tid=task.trace_id,
                                       member=r.member, offset=r.file_off,
                                       length=r.length,
                                       args={"attempt": attempt + 1,
                                             "errno": se.errno})
                    self._retry.sleep(attempt, self._retry_rng)
                    attempt += 1
                    continue
                stats.member_error(r.member)
                if task.errno_:
                    raise se
                if _try_mirror():
                    break
                if fallback_ok:
                    # retries exhausted: degrade this extent to the
                    # buffered path (the reference's page-cache
                    # arbitration, reused as an error path)
                    stats.add("nr_io_fallback")
                    if _trace.active and task.trace_id:
                        _trace.instant("fallback_buffered",
                                       tid=task.trace_id, member=r.member,
                                       offset=r.file_off, length=r.length)
                    _buffered()
                    break
                raise se
        if config.get("checksum_verify"):
            self._verify_request_checksums(source, r, dest)

    def _read_hedged(self, task: DmaTask, source: Source, r: Request,
                     piece: memoryview, delay_s: float,
                     mirror: Optional[int]) -> bool:
        """Hedged read of one plain extent (Python pool path): the primary
        direct read races a hedge leg armed after *delay_s* — the mirror
        partner at direct speed when one exists, else the buffered path.
        Both legs land in private scratch buffers and the first completion
        copies into the destination under the winner lock; the loser is
        discarded (safe cancellation: a torn destination is impossible and
        a late loser never overwrites the winner).

        Returns True when either leg delivered the extent, False when
        there is nothing to hedge onto (the caller runs the plain ladder);
        raises when the primary failed and the hedge could not save it."""
        health = self._member_health
        use_mirror = mirror is not None and health.allow_direct(mirror)
        fallback_ok = bool(config.get("io_fallback"))
        if not use_mirror and not fallback_ok:
            return False
        # passthrough lane (PR 19): the primary leg of a resolved
        # sub-request stays on the raw-command path; the hedge leg is by
        # construction off-lane (mirror/buffered), so its win is an exit
        pt = task.passthru if (r.passthru_off is not None and
                               getattr(task.passthru, "pool_ok", False)) \
            else None
        lock = threading.Lock()
        won = threading.Event()            # a winner has landed in dest
        hedge_settled = threading.Event()  # the hedge leg has exited
        prim_settled = threading.Event()   # the primary leg has exited
        state = {"winner": None, "prim_ok": False, "prim_err": None}

        def _finish(who: str, scratch) -> bool:
            with lock:
                if state["winner"] is None and not task.errno_:
                    state["winner"] = who
                    piece[:] = scratch
                    won.set()
                    return True
            return False

        def _hedge_leg() -> None:
            scratch = mv = None
            try:
                if won.wait(delay_s) or task.errno_:
                    return            # primary beat the latch: never issued
                with lock:
                    if state["winner"] is not None:
                        return
                stats.add("nr_hedge_issued")
                # the race reads this extent twice — one leg's bytes are
                # pure overhead whoever wins (bytes-touched gate metric)
                stats.add("bytes_hedge_dup", r.length)
                th0 = time.monotonic_ns()
                if _trace.active and task.trace_id:
                    # hedge events ride the PRIMARY member's track: the
                    # race is a fact about the slow/failing member, the
                    # serving leg is an attribute
                    _trace.instant("hedge_issued", tid=task.trace_id,
                                   member=r.member,
                                   offset=r.file_off, length=r.length,
                                   args={"leg": f"mirror:{mirror}"
                                         if use_mirror else "buffered"})
                # page-aligned scratch: the direct leg is an O_DIRECT
                # pread and a heap bytearray would EINVAL it
                scratch = mmap.mmap(-1, max(r.length, 1))
                mv = memoryview(scratch)[:r.length]
                try:
                    if use_mirror:
                        source.read_member_direct(mirror, r.file_off, mv)
                    else:
                        source.read_member_buffered(r.member, r.file_off, mv)
                except (StromError, OSError):
                    if use_mirror:
                        health.record_failure(mirror)
                    stats.add("nr_hedge_cancelled")
                    if _trace.active and task.trace_id:
                        _trace.instant("hedge_cancelled",
                                       tid=task.trace_id, member=r.member,
                                       offset=r.file_off, length=r.length,
                                       args={"reason": "leg_failed"})
                    return
                if use_mirror:
                    health.record_success(mirror)
                    stats.add("nr_mirror_read")
                if _finish("hedge", scratch):
                    stats.add("nr_hedge_won")
                    if pt is not None:
                        _passthru_left_lane(task, r)
                    if _trace.active and task.trace_id:
                        _trace.span("hedge_won", th0, time.monotonic_ns(),
                                    tid=task.trace_id, member=r.member,
                                    offset=r.file_off, length=r.length,
                                    args={"leg": f"mirror:{mirror}"
                                          if use_mirror else "buffered"})
                else:
                    stats.add("nr_hedge_cancelled")
                    if _trace.active and task.trace_id:
                        _trace.instant("hedge_cancelled",
                                       tid=task.trace_id, member=r.member,
                                       offset=r.file_off, length=r.length,
                                       args={"reason": "primary_won"})
            finally:
                if mv is not None:
                    mv.release()
                if scratch is not None:
                    scratch.close()
                hedge_settled.set()

        def _primary_leg() -> None:
            scratch = mmap.mmap(-1, max(r.length, 1))   # O_DIRECT-aligned
            mv = memoryview(scratch)[:r.length]
            attempt = 0
            try:
                while True:
                    try:
                        if pt is not None:
                            pt.read(r.member, r.file_off, r.passthru_off, mv)
                            stats.add("nr_passthru_dma")
                        else:
                            source.read_member_direct(r.member, r.file_off,
                                                      mv)
                        health.record_success(r.member)
                        break
                    except (StromError, OSError) as e:
                        se = e if isinstance(e, StromError) else \
                            StromError(e.errno or _errno.EIO, str(e))
                        if se.transient and attempt < self._retry.attempts \
                                and not task.errno_ and not won.is_set():
                            health.record_failure(r.member)
                            stats.add("nr_io_retry")
                            stats.member_error(r.member, retried=True)
                            self._retry.sleep(attempt, self._retry_rng)
                            attempt += 1
                            continue
                        # terminal primary failure: exactly one health
                        # debit for this chunk even when the hedge already
                        # won — a hedged chunk must not double-count
                        # toward quarantine
                        health.record_failure(
                            r.member,
                            fatal=se.error_class is ErrorClass.PERSISTENT)
                        stats.member_error(r.member)
                        state["prim_err"] = se
                        return
                state["prim_ok"] = True
                _finish("primary", scratch)
            finally:
                mv.release()
                scratch.close()
                prim_settled.set()

        # both legs race off-thread so the extent completes at the FIRST
        # landing — the lane worker is not pinned behind a slow primary
        # after its hedge has already delivered (the hedge would otherwise
        # only save failed reads, never slow ones)
        self._pool.submit(_hedge_leg)
        self._pool.submit(_primary_leg)
        while not won.wait(0.05):
            if prim_settled.is_set() and hedge_settled.is_set():
                break
        with lock:
            if state["winner"] is not None:
                return True
        # no winner and both legs settled: either the task already
        # latched an error (nothing left to deliver) or the primary
        # failed terminally and the hedge could not save it
        if state["prim_ok"]:
            return True
        primary_err = state["prim_err"]
        if fallback_ok and not task.errno_:
            stats.add("nr_io_fallback")
            source.read_member_buffered(r.member, r.file_off, piece)
            return True
        raise primary_err

    def _verify_request_checksums(self, source: Source, r: Request,
                                  dest: memoryview) -> None:
        """Checksum-verify one planned request against the landed bytes.
        Plain requests verify their single extent; vectored requests
        verify each destination segment as its own sub-extent (each maps
        to a contiguous file range starting at ``file_off``)."""
        if not r.dest_segs:
            self._verify_chunk_checksums(
                source, r, dest[r.dest_off:r.dest_off + r.length])
            return
        foff = r.file_off
        for d, l in r.dest_segs:
            self._verify_chunk_checksums(
                source, Request(r.member, foff, l, d), dest[d:d + l])
            foff += l

    def _verify_chunk_checksums(self, source: Source, r: Request,
                                piece: memoryview) -> None:
        """Post-landing crc32c verification for one extent: pages that
        carry a checksum (heap header word 7) are recomputed; mismatches
        are re-read up to ``checksum_retries`` times, then latch EBADMSG
        (CORRUPTION).  File offsets must be page-aligned for pages to be
        addressable — misaligned extents are skipped (they are buffered
        legs anyway)."""
        from .scan.heap import PAGE_SIZE, verify_page_checksums
        if r.file_off % PAGE_SIZE:
            return
        bad = verify_page_checksums(piece)
        rereads = int(config.get("checksum_retries"))
        while bad:
            stats.add("nr_csum_fail", len(bad))
            if _trace.active:
                _trace.instant("csum_fail", member=r.member,
                               offset=r.file_off, length=r.length,
                               args={"bad_pages": len(bad)})
            if rereads <= 0:
                first = r.file_off + bad[0] * PAGE_SIZE
                raise StromError(
                    _errno.EBADMSG,
                    f"page checksum mismatch at file offset {first} "
                    f"({len(bad)} bad page(s), re-reads exhausted)")
            rereads -= 1
            stats.add("nr_csum_reread", len(bad))
            stats.add("bytes_verify_reread", len(bad) * PAGE_SIZE)
            for p in bad:
                off = p * PAGE_SIZE
                source.read_member_direct(
                    r.member, r.file_off + off,
                    piece[off:off + PAGE_SIZE])
            bad = verify_page_checksums(piece)

    def _await_native(self, task: DmaTask, eng, native_id: int,
                      write_ctx: Optional[tuple] = None) -> None:
        # *eng* is the engine that accepted the batch — NOT self._native,
        # which a lane scale-out may have swapped since submission
        err: Optional[StromError] = None
        while True:
            try:
                eng.wait(native_id, 500)
                break
            except StromError as e:
                if e.errno == _errno.ETIMEDOUT:
                    if self._abandon_native:
                        # close() gave up waiting; latch and let the pool
                        # thread exit so close cannot hang forever on a
                        # stuck fd (the reference's release path is bounded)
                        err = StromError(_errno.ETIMEDOUT,
                                        "native I/O abandoned at session close")
                        break
                    if task.expired:
                        # watchdog latched ETIMEDOUT already (waiters are
                        # awake); stop pinning a pool thread on the stuck
                        # batch — err stays None so the latch is untouched
                        break
                    continue
                err = e
                break
            except BaseException as e:  # pragma: no cover
                err = StromError(_errno.EIO, f"{type(e).__name__}: {e}")
                break
        if _trace.active:
            # the reaper just saw this batch complete: pull the engine's
            # per-lane event ring so the MEASURED device windows land in
            # the recorder close to their completion
            self._drain_native_trace(eng)
        if write_ctx is not None:
            sink, w_reqs, w_src, n_mirror = write_ctx
            if err is None and not task.expired:
                if n_mirror:
                    stats.add("nr_mirror_write", n_mirror)
            elif err is not None and not self._abandon_native \
                    and not task.expired and not task.errno_:
                # the native lane rejected or failed the write batch but
                # the session is still live: redrive each request through
                # the resilient pool ladder (per-leg retry, mirror
                # degradation, journaling).  One batch ref covers the
                # whole redrive; only the first residual error latches.
                stats.add("nr_backend_fallback")
                pr_warn("native write batch failed (%s); redriving %d "
                        "request(s) on the pool ladder",
                        err, len(w_reqs))
                err = None
                for r in w_reqs:
                    if task.errno_:
                        break
                    stats.add("nr_write_retry")
                    e2 = self._write_request_resilient(task, sink, r, w_src)
                    if e2 is not None and err is None:
                        err = e2
        self._task_put(task, err)

    def _drain_native_trace(self, eng=None) -> int:
        """Drain the native engine's per-lane trace ring into the flight
        recorder (device submit->complete windows, monotonic ns — same
        clock as the Python spans).  No-op on older .so builds."""
        eng = eng if eng is not None else self._native
        if eng is None:
            return 0
        try:
            evs = eng.trace_drain()
        except Exception:   # noqa: BLE001 — observability, not control
            return 0
        for ev in evs:
            _trace.native_event(ev["submit_ns"], ev["complete_ns"],
                                member=ev["member"], lane=ev["lane"],
                                offset=ev["file_off"], length=ev["len"],
                                result=ev["result"])
        return len(evs)

    def _adaptive_cap(self, floor: int, limit: int, member: int = 0) -> int:
        """Current effective coalescing cap from *member*'s adaptive sizer
        (created lazily; recreated when the config bounds change).
        Delegates to the controller (ISSUE 18) — the single writer of
        the effective cap; with ``autotune=off`` the tuner passes the
        static bounds through and this is the PR 4/5 behavior verbatim."""
        return self._tuner.chunk_cap(floor, limit, member)

    def _retire_member_pool(self, member: int) -> None:
        """Knob application (ISSUE 18): drop a member's executor lane so
        the next submit recreates it at the tuned width.  Queued work
        keeps running on the old pool's threads; shutdown(wait=False)
        just stops it accepting new work."""
        with self._lane_lock:
            pool = self._member_pools.pop(member, None)
        if pool is not None:
            pool.shutdown(wait=False)

    def _autotune_scale_lanes(self, want: int) -> None:
        """Engine-rebuild boundary (ISSUE 18): when the tuned window has
        outgrown the native lane count, rebuild the engine with more
        queue pairs (capped at 16, like _ensure_member_lanes).  No-op on
        the Python path or when already wide enough."""
        want = max(1, min(int(want), 16))
        with self._lane_lock:
            if self._native is None or self._native.nlanes() >= want:
                return
            self._scale_out_lanes(want, len(self._members_used) or 1)

    # -- lane scale-out (PR 5) ---------------------------------------------
    def _ensure_member_lanes(self, source: Source) -> None:
        """One-shot at the first striped submit: rebuild the native engine
        with one queue pair per stripe member (member i -> lane i % nlanes)
        so a slow member queues behind itself, never behind siblings — the
        per-NVMe-device blk-mq hardware-queue analog
        (kmod/nvme_strom.c:1201-1223).  An explicit lane count (env
        NSTPU_RINGS or config engine_rings > 0) keeps the operator's
        choice; after sizing, lanes are NUMA-pinned per numa_policy."""
        if self._native is None or self._lanes_sized:
            return
        members = getattr(source, "members", None)
        nmem = len(members) if members else 0
        if nmem <= 1:
            return
        with self._lane_lock:
            if self._lanes_sized or self._native is None:
                return
            self._lanes_sized = True
            try:
                explicit = int(os.environ.get("NSTPU_RINGS", "")) > 0
            except ValueError:
                explicit = int(config.get("engine_rings")) > 0
            want = min(nmem, 16)
            if not explicit and self._native.nlanes() < want:
                self._scale_out_lanes(want, nmem)
            self._pin_lanes(members)

    def _scale_out_lanes(self, nlanes: int, nmem: int) -> None:
        """Swap in a fresh native engine with *nlanes* queue pairs.  Fixed
        buffers are re-registered on the new engine under the fixed lock
        (so concurrent map_buffer registrations can't be lost), stats are
        folded first, and the old engine is retired to _old_engines —
        in-flight batches hold a direct reference and drain there."""
        from . import _native as _nat
        depth = int(config.get("member_queue_depth")) \
            or int(config.get("queue_depth"))
        backend = self.backend_name
        try:
            eng = _nat.NativeEngine(
                backend if backend in ("io_uring", "threadpool") else "auto",
                depth, rings=nlanes)
        except StromError as e:
            pr_warn("lane scale-out to %d lanes failed (%s); keeping the "
                    "single-lane engine", nlanes, e)
            return
        try:
            self._fold_native_stats()
        except StromError:
            pass
        with self._fixed_lock:
            for key, (_slot, backing, cb) in list(self._fixed_regs.items()):
                try:
                    nslot = eng.buf_register(backing.addr, backing.length)
                except Exception:
                    nslot = None
                self._fixed_regs[key] = (-1 if nslot is None else nslot,
                                         backing, cb)
            old, self._native = self._native, eng
        self._old_engines.append(old)
        if _trace.active:
            eng.trace_enable(True)
        self.backend_name = eng.backend_name
        pr_info("engine scaled out: %d lane(s) for %d stripe members "
                "(backend=%s depth=%d)", eng.nlanes(), nmem,
                eng.backend_name, depth)

    def _pin_lanes(self, members) -> None:
        """NUMA-pin each lane's service threads (reaper + workers) to its
        member's local node per ``numa_policy`` — the reference allocates
        DMA buffers device-locally (pgsql/nvme_strom.c:1454-1526); pinning
        the completion path keeps CQ reaping and the landing memcpy on
        local memory.  Unknown topology (no sysfs, node -1) leaves lanes
        floating rather than guessing."""
        policy = str(config.get("numa_policy"))
        if policy == "off" or self._native is None:
            return
        try:
            nlanes = self._native.nlanes()
        except Exception:
            return
        fixed_node = -1
        if policy.startswith("node:"):
            fixed_node = int(policy.split(":", 1)[1])
        pinned = 0
        for lane in range(nlanes):
            node = fixed_node
            if node < 0:
                # auto: pin to the backing-device node of the lane's first
                # member under the member % nlanes mapping (identity when
                # one lane per member)
                from .stripe import lane_members
                served = lane_members(lane, len(members), nlanes)
                if not served:
                    continue
                path = getattr(members[served[0]], "path", None)
                if not path:
                    continue
                try:
                    node = _numa.device_numa_node(path)
                except Exception:
                    node = -1
            if node < 0:
                continue
            try:
                cpus = _numa.node_cpus(node)
            except Exception:
                cpus = []
            if cpus and self._native.lane_pin(lane, cpus):
                pinned += 1
        if pinned:
            pr_info("NUMA: pinned %d/%d lane(s) (policy=%s)",
                    pinned, nlanes, policy)

    def _member_pool(self, member: int) -> ThreadPoolExecutor:
        """Per-member executor lane for the Python path: a quarantined or
        slow member's requests queue on their own workers instead of
        occupying the shared pool ahead of healthy siblings (the Python
        mirror of the native per-member lanes)."""
        pool = self._member_pools.get(member)
        if pool is None:
            with self._lane_lock:
                pool = self._member_pools.get(member)
                if pool is None:
                    width = int(config.get("member_queue_depth")) \
                        or int(config.get("queue_depth"))
                    width = max(1, min(width, 8))
                    if self._tuner.enabled:
                        # tuned submit window doubles as the member's
                        # lane width — the real concurrency bound here
                        width = self._tuner.pool_width(member, width)
                    pool = ThreadPoolExecutor(
                        max_workers=width,
                        thread_name_prefix=f"strom-io-m{member}")
                    self._member_pools[member] = pool
        return pool

    def _submit_pool_requests(self, task: DmaTask, source: Source,
                              reqs: Sequence[Request],
                              dest: memoryview) -> None:
        """Queue planned requests on the Python thread pool (the
        instrumented fallback executor; also the only path for sources
        that override the direct-read leg, i.e. test fakes).  Striped
        sources route each request to its member's own executor lane."""
        multi = len(getattr(source, "members", ())) > 1
        for r in reqs:
            self._task_get(task)
            cur = stats.gauge_add("cur_dma_count", 1)
            stats.gauge_max("max_dma_count", cur)
            stats.count_clock("submit_dma", 0)
            stats.add("total_dma_length", r.length)
            pool = self._member_pool(r.member) if multi else self._pool
            try:
                pool.submit(self._do_request, task, source, r, dest)
            except BaseException as e:
                stats.gauge_add("cur_dma_count", -1)
                self._task_put(task, StromError(_errno.ESHUTDOWN, str(e)))
                raise

    # -- stats + lifecycle -------------------------------------------------
    def _fold_native_stats(self, eng=None) -> dict:
        """Fold a native engine's counter deltas into the global
        registry (returns the raw delta dict).  Called from stat_info and
        from close() — a session must not take its I/O accounting to the
        grave just because nobody snapshotted before it closed.  *eng*
        defaults to the live engine; lane scale-out passes retired ones."""
        eng = eng if eng is not None else self._native
        d = eng.stats_delta()
        # nr/clk_ssd2dev + wait are counted per *Python* task already;
        # resubmit/sq_full ride the reference's spare debug counters
        stats.merge_native({
            "nr_submit_dma": d.get("nr_submit_dma", 0),
            "clk_submit_dma": d.get("clk_submit_dma", 0),
            "total_dma_length": d.get("total_dma_length", 0),
            "nr_enter_dma": d.get("nr_enter_dma", 0),
            "nr_debug1": d.get("nr_resubmit", 0),
            "nr_debug2": d.get("nr_sq_full", 0),
            "nr_debug4": d.get("nr_fixed_dma", 0),
            "occ_integral_ns": d.get("occ_integral_ns", 0),
            "occ_busy_ns": d.get("occ_busy_ns", 0),
        })
        # per-member deltas fold into the registry the same way
        used = sorted(self._members_used)
        for m, (nreq, nbytes, ns) in eng.member_stats_delta(used).items():
            stats.member_add(m, nbytes, ns, n=nreq)
        # service-latency histograms: fold the native deltas and feed the
        # mean service time to the adaptive sizers (native requests never
        # pass through _do_request, so this is their only observation
        # path).  Per-member histograms feed each member's own sizer; an
        # older .so without them falls back to the global mean for all.
        hd = eng.lat_hist_delta()
        if hd and any(hd):
            stats.merge_native_hist(hd)
            fed = False
            for m, h in eng.member_lat_hist_delta(used).items():
                stats.merge_member_hist(m, h)
                # suspect detection covers the native path too: the lane
                # reaper's per-member latency view folds into the health
                # machine's own histograms (PR 6)
                self._member_health.observe_hist(m, h)
                total = sum(h)
                if not total:
                    continue
                avg = sum(((1 << b) + ((1 << b) >> 1)) * c
                          for b, c in enumerate(h)) // total
                szr = self._chunk_sizers.get(m)
                if szr is not None:
                    szr.observe(avg)
                    fed = True
            if not fed and self._chunk_sizers:
                total = sum(hd)
                avg = sum(((1 << b) + ((1 << b) >> 1)) * c
                          for b, c in enumerate(hd)) // total
                for szr in self._chunk_sizers.values():
                    szr.observe(avg)
        # per-member queue-occupancy integrals (lane depth visibility)
        for m, (dint, dbusy) in eng.member_occ_delta(used).items():
            stats.member_occ_add(m, dint, dbusy)
        return d

    def stat_info(self, *, debug: bool = False):
        snap = None
        if self._native is not None:
            d = self._fold_native_stats()
            snap = stats.snapshot(debug=debug)
            # gauges combine at snapshot time (never merged into the registry)
            snap.counters["cur_dma_count"] += d.get("cur_dma_count", 0)
            snap.counters["max_dma_count"] = max(snap.counters["max_dma_count"],
                                                 d.get("max_dma_count", 0))
        return snap if snap is not None else stats.snapshot(debug=debug)

    def close(self, timeout: float = 30.0) -> List[int]:
        """Close the session: wait out running tasks, reap retained failures.

        Returns task ids that were force-reaped with errors (the reference
        logs these on fd close, kmod/nvme_strom.c:2138-2166)."""
        with self._id_lock:
            # atomic test-and-set: two racing closers must not both run
            # the teardown (double engine destroy, double pool shutdown)
            if self._closed:
                return []
            self._closed = True
        deadline = time.monotonic() + timeout
        reaped: List[int] = []
        for s, cv in enumerate(self._slot_cv):
            with cv:
                while any(t.state == DmaTaskState.RUNNING
                          for t in self._slots[s].values()):
                    remain = deadline - time.monotonic()
                    if remain <= 0 or not cv.wait(remain):
                        break
                for tid, t in list(self._slots[s].items()):
                    if t.state == DmaTaskState.FAILED:
                        reaped.append(tid)
                    del self._slots[s][tid]
        self._abandon_native = True  # bound pool shutdown on stuck native I/O
        self._watchdog_stop.set()
        self._watchdog.join(timeout=2.0)
        self._canary_stop.set()
        self._canary.join(timeout=2.0)
        self._scrubber.stop()
        self._tuner.stop()
        self._pool.shutdown(wait=True)
        if self._canary_buf is not None:
            try:
                self._canary_buf.close()
            except BufferError:
                pass  # a late canary still holds a view; dropped with it
        # swap the pool map out under the swap lock (scale-out mutates it
        # there), but shut the pools down outside it: a draining worker
        # may need the lane lock to finish
        with self._lane_lock:
            pools, self._member_pools = self._member_pools, {}
        for p in pools.values():
            p.shutdown(wait=True)
        # detach close hooks from long-lived (pool) buffers so a closed
        # session is not pinned in their callback lists; the engine close
        # below frees every kernel-side fixed slot wholesale
        with self._fixed_lock:
            regs, self._fixed_regs = list(self._fixed_regs.values()), {}
        for _slot, backing, cb in regs:
            try:
                backing.remove_close_cb(cb)
            except Exception:
                pass
        if self._native is not None:
            self._native.reap(timeout_ms=int(timeout * 1000))
            if _trace.active:
                self._drain_native_trace()
            try:
                self._fold_native_stats()
            except StromError:
                pass
            self._native.close()
        # engines retired by lane scale-out: every batch they accepted has
        # drained (pool shutdown above joins the awaiters), so reap any
        # residue, fold their remaining counters, and free them
        with self._lane_lock:
            olds, self._old_engines = self._old_engines, []
        for old in olds:
            try:
                old.reap(timeout_ms=2000)
                if _trace.active:
                    self._drain_native_trace(old)
                self._fold_native_stats(old)
                old.close()
            except Exception:
                pass
        return reaped

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
