"""SQL front-end: a parsed SELECT subset over heap tables.

The reference ships as a PostgreSQL extension — SQL *is* its user
interface (`pgsql/nvme_strom.c:941-979` hands tuples to the SQL
executor).  This module gives the TPU framework the same face for the
scan tier it implements: a hand-rolled tokenizer + recursive-descent
parser (no dependencies) maps a SELECT statement onto the
:class:`.query.Query` builder, so every access path the planner knows
(direct / vfs / index sidecars), both kernels, and the mesh mode are
reachable from a SQL string.

Supported subset (one fact table, one terminal — the Query contract):

    SELECT select_list FROM <name>
      [[INNER|LEFT|SEMI|ANTI] JOIN <dim> ON cN = <dim>.cM]...
      [WHERE cond [AND cond]...]
      [GROUP BY cN[, cM]]
      [HAVING agg cmp literal [AND ...]]
      [ORDER BY cN [ASC|DESC]]
      [LIMIT n [OFFSET m]]

JOIN binds a dimension table supplied via ``tables={"dim": (path,
schema)}`` (on-disk heap; the engine streams it in bounded passes when
it exceeds ``join_broadcast_max``) and serves both faces: aggregates —
``COUNT(*)``, ``SUM(cN)`` over fact columns, ``SUM(dim.cK)`` over the
matched build payload — or, with plain columns in the SELECT list, the
materialized rows.  TWO OR MORE JOIN clauses form a STAR statement
(round 5): every dimension probes in the same fused scan kernel
(broadcast-sized dims only; aggregates gain ``AVG(dim.cK)`` and
``SUM(expr)``, the row face serves any fact columns + one payload
column per dimension, LEFT dims add a ``matched_<dim>`` indicator).

    select_list := [DISTINCT] '*' | item [AS name] (',' item [AS name])*
    item  := cN | COUNT(*) | COUNT(DISTINCT cN)
           | SUM(cN|expr) | AVG(cN|expr) | MIN(cN) | MAX(cN)
    -- SELECT DISTINCT cols == GROUP BY the select list (keys only);
    -- ORDER BY takes cN[, cM] (later keys break ties) outside GROUP BY
    where := term (OR term)* ; term := factor (AND factor)*
    factor := NOT factor | '(' where ')' | cond   -- SQL precedence
    cond  := expr cmp expr
           | cN BETWEEN lit AND lit | cN IN (lit[, lit]...)
    expr  := cN | number | '(' expr ')' | -expr
           | expr (+|-|*|/) expr        -- usual precedence
    cmp   := = | == | != | <> | < | <= | > | >=
    literal := number | 'string'   (strings need a dictionary sidecar)

Expression semantics are EXACT, never approximate: int arithmetic runs
at int32 (the storage width — wraparound is the storage semantics),
float math at float32, mixed operands promote to float32; int/int
division is EINVAL (PostgreSQL truncates — returning the float answer
would be silent drift), as are uint32 operands and string columns in
arithmetic.  One DOCUMENTED divergence: float division follows IEEE 754
(``x / 0.0`` is ±inf, ``0.0 / 0.0`` is NaN, and NaN comparisons are
false) where PostgreSQL raises ``division_by_zero`` — a per-row raise
cannot live inside the fused kernel, and a silent wrong answer is
worse than the standard float answer.  Plain ``cN cmp literal`` leaves keep their structured
form, so index promotion and string translation are unchanged;
expression aggregates are scalar-only (no GROUP BY) and fuse into the
scan kernel (``Query.aggregate_exprs``).

Columns are named ``c0..cN-1`` (the CLI convention).  The mapping is
exact, never approximate: a statement outside the subset raises EINVAL
with a message naming the unsupported construct — silent semantic
drift from real SQL is the one unforgivable failure mode of a facade.

Mapping (each SQL shape -> the Query terminal that serves it):

* plain columns                  -> ``select(cols)`` (LIMIT/OFFSET ride
  the early DMA cut-off)
* COUNT(*) / SUM / AVG, no GROUP -> ``aggregate(cols=...)``
* sole MIN(c) / MAX(c), no GROUP -> ``top_k(c, 1)`` (index-served when
  a sidecar is fresh)
* sole COUNT(DISTINCT c)         -> ``count_distinct(c)``
* GROUP BY c[, c2]               -> ``group_by_cols`` (value-keyed,
  keys discovered; HAVING composes)
* ORDER BY c[, c2] [DESC]        -> ``order_by`` (sidecar-served when
  fresh; other selected columns fetched by position); ORDER BY an
  aggregate + LIMIT on grouped results = top-N groups
* SELECT DISTINCT cols           -> ``group_by_cols`` keys only
* AS name                        -> output relabeling (after string
  decode)
* :func:`create_table_as`        -> materialize any result as a new
  requeryable heap table (CLI ``--sql-create``)
* WHERE: the first index-capable LEAF of a top-level AND becomes a
  STRUCTURED filter (``where_eq`` / ``where_range`` / ``where_in`` —
  the planner can ride a sidecar); the rest of the tree — remaining
  conjuncts, OR subtrees — composes as the residual predicate the
  index path RECHECKS (Index Cond + Filter).
"""

from __future__ import annotations

import os
import re
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..api import StromError
from .query import Query

__all__ = ["parse_sql", "sql_query", "create_table_as"]

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<str>'[^']*')
    | (?P<num>\d+\.\d+(?:[eE][+-]?\d+)?|\d+)
    | (?P<name>[A-Za-z_][A-Za-z_0-9]*)
    | (?P<op><=|>=|!=|<>|==|=|<|>|\(|\)|,|\*|\.|\+|-|/|%)
    )""", re.VERBOSE)

_AGGS = ("count", "sum", "avg", "min", "max")
_CMPS = ("=", "==", "!=", "<>", "<", "<=", ">", ">=")


def _tokenize(sql: str) -> List[Tuple[str, str]]:
    out, pos = [], 0
    while pos < len(sql):
        m = _TOKEN.match(sql, pos)
        if m is None:
            if sql[pos:].strip() == "":
                break
            raise StromError(22, f"SQL: cannot tokenize at "
                                 f"{sql[pos:pos + 20]!r}")
        pos = m.end()
        if m.group("str") is not None:
            out.append(("str", m.group("str")[1:-1]))
        elif m.group("num") is not None:
            out.append(("num", m.group("num")))
        elif m.group("name") is not None:
            out.append(("name", m.group("name")))
        else:
            out.append(("op", m.group("op")))
    return out


class _P:
    """Token cursor with the small helpers a recursive descent needs."""

    def __init__(self, toks: List[Tuple[str, str]]):
        self.toks = toks
        self.i = 0

    def peek(self) -> Optional[Tuple[str, str]]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> Tuple[str, str]:
        t = self.peek()
        if t is None:
            raise StromError(22, "SQL: unexpected end of statement")
        self.i += 1
        return t

    def kw(self, word: str) -> bool:
        """Consume *word* (case-insensitive keyword) if next."""
        t = self.peek()
        if t and t[0] == "name" and t[1].lower() == word:
            self.i += 1
            return True
        return False

    def expect_op(self, op: str) -> None:
        t = self.next()
        if t != ("op", op):
            raise StromError(22, f"SQL: expected {op!r}, got {t[1]!r}")

    def expect_kw(self, word: str) -> None:
        if not self.kw(word):
            t = self.peek()
            raise StromError(22, f"SQL: expected {word.upper()}, got "
                                 f"{t[1] if t else 'end'!r}")


def _col(tok: Tuple[str, str], n_cols: int) -> int:
    kind, v = tok
    m = re.fullmatch(r"[cC](\d+)", v) if kind == "name" else None
    if not m:
        raise StromError(22, f"SQL: expected a column (c0..c{n_cols - 1})"
                             f", got {v!r}")
    c = int(m.group(1))
    if not 0 <= c < n_cols:
        raise StromError(22, f"SQL: column c{c} out of range "
                             f"(table has {n_cols})")
    return c


class _Str(str):
    """Marker for a parsed SQL string literal ('...') — translated to
    dictionary codes before the numeric machinery sees it."""


def _lit(tok: Tuple[str, str]):
    kind, v = tok
    if kind == "str":
        return _Str(v)
    if kind != "num":
        raise StromError(22, f"SQL: expected a literal, got {v!r}")
    return float(v) if ("." in v or "e" in v or "E" in v) else int(v)


def _plit(p: "_P"):
    """A possibly-negated literal (the tokenizer emits '-' as an
    operator so expressions can subtract)."""
    if p.peek() == ("op", "-"):
        p.next()
        v = _lit(p.next())
        if isinstance(v, _Str):
            raise StromError(22, "SQL: cannot negate a string literal")
        return -v
    return _lit(p.next())


# ---------------------------------------------------------------------------
# Arithmetic expressions (round 5): cN, literals, + - * /, parentheses
# ---------------------------------------------------------------------------
#
# Trees are picklable tuples — ("col", c) | ("lit", v) | ("neg", e) |
# ("bin", op, l, r) — so worker processes can rebuild them, and the SAME
# evaluator serves WHERE leaves and aggregate arguments.  Semantics are
# exact, never approximate: int arithmetic runs at int32 (the storage
# width — wraparound is the documented storage semantics, like the
# kernels' sums), float math at float32, mixed operands promote to
# float32, and integer/integer division is EINVAL (PostgreSQL truncates
# int division; silently returning the float answer would be semantic
# drift, so this subset only serves `/` when a float operand makes the
# answer SQL's answer).

_EXPR_DTS = (np.dtype(np.int32), np.dtype(np.float32))


def _parse_expr(p: "_P", n_cols: int):
    """expr := term (('+'|'-') term)* ; term := factor (('*'|'/'|'%')
    factor)* ; factor := ['-'] atom ; atom := cN | number | '(' expr ')'
    """
    def atom():
        t = p.peek()
        if t == ("op", "("):
            p.next()
            e = add()
            p.expect_op(")")
            return e
        if t is not None and t[0] in ("num", "str"):
            return ("lit", _lit(p.next()))
        return ("col", _col(p.next(), n_cols))

    def factor():
        if p.peek() == ("op", "-"):
            p.next()
            f = factor()
            if f[0] == "lit" and not isinstance(f[1], _Str):
                return ("lit", -f[1])
            return ("neg", f)
        return atom()

    def term():
        e = factor()
        while p.peek() in (("op", "*"), ("op", "/"), ("op", "%")):
            op = p.next()[1]
            if op == "%":
                raise StromError(22, "SQL: the modulo operator is "
                                     "outside this subset")
            e = ("bin", op, e, factor())
        return e

    def add():
        e = term()
        while p.peek() in (("op", "+"), ("op", "-")):
            op = p.next()[1]
            e = ("bin", op, e, term())
        return e

    return add()


def _expr_info(e, schema) -> Tuple[np.dtype, set]:
    """(result dtype, referenced columns) of an expression tree, raising
    EINVAL for shapes outside the subset (strings in arithmetic, uint32
    operands, int/int division, out-of-int32 literals)."""
    k = e[0]
    if k == "col":
        dt = schema.col_dtype(e[1])
        if dt not in _EXPR_DTS and dt != np.dtype(np.uint32) \
                and dt.kind not in "iuf":
            raise StromError(22, f"SQL: c{e[1]} ({dt}) in an expression")
        return dt, {e[1]}
    if k == "lit":
        v = e[1]
        if isinstance(v, _Str):
            raise StromError(22, "SQL: string literals cannot appear in "
                                 "arithmetic")
        if isinstance(v, int):
            if not -(1 << 31) <= v < (1 << 31):
                raise StromError(22, f"SQL: integer literal {v} outside "
                                     f"int32 in an expression")
            return np.dtype(np.int32), set()
        return np.dtype(np.float32), set()
    if k == "neg":
        dt, cs = _expr_info(e[1], schema)
        if dt == np.dtype(np.uint32):
            raise StromError(22, "SQL: negating a uint32 column is "
                                 "outside this subset")
        return dt, cs
    _k, op, l, r = e
    ld, lc = _expr_info(l, schema)
    rd, rc = _expr_info(r, schema)
    if np.dtype(np.uint32) in (ld, rd):
        raise StromError(22, "SQL: uint32 columns in arithmetic are "
                             "outside this subset (no SQL unsigned "
                             "type to map the wraparound onto)")
    if op == "/":
        if ld.kind != "f" and rd.kind != "f":
            raise StromError(22, "SQL: integer / integer is outside "
                                 "this subset (PostgreSQL truncates; "
                                 "use a float operand for float "
                                 "division)")
        return np.dtype(np.float32), lc | rc
    if np.dtype(np.float32) in (ld, rd):
        return np.dtype(np.float32), lc | rc
    return np.dtype(np.int32), lc | rc


def _eval_expr(e, cols):
    """jnp evaluation of an expression tree over decoded columns —
    dtype rules exactly as :func:`_expr_info` documents (the numpy
    oracle in the tests mirrors this step for step)."""
    import jax.numpy as jnp
    k = e[0]
    if k == "col":
        return cols[e[1]]
    if k == "lit":
        v = e[1]
        return jnp.float32(v) if isinstance(v, float) else jnp.int32(v)
    if k == "neg":
        return -_eval_expr(e[1], cols)
    _k, op, l, r = e
    a, b = _eval_expr(l, cols), _eval_expr(r, cols)
    if op == "/" or a.dtype == jnp.float32 or b.dtype == jnp.float32:
        a, b = a.astype(jnp.float32), b.astype(jnp.float32)
    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    return a / b


def _expr_str(e) -> str:
    k = e[0]
    if k == "col":
        return f"c{e[1]}"
    if k == "lit":
        return str(e[1])
    if k == "neg":
        return f"-{_expr_str(e[1])}"
    _k, op, l, r = e
    return f"({_expr_str(l)} {op} {_expr_str(r)})"


class _Item:
    """One select-list item: ("col", c), ("agg", fn, c|None, distinct),
    or ("agge", fn, expression tree) for SUM/AVG over arithmetic;
    ``table`` is None for fact columns, a dimension name for qualified
    ``dim.cK`` references."""

    def __init__(self, kind, fn=None, col=None, distinct=False,
                 label="", table=None, expr=None):
        self.kind, self.fn, self.col = kind, fn, col
        self.distinct, self.label, self.table = distinct, label, table
        self.expr = expr     # "agge": the argument tree
        self.alias = None   # AS name: relabels the output


def _colref(p: _P, n_cols: int) -> Tuple[Optional[str], int]:
    """(table|None, col): a bare fact column (validated now) or a
    qualified ``name.cK`` reference (validated at binding)."""
    t = p.next()
    if t[0] == "name" and p.peek() == ("op", "."):
        p.next()
        nxt = p.next()
        m = re.fullmatch(r"[cC](\d+)", nxt[1]) if nxt[0] == "name" \
            else None
        if not m:
            raise StromError(22, f"SQL: expected {t[1]}.cK, got "
                                 f"{nxt[1]!r}")
        return t[1], int(m.group(1))
    return None, _col(t, n_cols)


def _parse_select_list(p: _P, n_cols: int) -> Optional[List[_Item]]:
    """None = ``*``."""
    if p.peek() == ("op", "*"):
        p.next()
        return None
    items = []
    while True:
        t = p.peek()
        if t and t[0] == "name" and t[1].lower() in _AGGS \
                and self_is_call(p):
            p.next()
            fn = t[1].lower()
            p.next()   # the '('
            distinct = False
            expr = None
            if p.peek() == ("op", "*"):
                p.next()
                if fn != "count":
                    raise StromError(22, f"SQL: {fn.upper()}(*) is not "
                                         f"a thing; name a column")
                tbl, col = None, None
                label = "count(*)"
            else:
                if p.kw("distinct"):
                    distinct = True
                    if fn != "count":
                        raise StromError(22, "SQL: DISTINCT only under "
                                             "COUNT in this subset")
                t2 = p.peek()
                qualified = (t2 is not None and t2[0] == "name"
                             and p.i + 1 < len(p.toks)
                             and p.toks[p.i + 1] == ("op", "."))
                if distinct or qualified:
                    tbl, col = _colref(p, n_cols)
                else:
                    e = _parse_expr(p, n_cols)
                    if e[0] == "col":
                        tbl, col = None, e[1]
                    elif fn not in ("sum", "avg"):
                        raise StromError(22, f"SQL: {fn.upper()} over "
                                             f"an expression is outside "
                                             f"this subset (SUM/AVG "
                                             f"take arithmetic)")
                    else:
                        tbl, col, expr = None, None, e
                if expr is not None:
                    label = f"{fn}({_expr_str(expr)})"
                else:
                    base = f"{tbl}.c{col}" if tbl else f"c{col}"
                    label = (f"{fn}(distinct {base})" if distinct
                             else f"{fn}({base})")
            p.expect_op(")")
            if expr is not None:
                items.append(_Item("agge", fn, label=label, expr=expr))
            else:
                items.append(_Item("agg", fn, col, distinct, label,
                                   tbl))
        else:
            tbl, c = _colref(p, n_cols)
            label = f"{tbl}.c{c}" if tbl else f"c{c}"
            items.append(_Item("col", col=c, label=label, table=tbl))
        if p.kw("as"):
            alias = p.next()
            if alias[0] != "name":
                raise StromError(22, "SQL: AS needs a name")
            items[-1].alias = alias[1]
        if p.peek() == ("op", ","):
            p.next()
            continue
        return items


def self_is_call(p: _P) -> bool:
    """Lookahead: the NAME at the cursor is followed by '('."""
    return p.i + 1 < len(p.toks) and p.toks[p.i + 1] == ("op", "(")


def _parse_cond_leaf(p: _P, n_cols: int) -> tuple:
    """One comparison: ("cmp", col, op, lit) | ("between", col, lo, hi)
    | ("in", col, [lits]) — or, when either side carries arithmetic or
    a second column, ("cmpe", lexpr, op, rexpr).  The simple shapes
    keep their dedicated forms so index promotion and string-dictionary
    translation stay exactly as before."""
    # a bare string literal can only open `'lit' cmp cN` — it cannot
    # start an expression
    if p.peek() is not None and p.peek()[0] == "str":
        lit = _lit(p.next())
        op = p.next()
        if op[0] != "op" or op[1] not in _CMPS:
            raise StromError(22, f"SQL: expected comparison, got "
                                 f"{op[1]!r}")
        c = _col(p.next(), n_cols)
        flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
        return ("cmp", c, flip.get(op[1], op[1]), lit)
    left = _parse_expr(p, n_cols)
    if left[0] == "col":
        c = left[1]
        if p.kw("is"):
            neg = p.kw("not")
            p.expect_kw("null")
            return ("isnull", c, neg)
        if p.kw("between"):
            lo = _plit(p)
            p.expect_kw("and")
            hi = _plit(p)
            return ("between", c, lo, hi)
        if p.kw("in"):
            p.expect_op("(")
            lits = [_plit(p)]
            while p.peek() == ("op", ","):
                p.next()
                lits.append(_plit(p))
            p.expect_op(")")
            return ("in", c, lits)
    op = p.next()
    if op[0] != "op" or op[1] not in _CMPS:
        raise StromError(22, f"SQL: expected comparison, got {op[1]!r}")
    if p.peek() is not None and p.peek()[0] == "str":
        right = ("lit", _lit(p.next()))
    else:
        right = _parse_expr(p, n_cols)
    if left[0] == "col" and right[0] == "lit":
        return ("cmp", left[1], op[1], right[1])
    if left[0] == "lit" and right[0] == "col":   # literal cmp col: flip
        flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
        return ("cmp", right[1], flip.get(op[1], op[1]), left[1])
    return ("cmpe", left, op[1], right)


def _parse_where(p: _P, n_cols: int):
    """Condition TREE with SQL precedence (AND binds tighter than OR;
    parentheses group): ("leaf", cond) | ("and", [t..]) | ("or", [t..]).
    """
    def factor():
        if p.kw("not"):
            return ("not", [factor()])
        if p.peek() == ("op", "("):
            # '(' is ambiguous: a condition group OR an arithmetic
            # subexpression ("(c0 + c1) > 5").  Try the group reading
            # first and backtrack to the expression leaf on failure.
            mark = p.i
            try:
                p.next()
                t = expr()
                p.expect_op(")")
                return t
            except StromError:
                p.i = mark
        return ("leaf", _parse_cond_leaf(p, n_cols))

    def term():
        fs = [factor()]
        while p.kw("and"):
            fs.append(factor())
        return fs[0] if len(fs) == 1 else ("and", fs)

    def expr():
        ts = [term()]
        while p.kw("or"):
            ts.append(term())
        return ts[0] if len(ts) == 1 else ("or", ts)

    return expr()


def _parse_having(p: _P, n_cols: int) -> List[tuple]:
    """[(fn, col|None, op, lit)] — aggregate comparisons only."""
    out = []
    while True:
        t = p.next()
        if t[0] != "name" or t[1].lower() not in _AGGS:
            raise StromError(22, "SQL: HAVING takes aggregate "
                                 "comparisons (COUNT/SUM/AVG/MIN/MAX)")
        fn = t[1].lower()
        p.expect_op("(")
        if p.peek() == ("op", "*"):
            p.next()
            col = None
            if fn != "count":
                raise StromError(22, f"SQL: {fn.upper()}(*) in HAVING")
        else:
            col = _col(p.next(), n_cols)
        p.expect_op(")")
        op = p.next()
        if op[0] != "op" or op[1] not in _CMPS:
            raise StromError(22, "SQL: HAVING needs a comparison")
        lit = _plit(p)
        if isinstance(lit, _Str):
            raise StromError(22, "SQL: HAVING against a string literal "
                                 "is outside this subset (aggregates "
                                 "compare numerically)")
        out.append((fn, col, op[1], lit))
        if p.kw("and"):
            continue
        return out


def _dict_cache(source):
    """Per-statement dictionary loader: ``get(col) -> StringDict|None``
    (missing sidecar = a plain numeric column; a STALE sidecar raises
    EIO loudly — stale codes decode to WRONG strings)."""
    cache: dict = {}

    def get(c: int):
        if c in cache:
            return cache[c]
        d = None
        if isinstance(source, str):
            from .strings import load_dict
            try:
                d = load_dict(source, c)
            except FileNotFoundError:
                d = None
        cache[c] = d
        return d
    return get


def _translate_cond(cond, dicts, schema=None) -> Optional[tuple]:
    """One leaf onto dictionary-code space (see the module docstring);
    None = the leaf is vacuously TRUE (``!= 'absent string'``)."""
    if cond[0] == "isnull":
        _k, c, neg = cond
        if schema is not None and not schema.col_nullable(c):
            # IS NULL on a non-nullable column: constant-fold exactly
            # (always false / always true)
            return None if neg else ("in", c, [])
        return cond
    if cond[0] == "cmpe":
        # expression comparison: validate the subset here (both sides
        # type-check, no dictionary columns — codes are ranks, and
        # arithmetic over ranks would be silent nonsense)
        _k, l, op, r = cond
        ld, lc = _expr_info(l, schema)
        rd, rc = _expr_info(r, schema)
        for cc in sorted(lc | rc):
            if dicts(cc) is not None:
                raise StromError(22, f"SQL: c{cc} (string column) in an "
                                     f"expression comparison")
        if np.dtype(np.uint32) in (ld, rd) and ld != rd:
            raise StromError(22, "SQL: comparing uint32 with a "
                                 "different type is outside this "
                                 "subset")
        return cond
    has_str = any(isinstance(x, _Str) for x in
                  (cond[2:] if cond[0] != "in" else cond[2]))
    c = cond[1]
    if not has_str:
        if dicts(c) is not None:
            raise StromError(22, f"SQL: comparing c{c} (string "
                                 f"column) with a number — use a "
                                 f"'string' literal")
        return cond
    d = dicts(c)
    if d is None:
        raise StromError(22, f"SQL: string literal against c{c}, "
                             f"which has no string dictionary "
                             f"(scan.strings.save_dict builds one)")
    vals = np.asarray(d.values)
    if cond[0] == "cmp":
        _k, _c, op, lit = cond
        if not isinstance(lit, _Str):
            raise StromError(22, f"SQL: comparing c{c} (string "
                                 f"column) with a number")
        if op in ("=", "=="):
            code = d.code_of(lit)
            return ("cmp", c, "=", code) if code is not None                 else ("in", c, [])
        if op in ("!=", "<>"):
            code = d.code_of(lit)
            return ("cmp", c, "!=", code) if code is not None else None
        if op == "<":
            hi = int(np.searchsorted(vals, str(lit), "left")) - 1
            return ("between", c, 0, hi) if hi >= 0 else ("in", c, [])
        if op == "<=":
            hi = int(np.searchsorted(vals, str(lit), "right")) - 1
            return ("between", c, 0, hi) if hi >= 0 else ("in", c, [])
        if op == ">":
            lo = int(np.searchsorted(vals, str(lit), "right"))
            return ("between", c, lo, len(vals) - 1)                 if lo < len(vals) else ("in", c, [])
        lo = int(np.searchsorted(vals, str(lit), "left"))
        return ("between", c, lo, len(vals) - 1)             if lo < len(vals) else ("in", c, [])
    if cond[0] == "between":
        _k, _c, lo, hi = cond
        if not (isinstance(lo, _Str) and isinstance(hi, _Str)):
            raise StromError(22, f"SQL: BETWEEN on c{c} mixes "
                                 f"string and numeric bounds")
        clo, chi = d.range_codes(lo, hi)
        return ("between", c, clo, chi)             if clo is not None and chi is not None and clo <= chi             else ("in", c, [])
    _k, _c, lits = cond
    if not all(isinstance(x, _Str) for x in lits):
        raise StromError(22, f"SQL: IN list on c{c} mixes "
                             f"strings and numbers")
    codes = [d.code_of(x) for x in lits]
    return ("in", c, [x for x in codes if x is not None])


def _translate_tree(tree, dicts, schema=None):
    """Translate every leaf; vacuously-true leaves simplify out (a true
    child erases an OR, drops from an AND).  None = no filter at all."""
    if tree is None:
        return None
    kind = tree[0]
    if kind == "leaf":
        cond = _translate_cond(tree[1], dicts, schema)
        return None if cond is None else ("leaf", cond)
    kids = [_translate_tree(t, dicts, schema) for t in tree[1]]
    if kind == "not":
        # NOT over a vacuously-true child is vacuously FALSE: keep a
        # match-nothing leaf so the truth value survives simplification
        return ("not", kids) if kids[0] is not None \
            else ("leaf", ("in", 0, []))
    if kind == "or" and any(k is None for k in kids):
        return None
    kids = [k for k in kids if k is not None]
    if not kids:
        return None
    return kids[0] if len(kids) == 1 else (kind, kids)


def _decode_strings(out: dict, dicts) -> dict:
    """Result-edge decode: labels naming a dictionary column (``cN``,
    ``min(cN)``, ``max(cN)``) turn codes back into strings."""
    for label, v in list(out.items()):
        m = re.fullmatch(r"(?:(min|max)\()?c(\d+)\)?", label)
        if not m:
            continue
        d = dicts(int(m.group(2)))
        if d is None:
            continue
        if v is None:
            continue
        arr = np.asarray(v)
        if arr.ndim == 0:
            out[label] = d.decode([int(arr)])[0]
        else:
            out[label] = d.decode(arr)
    return out


def _cmp_np(op: str):
    return {"=": np.equal, "==": np.equal, "!=": np.not_equal,
            "<>": np.not_equal, "<": np.less, "<=": np.less_equal,
            ">": np.greater, ">=": np.greater_equal}[op]


def _expr_cols_of(e) -> set:
    if e[0] == "col":
        return {e[1]}
    if e[0] == "lit":
        return set()
    if e[0] == "neg":
        return _expr_cols_of(e[1])
    return _expr_cols_of(e[2]) | _expr_cols_of(e[3])


def _null_mask(cols, refs):
    """OR of the NULL masks of every referenced nullable column — the
    rows where a comparison is UNKNOWN rather than false (SQL 3VL).
    None when no referenced column is nullable (nothing can be
    unknown, the common all-NOT-NULL schema)."""
    u = None
    for c in refs:
        n = getattr(cols, "nulls", {}).get(c)
        if n is not None:
            u = n if u is None else (u | n)
    return u


def _not_null(cols, refs, mask):
    """SQL comparison semantics: NULL cmp x is never true — AND away
    the NULL rows of every referenced nullable column."""
    u = _null_mask(cols, refs)
    return mask if u is None else mask & ~u


def _leaf_mask(cond, cols):
    """jnp mask for one leaf condition (NULL rows of referenced
    nullable columns never match, per SQL three-valued logic)."""
    import jax.numpy as jnp
    if cond[0] == "isnull":
        _k, c, neg = cond
        n = getattr(cols, "nulls", {}).get(c)
        if n is None:              # untranslated non-nullable leaf
            base = jnp.zeros(cols[c].shape, bool)
            return ~base if neg else base
        return ~n if neg else n
    if cond[0] == "cmpe":
        _k, l, op, r = cond
        a, b = _eval_expr(l, cols), _eval_expr(r, cols)
        if a.dtype != b.dtype:     # validated: only int/float mixing
            a, b = a.astype(jnp.float32), b.astype(jnp.float32)
        fns = {"=": jnp.equal, "==": jnp.equal,
               "!=": jnp.not_equal, "<>": jnp.not_equal,
               "<": jnp.less, "<=": jnp.less_equal,
               ">": jnp.greater, ">=": jnp.greater_equal}
        return _not_null(cols, _expr_cols_of(l) | _expr_cols_of(r),
                         fns[op](a, b))
    if cond[0] == "cmp":
        _, c, op, lit = cond
        fns = {"=": jnp.equal, "==": jnp.equal,
               "!=": jnp.not_equal, "<>": jnp.not_equal,
               "<": jnp.less, "<=": jnp.less_equal,
               ">": jnp.greater, ">=": jnp.greater_equal}
        return _not_null(cols, {c}, fns[op](cols[c], lit))
    if cond[0] == "between":
        _, c, lo, hi = cond
        return _not_null(cols, {c}, (cols[c] >= lo) & (cols[c] <= hi))
    _, c, lits = cond
    import jax.numpy as jnp
    one = jnp.zeros(cols[c].shape, bool)
    for v in lits:
        one = one | (cols[c] == v)
    return _not_null(cols, {c}, one)


def _leaf_unknown(cond, cols):
    """UNKNOWN mask for one leaf: rows where a referenced nullable
    column is NULL.  IS [NOT] NULL is the one predicate that is never
    unknown.  None = no row can be unknown."""
    if cond[0] == "isnull":
        return None
    if cond[0] == "cmpe":
        refs = _expr_cols_of(cond[1]) | _expr_cols_of(cond[3])
    else:
        refs = {cond[1]}
    return _null_mask(cols, refs)


def _or_unknown(a, b):
    if a is None:
        return b
    return a if b is None else (a | b)


def _tree_masks(tree, cols):
    """Kleene 3VL masks for a subtree: ``(true, unknown)``, with
    *unknown* None when no NULL can reach the subtree.  FALSE is
    whatever is neither.  NOT swaps TRUE/FALSE and keeps UNKNOWN
    unknown — a plain ``~true`` wrongly admitted NULL rows; AND is
    false if any operand is false, OR is true if any operand is true
    (truth dominates unknown on the side that decides the row)."""
    if tree[0] == "leaf":
        return _leaf_mask(tree[1], cols), _leaf_unknown(tree[1], cols)
    if tree[0] == "not":
        t, u = _tree_masks(tree[1][0], cols)
        return (~t if u is None else ~t & ~u), u
    t, u = _tree_masks(tree[1][0], cols)
    for kid in tree[1][1:]:
        t2, u2 = _tree_masks(kid, cols)
        if tree[0] == "and":
            if u is not None or u2 is not None:
                f1 = ~t if u is None else ~t & ~u
                f2 = ~t2 if u2 is None else ~t2 & ~u2
                u = _or_unknown(u, u2) & ~f1 & ~f2
            t = t & t2
        else:
            t = t | t2
            if u is not None or u2 is not None:
                u = _or_unknown(u, u2) & ~t
    return t, u


def _tree_mask(tree, cols):
    """The WHERE answer is the definitely-TRUE mask (UNKNOWN rows drop,
    per SQL).  Workers rebuild this from the shipped ``_tree``."""
    return _tree_masks(tree, cols)[0]


def _promotable(cond) -> bool:
    return (cond[0] == "cmp" and cond[2] in ("=", "=="))         or cond[0] in ("between", "in")


def _promote(q: Query, cond) -> Query:
    if cond[0] == "cmp":
        return q.where_eq(cond[1], cond[3])
    if cond[0] == "between":
        return q.where_range(cond[1], cond[2], cond[3])
    return q.where_in(cond[1], cond[2])


def _apply_where(q: Query, tree) -> Query:
    """The first index-capable LEAF of a top-level AND (or a sole leaf)
    becomes a STRUCTURED filter the planner can serve from a sidecar;
    everything else — the rest of the conjunction, or any OR tree —
    composes as a residual ``where`` predicate the index path RECHECKS
    on index-resolved rows (Query's Index Cond + Filter shape)."""
    if tree is None:
        return q
    rest = None
    if tree[0] == "leaf" and _promotable(tree[1]):
        q = _promote(q, tree[1])
    elif tree[0] == "and":
        kids = list(tree[1])
        pick = next((i for i, k in enumerate(kids)
                     if k[0] == "leaf" and _promotable(k[1])), None)
        if pick is None:
            rest = tree
        else:
            q = _promote(q, kids[pick][1])
            kids = kids[:pick] + kids[pick + 1:]
            rest = kids[0] if len(kids) == 1 else ("and", kids)
    else:
        rest = tree
    if rest is not None:
        # _tree rides along so worker processes can rebuild the mask
        # (a bare lambda would mark the query non-parallel)
        q = q.where(lambda cols, rest=rest: _tree_mask(rest, cols),
                    _tree=rest)
    return q


def _having_fn(havings: List[tuple], agg_cols: List[int]):
    if not havings:
        return None

    def hv(res, havings=havings, agg_cols=agg_cols):
        m = np.ones(len(np.asarray(res["count"])), bool)
        for fn, col, op, lit in havings:
            if fn == "count":
                vals = np.asarray(res["count"])
            else:
                if col not in agg_cols:
                    raise StromError(22, f"SQL: HAVING {fn}(c{col}) "
                                         f"needs c{col} aggregated in "
                                         f"the SELECT list")
                i = agg_cols.index(col)
                vals = np.asarray(res[{"sum": "sums", "avg": "avgs",
                                       "min": "mins",
                                       "max": "maxs"}[fn]][i])
            m = m & _cmp_np(op)(vals, lit)
        return m
    return hv


_JOIN_TYPES = ("inner", "left", "semi", "anti")


def _build_star(q: Query, joins, items, tables, group_cols, havings,
                order, limit, off, dicts, schema):
    """The >=2-JOIN statement (star schema in ONE statement, round 5):
    every dimension probes in the same fused scan kernel
    (`Query.star_join`).  Faces: additive aggregates — COUNT(*),
    SUM/AVG over fact columns or expressions, SUM/AVG(dim.cK) — or row
    materialization (fact columns + dim payloads).  The reference's
    scan inherits arbitrary join composition from the executor above it
    (`pgsql/nvme_strom.c:941-979`); this serves the star core of it."""
    from .strings import dict_path_for
    if group_cols is not None or havings or order is not None:
        raise StromError(22, "SQL: GROUP BY/HAVING/ORDER BY with JOIN "
                             "are outside this subset")
    if items is None:
        raise StromError(22, "SQL: JOIN needs an explicit select list")
    dim_names = [dname for _h, dname, _pc, _kc in joins]
    for it in items:
        if it.table is not None and it.table not in dim_names:
            raise StromError(22, f"SQL: unknown table {it.table!r}")
    # per-dim payload columns referenced in the select list
    payload: dict = {}
    for it in items:
        if it.table is not None:
            payload.setdefault(it.table, set()).add(it.col)
    specs = []
    for how, dname, pc, kc in joins:
        if not tables or dname not in tables:
            raise StromError(22, f"SQL: JOIN table {dname!r} not bound "
                                 f"(pass tables={{{dname!r}: (path, "
                                 f"schema)}})")
        dpath, dschema = tables[dname]
        if not 0 <= kc < dschema.n_cols:
            raise StromError(22, f"SQL: {dname}.c{kc} out of range")
        # two string columns carry codes from SEPARATE dictionaries
        # (same refusal as the single join)
        if dicts(pc) is not None or (
                isinstance(dpath, str)
                and os.path.exists(dict_path_for(dpath, kc))):
            raise StromError(22, "SQL: JOIN on string-dictionary "
                                 "columns is outside this subset "
                                 "(separate dictionaries make codes "
                                 "incomparable)")
        cols_ref = sorted(payload.get(dname, ()))
        if len(cols_ref) > 1:
            raise StromError(22, f"SQL: one {dname}.cK column per "
                                 f"dimension in this subset")
        if cols_ref and how in ("semi", "anti"):
            raise StromError(22, f"SQL: {how.upper()} JOIN does not "
                                 f"expose {dname} columns (EXISTS "
                                 f"semantics)")
        vc = cols_ref[0] if cols_ref else None
        if vc is not None and not 0 <= vc < dschema.n_cols:
            raise StromError(22, f"SQL: {dname}.c{vc} out of range")
        specs.append({"probe_col": pc, "table": dpath,
                      "schema": dschema, "key_col": kc,
                      "value_col": vc, "how": how})
    dim_idx = {dname: i for i, dname in enumerate(dim_names)}
    agg_items = [it for it in items if it.kind in ("agg", "agge")]
    if agg_items and len(agg_items) != len(items):
        raise StromError(22, "SQL: JOIN mixes aggregates and bare "
                             "columns")
    if agg_items:
        if limit is not None:
            raise StromError(22, "SQL: LIMIT on a join aggregate")
        exprs, eidx = [], {}
        for it in agg_items:
            if it.kind == "agge":
                eidx[id(it)] = len(exprs)
                exprs.append(it.expr)
                continue
            ok = (it.fn == "count" and it.col is None
                  and not it.distinct) or \
                 (it.fn in ("sum", "avg") and not it.distinct
                  and it.col is not None)
            if not ok:
                raise StromError(22, f"SQL: {it.label} with a star "
                                     f"join is outside this subset")
            if it.table is None and it.fn in ("sum", "avg") \
                    and dicts(it.col) is not None:
                raise StromError(22, f"SQL: {it.label} over a string "
                                     f"column")
        q = q.star_join(specs, exprs=exprs)

        def assemble(res, agg_items=agg_items, eidx=eidx,
                     dim_idx=dim_idx):
            out = {}
            n = int(res["count"])
            for it in agg_items:
                if it.kind == "agge":
                    s = np.asarray(res["esums"][eidx[id(it)]]).item()
                    out[it.label] = s if it.fn == "sum" else \
                        (s / n if n else None)
                elif it.fn == "count":
                    out[it.label] = n
                elif it.table is None:
                    s = np.asarray(res["sums"][it.col]).item()
                    if it.fn == "sum":
                        out[it.label] = s
                    else:   # AVG skips NULL cells: non-NULL denominator
                        nnc = res.get("nncounts")
                        nn = n if nnc is None \
                            else int(np.asarray(nnc[it.col]))
                        out[it.label] = s / nn if nn else None
                else:
                    i = dim_idx[it.table]
                    s = np.asarray(res["pay_sums"][i]).item()
                    if it.fn == "sum":
                        out[it.label] = s
                    else:   # AVG over the dim payload skips NULLs
                        hits = n - int(np.asarray(res["null_counts"][i]))
                        out[it.label] = s / hits if hits else None
            return out
        return q, assemble
    # row face: fact columns + dim payloads
    fact_cols = []
    for it in items:
        if it.table is None and it.col not in fact_cols:
            fact_cols.append(it.col)
    q = q.star_join(specs, materialize=True, fact_cols=fact_cols,
                    limit=limit, offset=off)

    def assemble(res, items=items, dim_idx=dim_idx, joins=joins):
        out = {}
        for it in items:
            if it.table is None:
                out[it.label] = np.asarray(res[f"c{it.col}"])
            else:
                out[it.label] = np.asarray(
                    res[f"pay{dim_idx[it.table]}"])
        for how, dname, _pc, _kc in joins:
            if how == "left":   # the per-dim NULL indicator
                out[f"matched_{dname}"] = np.asarray(
                    res[f"m{dim_idx[dname]}"])
        out["positions"] = np.asarray(res["positions"])
        return out
    return q, assemble


def parse_sql(sql: str, source, schema,
              tables: Optional[dict] = None,
              workers: int = 0) -> Tuple[Query, "callable"]:
    """Parse *sql* against *source*/*schema*; returns ``(query,
    assemble)`` where ``assemble(run_result) -> dict`` relabels the
    terminal's output into the statement's select-list names — with
    dictionary-encoded string columns decoded back to strings at the
    edge.  *tables* binds JOIN dimension names to ``(path, schema)``.
    ``workers=N`` plans the scan over N worker processes (the Gather
    analog; predicate trees ship to workers, so any WHERE subset
    statement parallelizes)."""
    import inspect
    aliases: dict = {}
    q, assemble = _parse_sql_raw(sql, source, schema, tables=tables,
                                 _aliases_out=aliases, workers=workers)
    dicts = _dict_cache(source)

    def assemble_decoded(res, **kw):
        out = _decode_strings(assemble(res, **kw), dicts)
        return {aliases.get(k, k): v for k, v in out.items()}

    assemble_decoded.__signature__ = inspect.signature(assemble)
    return q, assemble_decoded


def _parse_sql_raw(sql: str, source, schema,
                   tables: Optional[dict] = None,
                   _aliases_out: Optional[dict] = None,
                   workers: int = 0) -> Tuple[Query, "callable"]:
    n_cols = schema.n_cols
    p = _P(_tokenize(sql))
    p.expect_kw("select")
    select_distinct = p.kw("distinct")
    items = _parse_select_list(p, n_cols)
    if _aliases_out is not None and items:
        _aliases_out.update({it.label: it.alias for it in items
                             if it.alias})
    p.expect_kw("from")
    t = p.next()
    if t[0] != "name":
        raise StromError(22, f"SQL: FROM needs a table name, got {t[1]!r}")
    joins: List[tuple] = []   # (how, dim_name, probe_col, dim_key_col)
    while True:
        nxt = p.peek()
        how = "inner"
        joining = False
        if nxt and nxt[0] == "name" and nxt[1].lower() in _JOIN_TYPES:
            how = p.next()[1].lower()
            p.expect_kw("join")  # "FROM t LEFT ..." can be nothing else
            joining = True
        else:
            joining = p.kw("join")
        if not joining:
            if how != "inner":
                raise StromError(22, "SQL: join type without JOIN")
            break
        dn = p.next()
        if dn[0] != "name":
            raise StromError(22, "SQL: JOIN needs a table name")
        p.expect_kw("on")
        lt, lc = _colref(p, n_cols)
        p.expect_op("=")
        rt, rc = _colref(p, n_cols)
        sides = {lt: lc, rt: rc}
        if None not in sides or dn[1] not in sides:
            raise StromError(22, f"SQL: ON must equate a fact column "
                                 f"with a {dn[1]}.cK column")
        if any(j[1] == dn[1] for j in joins):
            raise StromError(22, f"SQL: table {dn[1]!r} joined twice")
        joins.append((how, dn[1], sides[None], sides[dn[1]]))
    join = joins[0] if len(joins) == 1 else None
    where_tree = _parse_where(p, n_cols) if p.kw("where") else None
    dicts = _dict_cache(source)
    where_tree = _translate_tree(where_tree, dicts, schema)
    group_cols: Optional[List[int]] = None
    if p.kw("group"):
        p.expect_kw("by")
        group_cols = [_col(p.next(), n_cols)]
        while p.peek() == ("op", ","):
            p.next()
            group_cols.append(_col(p.next(), n_cols))
    havings = _parse_having(p, n_cols) if p.kw("having") else []
    # (("col", c) | ("agg", fn, col), descending)
    order: Optional[Tuple[tuple, bool]] = None
    if p.kw("order"):
        p.expect_kw("by")
        t2 = p.peek()
        if t2 and t2[0] == "name" and t2[1].lower() in _AGGS \
                and self_is_call(p):
            # ORDER BY COUNT(*)/SUM(c)/... — grouped-result ordering
            fn = p.next()[1].lower()
            p.next()   # '('
            if p.peek() == ("op", "*"):
                p.next()
                ocol = None
                if fn != "count":
                    raise StromError(22, f"SQL: {fn.upper()}(*)")
            else:
                ocol = _col(p.next(), n_cols)
                if fn == "count":
                    raise StromError(22, "SQL: COUNT takes (*) in this "
                                         "subset (COUNT(cN) would "
                                         "silently mean COUNT(*))")
            p.expect_op(")")
            okey = ("agg", fn, ocol)
        else:
            ocols = [_col(p.next(), n_cols)]
            while p.peek() == ("op", ","):
                p.next()
                ocols.append(_col(p.next(), n_cols))
            okey = ("col", ocols[0]) if len(ocols) == 1 \
                else ("cols", ocols)
        desc = False
        if p.kw("desc"):
            desc = True
        else:
            p.kw("asc")
        order = (okey, desc)
    limit = offset = None
    if p.kw("limit"):
        limit = int(_plit(p))
    if p.kw("offset"):
        offset = int(_plit(p))
    left = p.peek()
    if left is not None:
        raise StromError(22, f"SQL: trailing input at {left[1]!r}")
    if havings and group_cols is None:
        raise StromError(22, "SQL: HAVING requires GROUP BY")

    if not joins and items is not None:
        for it in items:
            if it.table is not None:
                raise StromError(22, f"SQL: {it.label} references a "
                                     f"table with no JOIN")
    if select_distinct:
        if items is None or any(it.kind != "col" or it.table is not None
                                for it in items):
            raise StromError(22, "SQL: SELECT DISTINCT takes 1-2 plain "
                                 "fact columns")
        if group_cols is not None or join is not None:
            raise StromError(22, "SQL: SELECT DISTINCT with GROUP BY/"
                                 "JOIN is outside this subset")
        seen: List[int] = []
        for it in items:
            if it.col not in seen:
                seen.append(it.col)
        group_cols = seen      # DISTINCT == GROUP BY the select list
    q = _apply_where(Query(source, schema, workers=workers), where_tree)
    off = offset or 0

    # --- STAR (>= 2 JOINs probed in one pass) -----------------------------
    if len(joins) >= 2:
        return _build_star(q, joins, items, tables, group_cols, havings,
                           order, limit, off, dicts, schema)

    # --- JOIN -------------------------------------------------------------
    if join is not None:
        how_, dname, probe_col, key_col = join
        if not tables or dname not in tables:
            raise StromError(22, f"SQL: JOIN table {dname!r} not bound "
                                 f"(pass tables={{{dname!r}: (path, "
                                 f"schema)}})")
        dpath, dschema = tables[dname]
        if group_cols is not None or havings or order is not None:
            raise StromError(22, "SQL: GROUP BY/HAVING/ORDER BY with "
                                 "JOIN are outside this subset")
        if items is None:
            raise StromError(22, "SQL: JOIN needs an explicit select "
                                 "list")
        if not 0 <= key_col < dschema.n_cols:
            raise StromError(22, f"SQL: {dname}.c{key_col} out of range")
        # two string columns carry codes from SEPARATE dictionaries —
        # joining them would compare incomparable ranks and silently
        # return wrong rows; refuse until the tables share an encoding
        from .strings import dict_path_for
        if dicts(probe_col) is not None or (
                isinstance(dpath, str)
                and os.path.exists(dict_path_for(dpath, key_col))):
            raise StromError(22, "SQL: JOIN on string-dictionary "
                                 "columns is outside this subset "
                                 "(separate dictionaries make codes "
                                 "incomparable)")
        for it in items:
            if it.kind == "agge":
                raise StromError(22, f"SQL: {it.label} with a single "
                                     f"JOIN is outside this subset "
                                     f"(star statements serve "
                                     f"expression aggregates)")
            if it.table is not None and it.table != dname:
                raise StromError(22, f"SQL: unknown table {it.table!r}")
        dim_cols = sorted({it.col for it in items if it.table == dname})
        if len(dim_cols) > 1:
            raise StromError(22, f"SQL: one {dname}.cK column per join "
                                 f"in this subset")
        if dim_cols and how_ in ("semi", "anti"):
            raise StromError(22, f"SQL: {how_.upper()} JOIN does not "
                                 f"expose {dname} columns (EXISTS "
                                 f"semantics)")
        value_col = dim_cols[0] if dim_cols else key_col
        if not 0 <= value_col < dschema.n_cols:
            raise StromError(22, f"SQL: {dname}.c{value_col} out of "
                                 f"range")
        agg_items = [it for it in items if it.kind == "agg"]
        if agg_items and len(agg_items) != len(items):
            raise StromError(22, "SQL: JOIN mixes aggregates and bare "
                                 "columns")
        if agg_items:
            if limit is not None:
                raise StromError(22, "SQL: LIMIT on a join aggregate")
            for it in agg_items:
                ok = (it.fn == "count" and it.col is None) or \
                     (it.fn == "sum" and not it.distinct)
                if not ok:
                    raise StromError(22, f"SQL: {it.label} with JOIN "
                                         f"is outside this subset")
            q = q.join_table(probe_col, dpath, dschema, key_col,
                             value_col, how=how_)

            def assemble(res, agg_items=agg_items):
                out = {}
                for it in agg_items:
                    if it.fn == "count":
                        out[it.label] = int(res["matched"])
                    elif it.table is None:
                        out[it.label] = \
                            np.asarray(res["sums"][it.col]).item()
                    else:
                        out[it.label] = \
                            np.asarray(res["payload_sum"]).item()
                return out
            return q, assemble
        for it in items:
            if it.table is None and it.col != probe_col:
                raise StromError(
                    22, f"SQL: the row face serves the probe column "
                        f"c{probe_col} and {dname}.cK; fetch() other "
                        f"fact columns by position")
        q = q.join_table(probe_col, dpath, dschema, key_col, value_col,
                         materialize=True, limit=limit, offset=off,
                         how=how_)

        def assemble(res, items=items):
            out = {}
            for it in items:
                out[it.label] = np.asarray(
                    res["keys"] if it.table is None else res["payload"])
            out["positions"] = np.asarray(res["positions"])
            if "matched" in res:   # the left face's NULL indicator
                out["matched"] = np.asarray(res["matched"])
            return out
        return q, assemble

    # --- GROUP BY ---------------------------------------------------------
    if group_cols is not None:
        if items is None:
            raise StromError(22, "SQL: GROUP BY needs an explicit "
                                 "select list (group cols + aggregates)")
        agg_cols: List[int] = []
        for it in items:
            if it.kind == "agge":
                raise StromError(22, f"SQL: {it.label} under GROUP BY "
                                     f"is outside this subset "
                                     f"(expression aggregates are "
                                     f"scalar-only)")
            if it.kind == "col":
                if it.col not in group_cols:
                    raise StromError(22, f"SQL: c{it.col} is neither "
                                         f"grouped nor aggregated")
            elif it.fn == "count" and it.col is None and not it.distinct:
                pass
            elif it.fn in ("sum", "avg", "min", "max"):
                if it.fn in ("sum", "avg") and dicts(it.col) is not None:
                    raise StromError(22, f"SQL: {it.label} over a "
                                         f"string column (codes would "
                                         f"sum meaninglessly; MIN/MAX/"
                                         f"COUNT are the string "
                                         f"aggregates)")
                if it.col not in agg_cols:
                    agg_cols.append(it.col)
            else:
                raise StromError(22, f"SQL: {it.label} under GROUP BY "
                                     f"is outside this subset")
        for fn, col, _op, _lit_ in havings:
            if col is not None and col not in agg_cols:
                agg_cols.append(col)
        # ORDER BY on grouped results sorts groups post-aggregation (the
        # SQL top-N-groups shape): the key is a group column or an
        # aggregate, which may need aggregating even if unselected
        if order is not None and order[0][0] == "agg" \
                and order[0][2] is not None \
                and order[0][2] not in agg_cols:
            agg_cols.append(order[0][2])
        if order is not None and order[0][0] == "cols":
            raise StromError(22, "SQL: multi-key ORDER BY on grouped "
                                 "results is outside this subset")
        if order is not None and order[0][0] == "col" \
                and order[0][1] not in group_cols:
            raise StromError(22, f"SQL: ORDER BY c{order[0][1]} is "
                                 f"neither grouped nor an aggregate")
        # the groupby kernels need at least one aggregation column even
        # for a COUNT(*)-only statement: the group key column itself is
        # the free choice (its sums are simply unused)
        eff_aggs = agg_cols or [group_cols[0]]
        q = q.group_by_cols(group_cols, agg_cols=eff_aggs,
                            having=_having_fn(havings, eff_aggs))

        def assemble(res, items=items, group_cols=group_cols,
                     agg_cols=eff_aggs, order=order, limit=limit,
                     off=off):
            def field(kind, fn=None, col=None):
                if kind == "col":
                    return np.asarray(
                        res["key_cols"][group_cols.index(col)])
                if fn == "count":
                    return np.asarray(res["count"])
                return np.asarray(res[{"sum": "sums", "avg": "avgs",
                                       "min": "mins",
                                       "max": "maxs"}[fn]]
                                  [agg_cols.index(col)])

            n = len(np.asarray(res["count"]))
            perm = np.arange(n)
            if order is not None:
                okey, desc = order
                vals = field(*okey) if okey[0] == "agg" else \
                    field("col", col=okey[1])
                perm = np.argsort(vals, kind="stable")
                if desc:
                    perm = perm[::-1]
            if order is not None or limit is not None or off:
                end = None if limit is None else off + limit
                perm = perm[off:end]
            out = {}
            for it in items:
                arr = field(it.kind, it.fn, it.col)
                out[it.label] = arr[perm]
            return out
        return q, assemble

    # --- ORDER BY ---------------------------------------------------------
    if order is not None:
        okey, desc = order
        if okey[0] == "agg":
            raise StromError(22, "SQL: ORDER BY an aggregate requires "
                                 "GROUP BY")
        ocols = [okey[1]] if okey[0] == "col" else list(okey[1])
        oc = ocols[0]
        extra: List[int] = []
        if items is not None:
            for it in items:
                if it.kind != "col":
                    raise StromError(22, "SQL: ORDER BY with "
                                         "aggregates requires GROUP BY")
                if it.col != oc and it.col not in extra:
                    extra.append(it.col)
        else:
            extra = [c for c in range(n_cols) if c != oc]
        q = q.order_by(ocols, descending=desc, limit=limit, offset=off)
        labels = [it.label for it in items] if items is not None else \
            [f"c{c}" for c in range(n_cols)]

        def assemble(res, oc=oc, extra=extra, labels=labels,
                     source=source, schema=schema, session=None,
                     device=None):
            pos = np.asarray(res["positions"])
            out = {f"c{oc}": np.asarray(res["values"])}
            if extra:
                # projected columns beyond the sort key: point-lookups
                # by position, returned in caller (sorted) order — on
                # the CALLER's session/device (sql_query threads them)
                fetched = Query(source, schema).fetch(
                    pos, cols=extra, session=session, device=device)
                for c in extra:
                    out[f"c{c}"] = np.asarray(fetched[f"col{c}"])
            return {**{lbl: out[lbl] for lbl in labels},
                    "positions": pos}
        return q, assemble

    # --- plain projection -------------------------------------------------
    if items is None or all(it.kind == "col" for it in items):
        cols = None if items is None else [it.col for it in items]
        q = q.select(cols, limit=limit, offset=off)

        def assemble(res, cols=cols):
            sel = cols if cols is not None else \
                [int(k[3:]) for k in res if k.startswith("col")]
            out = {}
            for c in sel:
                arr = np.asarray(res[f"col{c}"])
                if f"null{c}" in res:
                    # nullable column: real NULLs at the result edge
                    # (object array with None — never a sentinel value)
                    m = np.asarray(res[f"null{c}"]).astype(bool)
                    obj = arr.astype(object)
                    obj[m] = None
                    arr = obj
                out[f"c{c}"] = arr
            out["positions"] = np.asarray(res["positions"])
            return out
        return q, assemble

    # --- scalar aggregates ------------------------------------------------
    if limit is not None:
        raise StromError(22, "SQL: LIMIT on a scalar aggregate")
    aggs = [it for it in items if it.kind == "agg"]
    agges = [it for it in items if it.kind == "agge"]
    if len(aggs) + len(agges) != len(items):
        raise StromError(22, "SQL: mixing bare columns with aggregates "
                             "needs GROUP BY")
    if agges:
        # any expression aggregate routes the WHOLE list through the
        # fused expression kernel (plain SUM(cN) becomes the ("col", c)
        # tree) — one scan, one result contract
        trees, tmap = [], {}
        for it in items:
            if it.kind == "agge":
                tmap[id(it)] = len(trees)
                trees.append(it.expr)
            elif it.fn == "count" and it.col is None and not it.distinct:
                pass
            elif it.fn in ("sum", "avg") and not it.distinct:
                if dicts(it.col) is not None:
                    raise StromError(22, f"SQL: {it.label} over a "
                                         f"string column")
                tmap[id(it)] = len(trees)
                trees.append(("col", it.col))
            else:
                raise StromError(22, f"SQL: {it.label} cannot combine "
                                     f"with expression aggregates")
        q = q.aggregate_exprs(trees)

        def assemble(res, items=items, tmap=tmap):
            out = {}
            n = int(res["count"])
            for it in items:
                if it.kind == "agg" and it.fn == "count":
                    out[it.label] = n
                    continue
                s = np.asarray(res["esums"][tmap[id(it)]]).item()
                out[it.label] = s if it.fn == "sum" else \
                    (s / n if n else None)
            return out
        return q, assemble
    if len(aggs) == 1 and aggs[0].distinct:
        q = q.count_distinct(aggs[0].col)
        lbl = aggs[0].label
        return q, (lambda res, lbl=lbl: {lbl: int(res["distinct"])})
    if len(aggs) == 1 and aggs[0].fn in ("min", "max"):
        it = aggs[0]
        q = q.top_k(it.col, 1, largest=(it.fn == "max"))

        def assemble(res, it=it):
            vals = np.asarray(res["values"])
            poss = np.asarray(res["positions"])
            empty = len(vals) == 0 or int(poss[0]) < 0
            return {it.label: None if empty else vals[0].item()}
        return q, assemble
    sum_cols: List[int] = []
    for it in aggs:
        if it.fn in ("sum", "avg"):
            if dicts(it.col) is not None:
                raise StromError(22, f"SQL: {it.label} over a string "
                                     f"column (MIN/MAX/COUNT are the "
                                     f"string aggregates)")
            if it.col not in sum_cols:
                sum_cols.append(it.col)
        elif it.fn == "count" and it.col is None:
            pass
        elif it.fn == "count" and not it.distinct:
            # COUNT(cN): non-NULL count (round 5) — rides the same
            # projected column slot so nncounts stays aligned
            if it.col not in sum_cols:
                sum_cols.append(it.col)
        else:
            raise StromError(22, f"SQL: {it.label} cannot combine with "
                                 f"other aggregates without GROUP BY")
    q = q.aggregate(cols=sum_cols or None)

    def assemble(res, aggs=aggs, sum_cols=sum_cols):
        out = {}
        n = int(res["count"])
        nnc = res.get("nncounts")   # present iff the schema has
        #                             nullable columns (NULL-aware)

        def denom(col):
            return int(np.asarray(nnc[sum_cols.index(col)])) \
                if nnc is not None else n
        for it in aggs:
            if it.fn == "count" and it.col is None:
                out[it.label] = n
            elif it.fn == "count":
                out[it.label] = denom(it.col)
            else:
                s = np.asarray(res["sums"][sum_cols.index(it.col)])
                d = denom(it.col)
                out[it.label] = s.item() if it.fn == "sum" else \
                    (s.item() / d if d else None)
        return out
    return q, assemble


def sql_query(sql: str, source, schema, tables: Optional[dict] = None,
              **run_kw) -> dict:
    """Parse + run in one call; returns the select-list-labeled result.
    ``session``/``device`` run kwargs also reach any post-pass the
    assembler performs (the projected ORDER BY point-lookups)."""
    import inspect
    q, assemble = parse_sql(sql, source, schema, tables=tables,
                            workers=int(run_kw.get("workers") or 0))
    res = q.run(**run_kw)
    params = inspect.signature(assemble).parameters
    extra = {k: run_kw[k] for k in ("session", "device")
             if k in run_kw and k in params}
    out = assemble(res, **extra)
    if isinstance(res, dict) and "_analyze" in res:
        out["_analyze"] = res["_analyze"]   # EXPLAIN ANALYZE face
    if isinstance(res, dict) and "_workers" in res:
        out["_workers"] = res["_workers"]   # per-worker scan seconds
    return out


def create_table_as(dest_path: str, sql: str, source, schema,
                    tables: Optional[dict] = None,
                    overwrite: bool = False, **run_kw):
    """CREATE TABLE AS: run *sql* and materialize its result as a NEW
    heap table at *dest_path* (the ETL face — derived tables requery
    with the full scan machinery, indexes and SQL included).

    Every equal-length result column becomes a table column in
    select-list order: int results land as int32 (range-checked, never
    silently wrapped), uint as uint32, floats as float32, and STRING
    columns re-encode with a fresh sorted dictionary saved as the new
    table's sidecar.  Scalar aggregate results build a 1-row table.
    ``positions`` (row provenance) is dropped.  An existing
    *dest_path* is refused (EEXIST) unless ``overwrite=True``.  Returns
    ``(dest_schema, n_rows)``."""
    from .heap import HeapSchema as _HS, build_heap_file
    from .strings import StringDict, save_dict
    if os.path.exists(dest_path) and not overwrite:
        raise StromError(17, f"CREATE TABLE AS: {dest_path} exists "
                             f"(overwrite=True replaces it)")
    out = sql_query(sql, source, schema, tables=tables, **run_kw)
    out.pop("_analyze", None)
    out.pop("_workers", None)      # scan telemetry, not data
    out.pop("positions", None)     # row provenance, not data
    # LEFT-join NULL indicators become REAL NULLS (round 5): the
    # unaliased dim payload labels ("<dim>.cK") turn into nullable
    # columns masked by their indicator, and the indicator column
    # drops.  Aliased payloads keep the indicator (the label link is
    # gone), preserving the round-4 int32-indicator behavior.
    null_of: dict = {}
    matched = out.get("matched")
    if matched is not None:
        m = ~np.asarray(matched).astype(bool)
        hits = [lbl for lbl in out if "." in lbl]
        if hits:
            for lbl in hits:
                null_of[lbl] = m
            out.pop("matched")
    for key in [k for k in out if k.startswith("matched_")]:
        dname = key[len("matched_"):]
        m = ~np.asarray(out[key]).astype(bool)
        hits = [lbl for lbl in out if lbl.startswith(dname + ".")]
        if hits:
            for lbl in hits:
                null_of[lbl] = m
            out.pop(key)
    cols, dts, dict_cols, nullable, nulls = [], [], {}, [], {}
    n_rows = None

    def add(label, arr, dt, mask):
        if mask is not None and mask.any():
            nulls[len(cols)] = mask
            nullable.append(True)
        else:
            nullable.append(False)
        cols.append(arr)
        dts.append(dt)

    for label, v in out.items():
        if v is None:
            # a NULL scalar aggregate (MIN over zero rows): the heap
            # format has no scalar NULL — refuse rather than silently
            # materializing SQL NULL as a real value
            raise StromError(22, f"CREATE TABLE AS: {label!r} is NULL "
                                 f"(aggregate over zero rows) — no NULL "
                                 f"scalar representation in the heap "
                                 f"format")
        arr = np.asarray([v]) if np.isscalar(v) else np.asarray(v)
        arr = arr.reshape(-1)
        if arr.dtype.kind in "US":     # string results re-encode below
            arr = arr.astype(object)
        if n_rows is None:
            n_rows = len(arr)
        elif len(arr) != n_rows:
            raise StromError(22, f"CREATE TABLE AS: column {label!r} "
                                 f"has {len(arr)} rows, expected "
                                 f"{n_rows} (mixed result faces)")
        mask = null_of.get(label)
        if arr.dtype.kind == "O":
            present = [x for x in arr if x is not None]
            if any(isinstance(x, str) for x in present):
                if len(present) != len(arr) or mask is not None:
                    raise StromError(22, f"CREATE TABLE AS: {label!r} "
                                         f"mixes strings and NULLs "
                                         f"(nullable string columns "
                                         f"are outside this subset)")
                d = StringDict(arr.tolist())
                dict_cols[len(cols)] = d
                add(label, d.encode(arr.tolist()), "uint32", None)
                continue
            # numeric object column with None holes (a nullable source
            # column projected through SQL): real NULLs round-trip
            om = np.array([x is None for x in arr], dtype=bool)
            mask = om if mask is None else (mask | om)
            isf = any(isinstance(x, float) for x in present)
            arr = np.array([0 if x is None else x for x in arr],
                           dtype=np.float64 if isf else np.int64)
        if arr.dtype.kind == "f":
            f32 = arr.astype(np.float32)
            if mask is not None:
                f32 = np.where(mask, np.float32(0), f32)
            add(label, f32, "float32", mask)
        elif arr.dtype.kind == "u":
            if len(arr) and int(arr.max()) > 0xFFFFFFFF:
                raise StromError(34, f"CREATE TABLE AS: {label!r} "
                                     f"exceeds uint32")
            add(label, arr.astype(np.uint32), "uint32", mask)
        else:
            live = arr if mask is None else arr[~mask]
            if len(live) and (int(live.min()) < -(1 << 31)
                              or int(live.max()) >= (1 << 31)):
                raise StromError(34, f"CREATE TABLE AS: {label!r} "
                                     f"exceeds int32")
            i32 = np.where(mask, 0, arr).astype(np.int32) \
                if mask is not None else arr.astype(np.int32)
            add(label, i32, "int32", mask)
    if not cols:
        raise StromError(22, "CREATE TABLE AS: the statement returned "
                             "no columns")
    dest_schema = _HS(n_cols=len(cols), visibility=False,
                      dtypes=tuple(dts),
                      nullable=tuple(nullable) if any(nullable)
                      else None)
    build_heap_file(dest_path, cols, dest_schema, nulls=nulls or None)
    for c, d in dict_cols.items():
        save_dict(dest_path, c, d)
    return dest_schema, n_rows
