"""Table scan executor: async DMA ring + direct-to-device filter pipeline.

Capability analog of the pgsql scan executor (`pgsql/nvme_strom.c:636-1055`):
a ring of ``async_depth`` in-flight DMA tasks kept full by claiming block
ranges from an (atomic, shareable) cursor, waiting on the oldest
(``nvmestrom_next_chunk``, `:846-936`), with per-segment fd tables,
NUMA binding for the scan duration (`:353-446,716`), and the MVCC/cache
arbitration folded in: host-cache-hot chunks arrive via the engine's
write-back path, and per-tuple visibility is masked by the filter kernels
(`nvmestrom_load_chunk``'s two-way split, `:722-841`).

TPU-first shape: batches land in pinned pool chunks, stream to the device,
and the *filter runs as an XLA kernel overlapped with the next batch's DMA*
— the reference's per-tuple CPU walk becomes a device-resident reduction.
"""

from __future__ import annotations

import errno as _errno
import functools
import operator
import os
import threading
from dataclasses import dataclass
from typing import Callable, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..api import StromError
from ..config import config
from ..engine import Session, Source, open_source
from ..stats import stats
from ..numa import bind_to_node
from .heap import PAGE_SIZE, HeapSchema
from .planner import capability_cache
from .pool import DmaBufferPool, DmaChunk, ResourceOwner

__all__ = ["LocalCursor", "Batch", "TableScanner", "fold_results",
           "cursor_chunk_count"]


def cursor_chunk_count(size: int, chunk_size: int) -> int:
    """Total cursor positions for a source of *size* bytes: whole chunks
    plus one tail position when the remainder still holds whole pages.
    THE single formula — :class:`TableScanner` sizes its own cursor with
    it and the cross-process :class:`..scan.parallel.SharedCursor` must
    be created with the same count, or workers would skip (or
    double-claim) the tail."""
    n_chunks = size // chunk_size
    tail = size - n_chunks * chunk_size
    return n_chunks + (1 if (tail and tail % PAGE_SIZE == 0) else 0)


class CoalescedFold:
    """Reusable K-wide jitted fold of a jit-safe batch kernel: one
    traced call runs ``filter_fn`` over K device batches and folds the
    results on device (tree-sum, or *combine*).  Create once and pass as
    ``TableScanner.scan_filter(..., dispatch_coalesce=fold)`` so
    repeated scans — and an untimed warm call — share one compiled
    specialization instead of recompiling per scan."""

    def __init__(self, filter_fn: Callable, k: int,
                 combine: Optional[Callable] = None):
        import jax
        self.k = int(k)
        if combine is None:
            def _many(*bs):
                outs = [filter_fn(b) for b in bs]
                return jax.tree.map(
                    lambda *xs: functools.reduce(operator.add, xs),
                    *outs)
        else:
            def _many(*bs):
                out = filter_fn(bs[0])
                for b in bs[1:]:
                    out = combine(out, filter_fn(b))
                return out
        self._jfn = jax.jit(_many)

    def __call__(self, *batches):
        return self._jfn(*batches)


def fold_results(acc, out, combine: Optional[Callable] = None):
    """Fold one batch result into the accumulator (sum per key by default).

    Shared by :meth:`TableScanner.scan_filter` and the distributed
    streaming fold in :func:`..parallel.stream.distributed_scan_filter`."""
    if acc is None:
        return out
    if combine is not None:
        return combine(acc, out)
    import jax
    return jax.tree.map(lambda a, b: a + b, acc, out)


class LocalCursor:
    """In-process atomic chunk-range cursor (the shared ``nsp_cblock``
    atomic, `pgsql/nvme_strom.c:883-885`, for a single process)."""

    def __init__(self, n_chunks: int, start: int = 0):
        self.n_chunks = n_chunks
        self._start = start
        self._next = start
        self._lock = threading.Lock()

    def claim(self, count: int) -> Tuple[int, int]:
        """Claim up to *count* chunks; returns (first, n) with n == 0 at end."""
        with self._lock:
            first = self._next
            n = min(count, self.n_chunks - first)
            if n <= 0:
                return first, 0
            self._next += n
            return first, n

    def reset(self) -> None:
        """Rewind for a rescan (ExecReScanNVMEStrom, pgsql/nvme_strom.c)."""
        with self._lock:
            self._next = self._start


@dataclass
class Batch:
    """One completed scan batch: pages resident in a pool chunk.

    ``pages`` is a zero-copy view into pinned memory — valid until the next
    batch is drawn from the scanner (DB-cursor discipline)."""

    pages: np.ndarray          # (n_pages, PAGE_SIZE) uint8 view
    chunk_ids: List[int]       # source chunk id per slot (engine-reordered)
    first_page: int
    nr_ssd: int
    nr_wb: int
    _chunk: DmaChunk = None
    _handle: int = 0


class TableScanner:
    """Direct-load scan over a heap source."""

    def __init__(self, source: Union[str, Sequence[str], Source],
                 schema: Optional[HeapSchema] = None, *,
                 session: Optional[Session] = None,
                 pool: Optional[DmaBufferPool] = None,
                 cursor: Optional[LocalCursor] = None,
                 chunk_size: Optional[int] = None,
                 async_depth: Optional[int] = None,
                 segment_size: Optional[int] = None,
                 numa_bind: bool = True):
        self.schema = schema
        self.chunk_size = chunk_size or config.get("chunk_size")
        if self.chunk_size % PAGE_SIZE:
            raise StromError(_errno.EINVAL,
                            f"chunk_size must be a multiple of {PAGE_SIZE}")
        if self.chunk_size & (self.chunk_size - 1):
            # the engine rejects non-pow2 chunks at submit time; fail at
            # construction instead of on the first batch
            raise StromError(_errno.EINVAL,
                            f"chunk_size {self.chunk_size} must be a power of 2")
        self.pages_per_chunk = self.chunk_size // PAGE_SIZE
        self.async_depth = async_depth or config.get("async_depth")
        self._own_session = session is None
        self.session = session or Session()
        if isinstance(source, Source):
            self.source = source
            self._own_source = False
        else:
            self.source = open_source(source, segment_size=segment_size) \
                if not isinstance(source, str) else open_source(source)
            self._own_source = True
        self.n_chunks = self.source.size // self.chunk_size
        tail = self.source.size - self.n_chunks * self.chunk_size
        if tail and tail % PAGE_SIZE == 0:
            # partial final chunk still holds whole pages; scanned separately
            self._tail_pages = tail // PAGE_SIZE
        else:
            self._tail_pages = 0
        self.cursor = cursor or LocalCursor(
            cursor_chunk_count(self.source.size, self.chunk_size))
        self._own_pool = pool is None
        # + h2d_depth_max: scan_filter keeps that many batches alive with
        # their H2D transfers in flight (deferred-fence pipelining), on
        # top of the DMA ring and the batch being consumed
        self.pool = pool or DmaBufferPool(
            chunk_size=self.chunk_size,
            total_size=self.chunk_size *
            max(self.async_depth + int(config.get("h2d_depth_max")) + 1, 2))
        self._numa_bound = False
        self._prev_affinity = None
        if numa_bind:
            # bind to the storage's NUMA node for the scan (pgsql :716);
            # the previous affinity is restored by close()
            try:
                prev = os.sched_getaffinity(0)
                info = capability_cache.probe(
                    getattr(self.source, "path", None) or ".")
                self._numa_bound = bind_to_node(info.numa_node_id)
                if self._numa_bound:
                    self._prev_affinity = prev
            except (StromError, OSError, AttributeError):
                pass

    # -- core ring ----------------------------------------------------------
    def batches(self, owner: Optional[ResourceOwner] = None, *,
                auto_recycle: bool = True) -> Iterator[Batch]:
        """Yield completed batches, keeping ``async_depth`` DMAs in flight.

        With ``auto_recycle`` (default) the previous batch's pool chunk is
        recycled when the next batch is requested — the one-live-batch
        DB-cursor discipline.  ``auto_recycle=False`` hands recycling to
        the consumer (call :meth:`recycle` on each batch when its bytes
        are no longer needed), which lets the consumer keep several
        batches alive with H2D transfers in flight; the pool is sized for
        up to ``h2d_depth_max`` such batches."""
        # ring entries: (task_id, chunk, handle, first_chunk, MemCopyResult);
        # task_id == 0 marks the buffered tail read (real ids start at 1)
        ring: List[Tuple[int, DmaChunk, int, int, object]] = []
        prev: Optional[Batch] = None

        def submit_next() -> bool:
            first, n = self.cursor.claim(1)
            if n == 0:
                return False
            chunk = self.pool.alloc(owner=owner)
            handle = None
            try:
                handle = self.session.map_buffer(
                    chunk.view, kind="pinned_host",
                    backing=self.pool.backing_buffer(chunk.node))
                if first < self.n_chunks:
                    ids = [first]
                    res = self.session.memcpy_ssd2ram(self.source, handle,
                                                      ids, self.chunk_size)
                    ring.append((res.dma_task_id, chunk, handle, first, res))
                else:
                    # tail: whole pages past the chunk grid, read buffered
                    nbytes = self._tail_pages * PAGE_SIZE
                    self.source.read_buffered(self.n_chunks * self.chunk_size,
                                              chunk.view[:nbytes])
                    ring.append((0, chunk, handle, first, None))
            except BaseException:
                # failed submissions must not strand the chunk/handle
                # (memcpy_ssd2ram has already waited out its own in-flight
                # work before raising, so the buffer is idle here)
                if handle is not None:
                    self.session.unmap_buffer(handle)
                chunk.release()
                raise
            return True

        try:
            for _ in range(self.async_depth):
                if not submit_next():
                    break
            while ring:
                task_id, chunk, handle, first, res = ring.pop(0)
                if task_id:
                    result = self.session.memcpy_wait(task_id)
                    n_pages = self.pages_per_chunk
                    nr_ssd, nr_wb = result.nr_ssd2dev, result.nr_ram2dev
                    ids = result.chunk_ids
                else:
                    n_pages = self._tail_pages
                    nr_ssd, nr_wb = 0, 1
                    ids = [first]
                # recycle the consumer's previous batch BEFORE submitting the
                # next DMA: at steady state the pool holds ring(depth) +
                # current + previous, so the freed chunk is what the next
                # submission allocates — submitting first deadlocks on a
                # depth+1-sized pool.  (Consumer-recycled mode: the
                # consumer must release before drawing past its own depth
                # budget for the same reason.)
                if auto_recycle and prev is not None:
                    self._recycle(prev)
                    prev = None
                submit_next()
                pages = np.frombuffer(chunk.view[:n_pages * PAGE_SIZE],
                                      dtype=np.uint8).reshape(n_pages, PAGE_SIZE)
                batch = Batch(pages=pages, chunk_ids=ids,
                              first_page=first * self.pages_per_chunk,
                              nr_ssd=nr_ssd, nr_wb=nr_wb,
                              _chunk=chunk, _handle=handle)
                if auto_recycle:
                    prev = batch
                yield batch
        finally:
            if prev is not None:
                self._recycle(prev)
            # drain anything still in flight (submit-error containment:
            # the reference waits out in-flight DMA on error, :1781-1784)
            for task_id, chunk, handle, _first, _res in ring:
                try:
                    if task_id:
                        self.session.memcpy_wait(task_id, timeout=30.0)
                except StromError:
                    pass
                self.session.unmap_buffer(handle)
                chunk.release()

    def _recycle(self, batch: Batch) -> None:
        self.session.unmap_buffer(batch._handle)
        batch._chunk.release()

    def recycle(self, batch: Batch) -> None:
        """Return a consumer-held batch's chunk to the pool
        (``batches(auto_recycle=False)`` mode)."""
        self._recycle(batch)

    def rescan(self) -> None:
        """Rewind the cursor so the table can be scanned again from page 0
        (ExecReScanNVMEStrom, `pgsql/nvme_strom.c:1047-1055`).  Only valid
        between scans — not while a batches() iterator is live."""
        self.cursor.reset()

    # -- device-filter pipeline --------------------------------------------
    def scan_filter(self, filter_fn: Callable, *, device=None,
                    combine: Optional[Callable] = None,
                    dispatch_coalesce: Union[int, CoalescedFold,
                                             None] = None) -> dict:
        """Stream every batch to the device and fold ``filter_fn`` over it.

        ``filter_fn(pages_u8_device) -> dict of scalars``; results are
        summed (or combined with *combine*).

        ``dispatch_coalesce=K`` folds K fenced device batches inside ONE
        jitted call (filter_fn traced K times, results tree-summed or
        *combine*-folded on device) instead of dispatching per batch —
        on a high-latency backend each dispatch is a full tunnel round
        trip, and per-16MB dispatches cap a streamed scan far below the
        transport ceiling.  OPT-IN because it traces ``filter_fn`` and
        *combine*: both must be jit-safe (the query kernels are; host-
        side collect closures are not).  None/1 = per-batch dispatch.
        Pass a prebuilt (warmable) :class:`CoalescedFold` to share one
        compiled specialization across scans.

        ADAPTIVE H2D pipelining (VERDICT r2 #3 + r3 #6): several batches
        keep their device transfers in flight at once — the fence on
        batch *k* is deferred until *k + depth* has been dispatched, so
        the H2D hop rides transfer bursts the way the 32-deep loader does
        instead of paying a synchronous fence per 16MB.  Depth policy is
        :class:`..hbm.staging.AdaptiveH2DDepth`: start at 2, deepen (up
        to config ``h2d_depth_max`` / pool headroom) whenever the
        consumer actually blocks on a transfer, and DECAY after a streak
        of fence-free retirements so a closed burst window releases its
        pool chunks instead of pinning them for the rest of the scan."""
        import time as _time

        import jax

        from ..hbm.staging import (AdaptiveH2DDepth, bounded_fence,
                                   h2d_meter, safe_device_put)
        # local_devices, not devices: under jax.distributed the
        # global list leads with process 0's device, and a
        # device_put onto a non-addressable device poisons the
        # whole scan (observed in the 2-process group_by_cols leg)
        dev = device or jax.local_devices()[0]
        acc: Optional[dict] = None
        # pool must hold: DMA ring (async_depth) + the batch being drawn
        # + every consumer-held in-flight batch
        depth_cap = max(1, min(int(config.get("h2d_depth_max")),
                               self.pool.n_chunks - self.async_depth - 1))
        ad = AdaptiveH2DDepth(depth_cap)
        self.last_h2d_depth = ad.depth   # per-scan observability (ANALYZE)
        # seed the process gauge with the starting depth so the registry
        # and ANALYZE agree whenever any pipelined scan ran (the gauge
        # otherwise only moved on deepening and could never read 2)
        stats.gauge_max("h2d_depth_reached", ad.depth)
        inflight: List[tuple] = []   # (dev_pages, batch), oldest first
        if isinstance(dispatch_coalesce, CoalescedFold):
            fold_many: Optional[CoalescedFold] = dispatch_coalesce
        elif dispatch_coalesce and int(dispatch_coalesce) > 1:
            fold_many = CoalescedFold(filter_fn, int(dispatch_coalesce),
                                      combine)
        else:
            fold_many = None
        kmax = fold_many.k if fold_many is not None else 1
        ready: List = []             # fenced batches awaiting dispatch

        def dispatch_many() -> None:
            # one traced call folds a full K-wide window on device; the
            # n<kmax tail goes per-batch through the already-compiled
            # filter_fn rather than paying a tail-width compile
            nonlocal acc
            if len(ready) == kmax and fold_many is not None:
                acc = fold_results(acc, fold_many(*ready), combine)
                stats.add("nr_kernel_dispatch")
            else:
                for dp in ready:
                    acc = fold_results(acc, filter_fn(dp), combine)
                    stats.add("nr_kernel_dispatch")
            ready.clear()

        def retire_oldest() -> None:
            dev_pages, b = inflight.pop(0)
            t0 = _time.monotonic_ns()
            # safe_device_put copied on CPU; on accelerators the H2D read
            # of the pinned chunk must finish before the chunk refills.
            # Bounded (VERDICT r3 #5): a dead backend fails the scan with
            # ENODEV instead of hanging the fence
            bounded_fence(dev_pages, "scan-h2d")
            blocked_ns = _time.monotonic_ns() - t0
            # transfer-bound retirements feed the live link estimate the
            # pushdown planner keys its host-vs-chip decision on
            h2d_meter.note(int(dev_pages.nbytes), blocked_ns)
            self.recycle(b)
            ready.append(dev_pages)
            if len(ready) >= kmax:
                dispatch_many()
            # last_h2d_depth = the PEAK this scan reached (ANALYZE's
            # "h2d_depth_reached"); decay lowers ad.depth, not the peak
            if ad.observe(blocked_ns) > self.last_h2d_depth:
                self.last_h2d_depth = ad.depth
                stats.gauge_max("h2d_depth_reached", ad.depth)
        with ResourceOwner("scan_filter") as owner:
            gen = self.batches(owner=owner, auto_recycle=False)
            try:
                for batch in gen:
                    # safe_device_put, NOT jax.device_put: batch.pages is a
                    # view into a pool chunk, and CPU-backend device_put
                    # zero-copy ALIASES it — the async filter compute would
                    # read the chunk after recycle+refill (silent wrong
                    # aggregates; caught by a cold-file 64KB-chunk scan)
                    inflight.append((safe_device_put(batch.pages, dev),
                                     batch))
                    # release below the depth budget BEFORE drawing the
                    # next batch, or the generator's pool alloc deadlocks
                    while len(inflight) >= ad.depth:
                        retire_oldest()
                while inflight:
                    retire_oldest()
                dispatch_many()   # tail below the coalescing width
            finally:
                # consumer-held batches: fence + recycle before the ring
                # drain, so abort recovery never frees a chunk an H2D
                # read is still consuming
                for dev_pages, b in inflight:
                    try:
                        # bounded: post-loss teardown must not re-hang
                        bounded_fence(dev_pages, "scan-teardown")
                    except Exception:   # noqa: BLE001 - teardown path
                        pass
                    self.recycle(b)
                inflight.clear()
                # drain the ring INSIDE the owner scope: when filter_fn
                # raises (e.g. a LIMIT early-exit), the generator's finally
                # must wait out in-flight SSD DMA before ResourceOwner
                # abort-recovery returns those chunks to a possibly-shared
                # pool — freeing first would let a concurrent scan alloc a
                # chunk the SSD is still writing into
                gen.close()
        if acc is None:
            return {}
        import jax
        if not isinstance(acc, dict):
            acc = dict(acc)
        # per-leaf conversion: a heterogeneous sums LIST (join/aggregate
        # faces mix int32/uint32/float32 accumulators) must keep each
        # leaf's acc dtype — np.asarray over the list would upcast all
        # of them to float64
        return jax.tree.map(np.asarray, acc)

    def close(self) -> None:
        if self._prev_affinity is not None:
            try:
                os.sched_setaffinity(0, self._prev_affinity)
            except OSError:
                pass
            self._prev_affinity = None
        if self._own_pool:
            self.pool.close()
        if self._own_session:
            self.session.close()
        if self._own_source:
            self.source.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
