"""Packed columnar extents: the compressed wire format for compute pushdown.

The h2d link is the hard ceiling of every tier built so far (BENCH_MATRIX:
``h2d_peak`` 1.06 GB/s against ``raw_seq_read`` 3.36 GB/s), and the way past
a transport ceiling is to move fewer, denser bytes and expand them on-chip
(ROADMAP item 5; AXI4MLIR's host<->accelerator transfer codegen is the
model for the per-column host-vs-chip expansion decision).  This module is
the *format* half: a ``<table>.cpk`` sidecar holding the same rows as the
heap table, re-encoded so each 8KB page carries ``rows_per_block`` rows
instead of the heap's ``tuples_per_page``.

Layout — every page is PAGE_SIZE bytes, so the packed file rides the whole
existing stack (chunked DMA ring, landing buffers, fault ladder, residency
cache) with zero special-casing:

* page 0: file header — ``CPK_FILE_MAGIC`` then a length-prefixed JSON
  metadata blob (schema facts, per-column codec + fixed region layout,
  source-table staleness stamp, exact packed/logical byte counts).
* pages 1..n_blocks: data blocks — a 64-byte header (``CPK_MAGIC``,
  block id, n_rows, payload crc32c) then per-column regions at the word
  offsets the file header declared.  Every block shares ONE layout, so
  the decode kernels are fully static: offsets, widths, dict capacities
  and run bounds are compile-time constants, never data.

Codecs (all chosen per column, globally for the file, so a region's shape
never varies block to block):

* ``raw``      — 32-bit words verbatim (bitcast for float32).
* ``bitpack``  — frame-of-reference base (region word 0) + deltas packed
  at a width that divides 32 (1/2/4/8/16) in a PLANAR layout: value ``j``
  lives in word ``j % nw`` at shift ``(j // nw) * bits``.  Planar (not
  word-major) on purpose: the chip decode is then shift + mask +
  concatenate along the minor axis — no gather and no reshape, neither
  of which TPU vector memory does cheaply.
* ``dict``     — per-block dictionary (``dsize`` slots, pow2) followed by
  bit-packed indices; decode is a ``dsize``-way static select-sum.
* ``rle``      — run values + cumulative run ends, ``rmax`` slots; decode
  is an ``rmax``-step static interval mask over a row iota.

The pure-numpy decoder here is the correctness oracle for the fused
Pallas/XLA kernels in ``ops/decode_pallas.py`` / ``ops/decode_xla.py``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .heap import (HEADER_WORDS, PAGE_SIZE, HeapSchema, crc32c,
                   pages_from_bytes, read_column)

__all__ = [
    "CPK_MAGIC", "CPK_FILE_MAGIC", "ColCodec", "PackedMeta",
    "packed_path_for", "build_packed", "load_meta", "probe_packed",
    "decode_pages_numpy", "decode_file_numpy",
]

CPK_MAGIC = 0x43504B31        # 'CPK1' — data-block header word 0
CPK_FILE_MAGIC = 0x43504B46   # 'CPKF' — file-header page word 0

_WORDS = PAGE_SIZE // 4
_PAYLOAD_WORDS = _WORDS - HEADER_WORDS

# static-unroll bounds for the chip decoders: a dict decode is a D-way
# select-sum and an RLE decode an R-step interval mask, so both must stay
# small enough to unroll (and to keep encode-side per-block stats cheap)
DICT_MAX = 64
RLE_MAX = 64
# the largest rows_per_block the encoder will emit: bounds the (bp, rpb)
# decoded-column tensors the kernels materialize in VMEM
_RPB_CANDIDATES = tuple(1 << k for k in range(15, 4, -1))   # 32768 .. 32

CODECS = ("raw", "bitpack", "dict", "rle")


@dataclasses.dataclass(frozen=True)
class ColCodec:
    """One column's codec + fixed region geometry (identical every block)."""

    codec: str            # raw | bitpack | dict | rle
    off: int              # region word offset within the page
    nwords: int           # region length in words
    bits: int = 0         # packed value/index width (bitpack/dict)
    dsize: int = 0        # dictionary capacity (dict; power of two)
    rmax: int = 0         # max runs per block (rle)
    packed_bytes: int = 0   # region bytes summed over all blocks
    logical_bytes: int = 0  # n_rows * 4

    @property
    def ratio(self) -> float:
        """Observed codec ratio: logical bytes per packed byte."""
        return self.logical_bytes / self.packed_bytes \
            if self.packed_bytes else 1.0


@dataclasses.dataclass(frozen=True)
class PackedMeta:
    """Parsed ``.cpk`` file header: everything the planner and the decode
    kernels need, all static."""

    version: int
    rows_per_block: int
    n_blocks: int
    n_rows: int
    dtypes: Tuple[str, ...]
    cols: Tuple[ColCodec, ...]
    table_size: int        # staleness stamp (scan/index.py idiom)
    table_mtime_ns: int
    path: str = ""

    @property
    def packed_bytes(self) -> int:
        """Wire bytes for a full scan: header page + data pages."""
        return (1 + self.n_blocks) * PAGE_SIZE

    @property
    def logical_bytes(self) -> int:
        return self.n_rows * 4 * len(self.dtypes)

    @property
    def ratio(self) -> float:
        return self.logical_bytes / self.packed_bytes \
            if self.packed_bytes else 1.0


def packed_path_for(table_path: str) -> str:
    return table_path + ".cpk"


# -- encode ---------------------------------------------------------------

def _pow2_width(span: int) -> int:
    """Smallest width in {1,2,4,8,16,32} holding *span* distinct deltas."""
    for b in (1, 2, 4, 8, 16):
        if span < (1 << b):
            return b
    return 32


def _pack_bits(vals: np.ndarray, bits: int, nw: int) -> np.ndarray:
    """Planar bit-pack of uint32 *vals* into exactly *nw* words: value
    ``j`` goes to word ``j % nw`` at shift ``(j // nw) * bits``.  *nw*
    is the region's fixed capacity (derived from rows_per_block), so a
    partial block packs identically to a full one."""
    vpw = 32 // bits
    v = np.zeros(nw * vpw, np.uint64)
    v[:len(vals)] = vals.astype(np.uint64)
    planes = v.reshape(vpw, nw)      # plane k = values [k*nw, (k+1)*nw)
    shifts = (np.arange(vpw, dtype=np.uint64) * np.uint64(bits))
    return ((planes << shifts[:, None]).sum(axis=0, dtype=np.uint64)
            & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def _unpack_bits(words: np.ndarray, bits: int, n: int) -> np.ndarray:
    """Inverse of :func:`_pack_bits` (nw = len(words))."""
    vpw = 32 // bits
    mask = np.uint32((1 << bits) - 1) if bits < 32 else np.uint32(0xFFFFFFFF)
    shifts = (np.arange(vpw, dtype=np.uint32) * np.uint32(bits))
    planes = (words.astype(np.uint32)[None, :] >> shifts[:, None]) & mask
    return planes.reshape(-1)[:n]


def _block_slices(n_rows: int, rpb: int) -> List[slice]:
    return [slice(i, min(i + rpb, n_rows)) for i in range(0, n_rows, rpb)]


def _col_u32(col: np.ndarray) -> np.ndarray:
    """Bit-pattern view: every codec below works on uint32 words."""
    return np.ascontiguousarray(col).view(np.uint32)


def _runs_per_block(u: np.ndarray, rpb: int) -> int:
    """Max run count over rpb-row blocks (block boundaries break runs)."""
    if len(u) == 0:
        return 0
    change = np.flatnonzero(np.diff(u) != 0) + 1
    # a run starts at 0, at every value change, and at every block edge
    starts = np.union1d(change, np.arange(0, len(u), rpb))
    return int(np.max(np.bincount(starts // rpb))) if len(starts) else 1


def _distinct_per_block(u: np.ndarray, rpb: int) -> int:
    if len(u) == 0:
        return 0
    return max(len(np.unique(u[sl])) for sl in _block_slices(len(u), rpb))


def _codec_candidates(u: np.ndarray, is_float: bool, rpb: int,
                      allowed: Sequence[str]):
    """[(words_per_block, codec, bits, dsize, rmax)] for one column."""
    out = [(rpb, "raw", 0, 0, 0)]
    n = len(u)
    if n == 0:
        return out
    if "bitpack" in allowed and not is_float:
        span = int(u.max()) - int(u.min())   # uint32 domain: span < 2^32
        b = _pow2_width(span)
        if b < 32:
            out.append((1 + (rpb * b + 31) // 32, "bitpack", b, 0, 0))
    if "dict" in allowed:
        d = _distinct_per_block(u, rpb)
        if 0 < d <= DICT_MAX:
            dsize = 1 << max(int(np.ceil(np.log2(d))), 0)
            bi = max(_pow2_width(dsize - 1), 1)
            out.append((dsize + (rpb * bi + 31) // 32, "dict", bi, dsize, 0))
    if "rle" in allowed:
        r = _runs_per_block(u, rpb)
        if 0 < r <= RLE_MAX:
            out.append((1 + 2 * r, "rle", 0, 0, r))
    return out


def _choose_layout(cols_u32: List[np.ndarray], floats: List[bool],
                   allowed: Sequence[str]):
    """Largest rows_per_block whose per-column best codecs fit one page.

    rows_per_block IS the compression ratio (rows delivered per 8KB of
    wire), so the search is simply: biggest rpb that fits."""
    for rpb in _RPB_CANDIDATES:
        picks, total = [], HEADER_WORDS
        for u, isf in zip(cols_u32, floats):
            cands = _codec_candidates(u, isf, rpb, allowed)
            picks.append(min(cands))
            total += picks[-1][0]
        if total <= _WORDS:
            return rpb, picks
    raise ValueError(f"schema too wide to pack ({len(cols_u32)} columns)")


def _encode_block(u: np.ndarray, pick, rpb: int) -> np.ndarray:
    nwords, codec, bits, dsize, rmax = pick
    out = np.zeros(nwords, np.uint32)
    n = len(u)
    if codec == "raw":
        out[:n] = u
    elif codec == "bitpack":
        base = u.min() if n else np.uint32(0)
        out[0] = base
        out[1:] = _pack_bits((u - base).astype(np.uint32), bits,
                             nwords - 1)
    elif codec == "dict":
        vals, idx = np.unique(u, return_inverse=True)
        out[:len(vals)] = vals
        out[dsize:] = _pack_bits(idx.astype(np.uint32), bits,
                                 nwords - dsize)
    else:   # rle
        if n:
            change = np.flatnonzero(np.diff(u) != 0) + 1
            starts = np.concatenate(([0], change))
            ends = np.concatenate((change, [n]))
            nr = len(starts)
            out[0] = nr
            out[1:1 + nr] = u[starts]
            out[1 + rmax:1 + rmax + nr] = ends.astype(np.uint32)
            # padded runs are empty intervals [n, n): decoders that walk
            # all rmax slots see zero-width masks past n_runs
            out[1 + nr:1 + rmax] = 0
            out[1 + rmax + nr:1 + 2 * rmax] = n
    return out


def build_packed(table_path: str, schema: HeapSchema, *,
                 out_path: Optional[str] = None,
                 codecs: Optional[Sequence[str]] = None) -> PackedMeta:
    """Encode a heap table into its ``.cpk`` packed twin (atomic rename).

    MVCC-invisible rows are dropped at encode time — the packed file holds
    exactly the rows a scan would aggregate, and the staleness stamp makes
    any later table write invalidate the sidecar."""
    if schema.has_wide or any(schema.nullable or ()):
        raise ValueError("packed extents serve the 4-byte non-null layout")
    if codecs is not None:
        allowed = tuple(codecs)
    else:
        from ..config import config
        allowed = tuple(c.strip()
                        for c in config.get("pushdown_codecs").split(",")
                        if c.strip())
    st = os.stat(table_path)
    with open(table_path, "rb") as f:
        pages = pages_from_bytes(f.read())
    cols = [read_column(pages, schema, c) for c in range(schema.n_cols)]
    if schema.visibility:
        words = pages.view(np.int32).reshape(len(pages), _WORDS)
        s, _e = schema.col_word_range(schema.n_cols)
        vis = np.concatenate([
            words[p, s:s + int(words[p, 2])] for p in range(len(pages))]) \
            if len(pages) else np.empty(0, np.int32)
        keep = vis != 0
        cols = [c[keep] for c in cols]
    n_rows = len(cols[0]) if cols else 0
    floats = [schema.col_dtype(c).kind == "f" for c in range(schema.n_cols)]
    cols_u32 = [_col_u32(c) for c in cols]
    rpb, picks = _choose_layout(cols_u32, floats, allowed)
    n_blocks = (n_rows + rpb - 1) // rpb

    col_metas, off = [], HEADER_WORDS
    for c, (nwords, codec, bits, dsize, rmax) in enumerate(picks):
        col_metas.append(ColCodec(
            codec=codec, off=off, nwords=nwords, bits=bits, dsize=dsize,
            rmax=rmax, packed_bytes=nwords * 4 * n_blocks,
            logical_bytes=n_rows * 4))
        off += nwords

    blocks = np.zeros((n_blocks, _WORDS), np.uint32)
    for bi, sl in enumerate(_block_slices(n_rows, rpb)):
        blocks[bi, 0] = CPK_MAGIC
        blocks[bi, 1] = bi
        blocks[bi, 2] = sl.stop - sl.start
        for c, (u, pick, cm) in enumerate(zip(cols_u32, picks, col_metas)):
            blocks[bi, cm.off:cm.off + cm.nwords] = \
                _encode_block(u[sl], pick, rpb)
        payload = blocks[bi, HEADER_WORDS:].tobytes()
        blocks[bi, 3] = np.uint32(crc32c(payload))

    meta = PackedMeta(
        version=1, rows_per_block=rpb, n_blocks=n_blocks, n_rows=n_rows,
        dtypes=tuple(np.dtype(schema.col_dtype(c)).name
                     for c in range(schema.n_cols)),
        cols=tuple(col_metas), table_size=st.st_size,
        table_mtime_ns=st.st_mtime_ns)
    head = np.zeros(_WORDS, np.uint32)
    head[0] = CPK_FILE_MAGIC
    blob = json.dumps(_meta_to_json(meta)).encode()
    head[1] = len(blob)
    head_bytes = bytearray(head.tobytes())
    head_bytes[8:8 + len(blob)] = blob
    if len(blob) > PAGE_SIZE - 8:
        raise ValueError("packed metadata blob exceeds the header page")

    dest = out_path or packed_path_for(table_path)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dest) or ".",
                               prefix=".cpk-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(bytes(head_bytes))
            f.write(blocks.tobytes())
        os.replace(tmp, dest)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return dataclasses.replace(meta, path=dest)


def _meta_to_json(m: PackedMeta) -> dict:
    return {
        "version": m.version, "page_size": PAGE_SIZE,
        "rows_per_block": m.rows_per_block, "n_blocks": m.n_blocks,
        "n_rows": m.n_rows, "dtypes": list(m.dtypes),
        "cols": [dataclasses.asdict(c) for c in m.cols],
        "table_size": m.table_size, "table_mtime_ns": m.table_mtime_ns,
    }


def load_meta(path: str) -> PackedMeta:
    """Parse a ``.cpk`` file header (no freshness check)."""
    with open(path, "rb") as f:
        head = f.read(PAGE_SIZE)
    if len(head) < PAGE_SIZE:
        raise ValueError(f"{path}: short packed header page")
    w = np.frombuffer(head[:8], np.uint32)
    if int(w[0]) != CPK_FILE_MAGIC:
        raise ValueError(f"{path}: bad packed-file magic 0x{int(w[0]):08x}")
    blob = head[8:8 + int(w[1])]
    d = json.loads(blob.decode())
    return PackedMeta(
        version=int(d["version"]), rows_per_block=int(d["rows_per_block"]),
        n_blocks=int(d["n_blocks"]), n_rows=int(d["n_rows"]),
        dtypes=tuple(d["dtypes"]),
        cols=tuple(ColCodec(**c) for c in d["cols"]),
        table_size=int(d["table_size"]),
        table_mtime_ns=int(d["table_mtime_ns"]), path=path)


def probe_packed(table_path: str, *,
                 path: Optional[str] = None) -> Optional[PackedMeta]:
    """Fresh packed sidecar for *table_path*, or None.

    Same contract as ``scan/index.py``'s probe: the stamp (source size +
    mtime_ns) must match the live table exactly, so any write to the
    table silently retires the packed representation."""
    p = path or packed_path_for(table_path)
    try:
        meta = load_meta(p)
        st = os.stat(table_path)
    except (OSError, ValueError):
        return None
    if meta.table_size != st.st_size \
            or meta.table_mtime_ns != st.st_mtime_ns:
        return None
    return meta


# -- numpy reference decoder (the kernels' correctness oracle) ------------

def _decode_region_numpy(words: np.ndarray, cm: ColCodec, n: int,
                         rpb: int) -> np.ndarray:
    r = words[cm.off:cm.off + cm.nwords].astype(np.uint32)
    if cm.codec == "raw":
        return r[:n].copy()
    if cm.codec == "bitpack":
        base = r[0]
        return (_unpack_bits(r[1:], cm.bits, n) + base).astype(np.uint32)
    if cm.codec == "dict":
        dvals = r[:cm.dsize]
        idx = _unpack_bits(r[cm.dsize:], cm.bits, n)
        return dvals[idx]
    # rle
    nr = int(r[0])
    vals = r[1:1 + nr]
    ends = r[1 + cm.rmax:1 + cm.rmax + nr].astype(np.int64)
    return np.repeat(vals, np.diff(ends, prepend=0))[:n]


def decode_pages_numpy(pages_u8: np.ndarray, meta: PackedMeta,
                       *, verify: bool = False
                       ) -> Tuple[List[np.ndarray], int]:
    """Decode packed pages to logical columns (pure numpy, independent of
    the jnp kernels — this is the oracle).  Pages that do not carry the
    data-block magic (the file header, zero padding) contribute no rows.
    Returns ``([col arrays in schema dtypes], n_rows)``."""
    pages = pages_from_bytes(pages_u8)
    words = pages.view(np.uint32).reshape(len(pages), _WORDS)
    outs: List[List[np.ndarray]] = [[] for _ in meta.cols]
    n_total = 0
    for p in range(len(pages)):
        if int(words[p, 0]) != CPK_MAGIC:
            continue
        n = int(words[p, 2])
        if verify:
            got = crc32c(words[p, HEADER_WORDS:].tobytes())
            if np.uint32(got) != words[p, 3]:
                raise ValueError(f"packed block {int(words[p, 1])}: "
                                 f"payload crc mismatch")
        n_total += n
        for c, cm in enumerate(meta.cols):
            outs[c].append(_decode_region_numpy(words[p], cm, n,
                                                meta.rows_per_block))
    cols = []
    for c, cm in enumerate(meta.cols):
        u = np.concatenate(outs[c]) if outs[c] \
            else np.empty(0, np.uint32)
        cols.append(u.view(np.dtype(meta.dtypes[c])))
    return cols, n_total


def decode_file_numpy(path: str,
                      meta: Optional[PackedMeta] = None
                      ) -> Tuple[List[np.ndarray], int]:
    meta = meta or load_meta(path)
    with open(path, "rb") as f:
        raw = f.read()
    return decode_pages_numpy(np.frombuffer(raw, np.uint8), meta)
