"""Multi-worker parallel scan: shared cursor across processes.

Capability analog of the pgsql Gather integration (`pgsql/nvme_strom.c:
1057-1112`): a DSM segment carries the scan descriptor (relation id, total
blocks, a shared atomic cursor, shared DMA counters) and every worker claims
disjoint block ranges from it.  Here the descriptor lives in
``multiprocessing.shared_memory`` and workers are processes running their
own :class:`~nvme_strom_tpu.scan.executor.TableScanner` against the shared
cursor — the same data-parallel shape, minus the PostgreSQL executor.
"""

from __future__ import annotations

import multiprocessing as mp
import struct
from multiprocessing import shared_memory
from typing import Callable, List, Optional, Tuple

import numpy as np

from .executor import TableScanner
from .heap import HeapSchema

__all__ = ["SharedCursor", "ParallelScanDesc", "parallel_scan"]

_HDR = struct.Struct("<qq")  # next_chunk, n_chunks


class SharedCursor:
    """Cross-process atomic chunk cursor (the DSM ``nsp_cblock`` analog).

    Safe under the ``spawn`` start method: workers re-attach by name and
    share the externally-provided lock (fork is unusable once a PJRT
    backend has initialized in the parent)."""

    def __init__(self, n_chunks: int, *, name: Optional[str] = None,
                 create: bool = True, lock=None):
        if create:
            self._shm = shared_memory.SharedMemory(create=True, size=_HDR.size)
            _HDR.pack_into(self._shm.buf, 0, 0, n_chunks)
        else:
            assert name is not None
            self._shm = shared_memory.SharedMemory(name=name)
        self._lock = lock if lock is not None else mp.Lock()
        self.name = self._shm.name

    @property
    def n_chunks(self) -> int:
        return _HDR.unpack_from(self._shm.buf, 0)[1]

    def claim(self, count: int) -> Tuple[int, int]:
        with self._lock:
            nxt, total = _HDR.unpack_from(self._shm.buf, 0)
            n = min(count, total - nxt)
            if n <= 0:
                return nxt, 0
            _HDR.pack_into(self._shm.buf, 0, nxt + n, total)
            return nxt, n

    def reset(self) -> None:
        """Rewind the shared cursor for a rescan (ExecReScan in parallel
        mode reinitializes the DSM block counter)."""
        with self._lock:
            _, total = _HDR.unpack_from(self._shm.buf, 0)
            _HDR.pack_into(self._shm.buf, 0, 0, total)

    def close(self, *, unlink: bool = False) -> None:
        self._shm.close()
        if unlink:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass


def _worker(path: str, cursor_name: str, lock, chunk_size: int,
            threshold: int, out_q) -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    from ..ops.filter_xla import scan_filter_step
    import jax.numpy as jnp
    cursor = SharedCursor(0, name=cursor_name, create=False, lock=lock)
    try:
        with TableScanner(path, chunk_size=chunk_size, cursor=cursor,
                          numa_bind=False) as scanner:
            acc = {"count": 0, "sum": 0, "pages": 0, "nr_ssd": 0, "nr_wb": 0}
            for batch in scanner.batches():
                out = scan_filter_step(batch.pages,
                                       jnp.asarray(threshold, jnp.int32))
                acc["count"] += int(out["count"])
                acc["sum"] += int(out["sum"])
                acc["pages"] += batch.pages.shape[0]
                acc["nr_ssd"] += batch.nr_ssd
                acc["nr_wb"] += batch.nr_wb
        out_q.put(("ok", acc))
    except BaseException as e:  # noqa: BLE001 — worker must always report
        out_q.put(("err", repr(e)))
    finally:
        cursor.close()


def parallel_scan(path: str, *, n_workers: int = 2,
                  chunk_size: int = 1 << 20,
                  threshold: int = 0) -> dict:
    """Scan *path* with ``n_workers`` processes sharing one cursor; returns
    summed aggregates (count/sum over the demo schema's filter)."""
    import os
    size = os.path.getsize(path)
    n_chunks = size // chunk_size
    ctx = mp.get_context("spawn")
    lock = ctx.Lock()
    cursor = SharedCursor(n_chunks, lock=lock)
    q = ctx.Queue()
    procs = [ctx.Process(target=_worker,
                         args=(path, cursor.name, lock, chunk_size,
                               threshold, q))
             for _ in range(n_workers)]
    try:
        for p in procs:
            p.start()
        results: List[dict] = []
        errors: List[str] = []
        for _ in procs:
            kind, payload = q.get(timeout=300)
            (results if kind == "ok" else errors).append(payload)
        for p in procs:
            p.join(timeout=60)
        if errors:
            raise RuntimeError(f"parallel scan worker failed: {errors[0]}")
        total = {k: sum(r[k] for r in results) for k in results[0]}
        total["workers"] = len(results)
        return total
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
        cursor.close(unlink=True)
