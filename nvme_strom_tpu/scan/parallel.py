"""Multi-worker parallel scan: shared cursor across processes.

Capability analog of the pgsql Gather integration (`pgsql/nvme_strom.c:
582-595,1057-1112`): a DSM segment carries the scan descriptor (relation
id, total blocks, a shared atomic cursor, shared DMA counters) and every
worker claims disjoint block ranges from it.  Here the descriptor lives
in ``multiprocessing.shared_memory`` and workers are processes running
their own :class:`~nvme_strom_tpu.scan.executor.TableScanner` against
the shared cursor — the same data-parallel shape, minus the PostgreSQL
executor.

Planner-integrated since round 5: ``Query(..., workers=N).run()`` (or
``run(workers=N)`` / ``sql_query(..., workers=N)`` / ``strom_query
--workers N``) ships a picklable spec (structured filters, SQL predicate
trees, terminal, resolved GROUP BY keys) to N spawned processes via
:func:`run_query_workers`; each rebuilds the query
(`Query._from_worker_spec`), scans chunks claimed from the shared
cursor with its OWN Session, and the leader folds the partial results
exactly like the batch fold (`Query._run_workers`).
"""

from __future__ import annotations

import multiprocessing as mp
import struct
from multiprocessing import shared_memory
from typing import List, Optional, Tuple

__all__ = ["SharedCursor", "run_query_workers", "parallel_scan"]

_HDR = struct.Struct("<qq")  # next_chunk, n_chunks


class SharedCursor:
    """Cross-process atomic chunk cursor (the DSM ``nsp_cblock`` analog).

    Safe under the ``spawn`` start method: workers re-attach by name and
    share the externally-provided lock (fork is unusable once a PJRT
    backend has initialized in the parent)."""

    def __init__(self, n_chunks: int, *, name: Optional[str] = None,
                 create: bool = True, lock=None):
        if create:
            self._shm = shared_memory.SharedMemory(create=True, size=_HDR.size)
            _HDR.pack_into(self._shm.buf, 0, 0, n_chunks)
        else:
            assert name is not None
            self._shm = shared_memory.SharedMemory(name=name)
        self._lock = lock if lock is not None else mp.Lock()
        self.name = self._shm.name

    @property
    def n_chunks(self) -> int:
        return _HDR.unpack_from(self._shm.buf, 0)[1]

    def claim(self, count: int) -> Tuple[int, int]:
        with self._lock:
            nxt, total = _HDR.unpack_from(self._shm.buf, 0)
            n = min(count, total - nxt)
            if n <= 0:
                return nxt, 0
            _HDR.pack_into(self._shm.buf, 0, nxt + n, total)
            return nxt, n

    def reset(self) -> None:
        """Rewind the shared cursor for a rescan (ExecReScan in parallel
        mode reinitializes the DSM block counter)."""
        with self._lock:
            _, total = _HDR.unpack_from(self._shm.buf, 0)
            _HDR.pack_into(self._shm.buf, 0, 0, total)

    def close(self, *, unlink: bool = False) -> None:
        self._shm.close()
        if unlink:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass


def _query_worker(spec: dict, cursor_name: str, lock, out_q) -> None:
    """Worker entry (spawned process): rebuild the query from the spec,
    scan shared-cursor chunks, report the picklable partial."""
    import os
    if spec.get("_test_crash_worker"):
        # test hook: die like an OOM-kill/segfault — no report, no
        # cleanup — so the leader's death detection is testable in CI
        os._exit(42)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    cursor = None
    try:
        # mirror the leader's runtime state BEFORE building anything:
        # the x64 flag changes accumulator widths (acc_dtypes) and the
        # config snapshot carries the scan/join knobs — a worker running
        # defaults would fold silently different partials
        import jax
        jax.config.update("jax_enable_x64", bool(spec.get("x64")))
        if spec.get("config") is not None:
            from ..config import config
            config.restore(spec["config"])
        cursor = SharedCursor(0, name=cursor_name, create=False,
                              lock=lock)
        from .query import Query
        q = Query._from_worker_spec(spec)
        out_q.put(("ok", q._run_worker_partial(spec, cursor)))
    except BaseException as e:  # noqa: BLE001 — worker must always report
        out_q.put(("err", repr(e)))
    finally:
        if cursor is not None:
            cursor.close()


def run_query_workers(spec: dict, n_workers: int, *,
                      timeout_s: float = 600.0) -> List[dict]:
    """Fan a worker spec out to *n_workers* spawned processes sharing one
    cursor; returns each worker's partial result (the leader folds).
    The cursor is sized by ``executor.cursor_chunk_count`` — the SAME
    formula ``TableScanner`` sizes its own cursor with."""
    import os

    from .executor import cursor_chunk_count
    if n_workers < 2:
        raise ValueError("run_query_workers needs >= 2 workers")
    size = os.path.getsize(spec["source"])
    total = cursor_chunk_count(size, spec["chunk_size"])
    ctx = mp.get_context("spawn")
    lock = ctx.Lock()
    cursor = SharedCursor(total, lock=lock)
    q = ctx.Queue()
    procs = [ctx.Process(target=_query_worker,
                         args=(spec, cursor.name, lock, q))
             for _ in range(n_workers)]
    import queue as _queue
    import time as _time
    try:
        for p in procs:
            p.start()
        results: List[dict] = []
        errors: List[str] = []
        # poll instead of one blocking get: a worker killed by the OOM
        # killer (or a segfault) never reports, and a bare
        # q.get(timeout=600) would sit out the whole deadline.  Short
        # get timeouts + liveness checks surface the death in seconds,
        # with a small grace window for the queue feeder thread to flush
        # a report that raced the exit.
        deadline = _time.monotonic() + timeout_s
        grace_until = None
        while len(results) + len(errors) < len(procs):
            try:
                kind, payload = q.get(timeout=0.25)
            except _queue.Empty:
                now = _time.monotonic()
                reported = len(results) + len(errors)
                if now > deadline:
                    raise RuntimeError(
                        f"parallel scan timed out after {timeout_s:.0f}s: "
                        f"{len(procs) - reported} worker(s) never reported")
                alive = sum(p.is_alive() for p in procs)
                if alive < len(procs) - reported:
                    if grace_until is None:
                        grace_until = now + 2.0
                    elif now > grace_until:
                        dead = [(p.pid, p.exitcode) for p in procs
                                if not p.is_alive()]
                        raise RuntimeError(
                            "parallel scan worker died without reporting "
                            f"(pid, exitcode of exited workers: {dead}); "
                            f"{reported}/{len(procs)} partials received")
                else:
                    grace_until = None
                continue
            (results if kind == "ok" else errors).append(payload)
        for p in procs:
            p.join(timeout=60)
        if errors:
            raise RuntimeError(f"parallel scan worker failed: {errors[0]}")
        return results
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
        cursor.close(unlink=True)


def parallel_scan(path: str, *, n_workers: int = 2,
                  chunk_size: int = 1 << 20,
                  threshold: int = 0) -> dict:
    """Back-compat demo face (subsumed by ``Query(..., workers=N)``):
    scan *path* with ``n_workers`` processes sharing one cursor over the
    demo filter (count rows with col0 > threshold, sum col1 over them);
    returns summed count/sum plus the worker count.  Unlike the old
    standalone harness this rides the planner-integrated path, so the
    sub-chunk tail IS covered."""
    from ..config import config
    from .heap import HeapSchema
    from .query import Query
    schema = HeapSchema(n_cols=2, visibility=True)
    q = Query(path, schema).where_range(0, threshold + 1, None) \
        .aggregate(cols=[1])
    prev = config.get("chunk_size")
    config.set("chunk_size", chunk_size)
    try:
        out = q.run(workers=n_workers)
    finally:
        config.set("chunk_size", prev)
    return {"count": int(out["count"]) if out else 0,
            "sum": int(out["sums"][0]) if out else 0,
            "workers": n_workers}
