from .heap import (HEAP_MAGIC, PAGE_SIZE, HeapSchema, build_heap_file,
                   pages_from_bytes)

__all__ = ["HEAP_MAGIC", "PAGE_SIZE", "HeapSchema", "build_heap_file",
           "pages_from_bytes"]
