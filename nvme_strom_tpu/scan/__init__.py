from .heap import (HEAP_MAGIC, PAGE_SIZE, HeapSchema, build_heap_file,
                   pages_from_bytes)
from .query import Query, QueryPlan

__all__ = ["HEAP_MAGIC", "PAGE_SIZE", "HeapSchema", "Query", "QueryPlan",
           "build_heap_file", "pages_from_bytes"]
