from .heap import (HEAP_MAGIC, PAGE_SIZE, HeapSchema, build_heap_file,
                   pages_from_bytes)
from .index import SortedIndex, build_index, open_index
from .query import Query, QueryPlan

__all__ = ["HEAP_MAGIC", "PAGE_SIZE", "HeapSchema", "Query", "QueryPlan",
           "SortedIndex", "build_heap_file", "build_index", "open_index",
           "pages_from_bytes"]
