"""TPU-native heap page format.

The reference scans PostgreSQL heap pages: 8KB blocks with line-pointer
arrays and variable-width tuples walked one at a time
(`pgsql/nvme_strom.c:941-979`).  That layout is pointer-chasing and
scalar — hostile to the MXU/VPU.  This framework's table format keeps the
8KB-block granularity (so the whole chunk/DMA machinery is shared) but lays
tuples out **columnar within the page**, fixed width, so a batch of pages
bitcasts to an int32 tensor and every predicate is a vectorized op:

``page[8192] = header[64B] | col0[T*4B] | col1[T*4B] | ... | pad``

header words (int32): [0]=magic [1]=page_id [2]=n_tuples [3]=n_cols
[4]=visibility_mode [5..15]=reserved.

Tuple *visibility* (the MVCC analog the reference arbitrates per tuple,
pgsql/nvme_strom.c:767-811) is a per-tuple bitmask column stored as the
LAST column when ``visibility_mode == 1``: a tuple counts only when its
mask word is nonzero.  ``visibility_mode == 0`` means all-visible (the
VM_ALL_VISIBLE fast path).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["PAGE_SIZE", "HEAP_MAGIC", "HEADER_BYTES", "HeapSchema",
           "build_heap_file", "pages_from_bytes", "validate_heap_header"]

PAGE_SIZE = 8192                  # BLCKSZ, matching the reference
HEADER_BYTES = 64
HEADER_WORDS = HEADER_BYTES // 4
HEAP_MAGIC = 0x53545250           # 'PRTS'


@dataclass(frozen=True)
class HeapSchema:
    """Fixed-width 4-byte column schema (int32 / float32 / uint32).

    ``dtypes`` — optional per-column dtype strings (default: all int32).
    Every dtype occupies one word, so layout is dtype-independent; typed
    decode is a bitcast in the XLA path."""

    n_cols: int
    visibility: bool = False       # append a per-tuple visibility column
    dtypes: Optional[tuple] = None

    def __post_init__(self):
        if self.dtypes is not None:
            if len(self.dtypes) != self.n_cols:
                raise ValueError(f"{len(self.dtypes)} dtypes for "
                                 f"{self.n_cols} columns")
            for d in self.dtypes:
                if np.dtype(d).itemsize != 4:
                    raise ValueError(f"column dtype {d} is not 4-byte")

    def col_dtype(self, c: int) -> np.dtype:
        return np.dtype(self.dtypes[c]) if self.dtypes else np.dtype(np.int32)

    @property
    def phys_cols(self) -> int:
        return self.n_cols + (1 if self.visibility else 0)

    @property
    def tuples_per_page(self) -> int:
        return (PAGE_SIZE - HEADER_BYTES) // (4 * self.phys_cols)

    def col_word_range(self, c: int):
        """(start, stop) word offsets of column *c* within a page."""
        t = self.tuples_per_page
        start = HEADER_WORDS + c * t
        return start, start + t


def build_pages(columns: Sequence[np.ndarray], schema: HeapSchema, *,
                visibility: Optional[np.ndarray] = None,
                start_page_id: int = 0) -> np.ndarray:
    """Pack column arrays (each shape (n_rows,), int32/float32) into pages.

    Returns a uint8 array of shape (n_pages, PAGE_SIZE)."""
    if len(columns) != schema.n_cols:
        raise ValueError(f"expected {schema.n_cols} columns, got {len(columns)}")
    n_rows = len(columns[0])
    for ci, c in enumerate(columns):
        if len(c) != n_rows:
            raise ValueError("ragged columns")
        if c.dtype.itemsize != 4:
            raise ValueError("columns must be 4-byte dtypes")
        if schema.dtypes is not None and c.dtype != schema.col_dtype(ci):
            raise ValueError(f"column {ci} dtype {c.dtype} != schema "
                             f"{schema.col_dtype(ci)}")
    if schema.visibility:
        if visibility is None:
            visibility = np.ones(n_rows, dtype=np.int32)
        if len(visibility) != n_rows:
            raise ValueError("visibility length mismatch")
    t = schema.tuples_per_page
    n_pages = max((n_rows + t - 1) // t, 1)
    pages = np.zeros((n_pages, PAGE_SIZE // 4), dtype=np.int32)
    pages[:, 0] = HEAP_MAGIC
    pages[:, 1] = np.arange(start_page_id, start_page_id + n_pages)
    pages[:, 3] = schema.n_cols
    pages[:, 4] = 1 if schema.visibility else 0
    for p in range(n_pages):
        lo, hi = p * t, min((p + 1) * t, n_rows)
        pages[p, 2] = hi - lo
        for ci in range(schema.n_cols):
            s, _ = schema.col_word_range(ci)
            pages[p, s:s + hi - lo] = columns[ci][lo:hi].view(np.int32)
        if schema.visibility:
            s, _ = schema.col_word_range(schema.n_cols)
            pages[p, s:s + hi - lo] = visibility[lo:hi].astype(np.int32)
    return pages.view(np.uint8).reshape(n_pages, PAGE_SIZE)


def build_heap_file(path: str, columns: Sequence[np.ndarray],
                    schema: HeapSchema, *,
                    visibility: Optional[np.ndarray] = None) -> int:
    """Write a heap file; returns number of pages."""
    pages = build_pages(columns, schema, visibility=visibility)
    with open(path, "wb") as f:
        f.write(pages.tobytes())
    return len(pages)


def validate_heap_header(path: str, schema: HeapSchema) -> None:
    """One 64-byte read checks the first page header against *schema*:
    magic, column count (header word 3), visibility mode (word 4) — the
    cheap guard that turns a wrong column count or a non-heap file into
    a clear error instead of silently garbled columns (pages carry their
    schema facts exactly so consumers CAN check; the reference trusts
    the catalog the same way, pgsql/nvme_strom.c:448-474).  Raises
    OSError (unreadable) or ValueError (mismatch)."""
    with open(path, "rb") as f:
        head = f.read(HEADER_BYTES)
    if len(head) < HEADER_BYTES:
        raise ValueError(f"{path}: not a heap file (short header)")
    w = np.frombuffer(head, np.int32)
    if int(w[0]) != HEAP_MAGIC:
        raise ValueError(f"{path}: bad heap magic "
                         f"0x{int(w[0]) & 0xffffffff:08x}")
    if int(w[3]) != schema.n_cols:
        raise ValueError(f"{path}: file pages carry {int(w[3])} columns, "
                         f"schema says {schema.n_cols}")
    vm = 1 if schema.visibility else 0
    if int(w[4]) != vm:
        raise ValueError(f"{path}: file visibility_mode {int(w[4])} != "
                         f"schema's {vm}")


def pages_from_bytes(raw: bytes | np.ndarray) -> np.ndarray:
    """View raw bytes as (n_pages, PAGE_SIZE) uint8 without copying."""
    arr = np.frombuffer(raw, dtype=np.uint8) if isinstance(raw, bytes) else raw
    if arr.size % PAGE_SIZE:
        raise ValueError(f"byte length {arr.size} not page-aligned")
    return arr.reshape(-1, PAGE_SIZE)


def read_column(pages: np.ndarray, schema: HeapSchema, c: int,
                dtype=None) -> np.ndarray:
    """Host-side column extraction (test oracle for the XLA kernels)."""
    dtype = dtype if dtype is not None else schema.col_dtype(c)
    words = pages.view(np.int32).reshape(pages.shape[0], PAGE_SIZE // 4)
    s, e = schema.col_word_range(c)
    out = []
    for p in range(pages.shape[0]):
        n = int(words[p, 2])
        out.append(words[p, s:s + n].view(dtype))
    return np.concatenate(out) if out else np.empty(0, dtype)
