"""TPU-native heap page format.

The reference scans PostgreSQL heap pages: 8KB blocks with line-pointer
arrays and variable-width tuples walked one at a time
(`pgsql/nvme_strom.c:941-979`).  That layout is pointer-chasing and
scalar — hostile to the MXU/VPU.  This framework's table format keeps the
8KB-block granularity (so the whole chunk/DMA machinery is shared) but lays
tuples out **columnar within the page**, fixed width, so a batch of pages
bitcasts to typed tensors and every predicate is a vectorized op:

``page[8192] = header[64B] | col regions | visibility | validity | pad``

header words (int32): [0]=magic [1]=page_id [2]=n_tuples [3]=n_cols
[4]=visibility_mode [5]=wide-column bitmask [6]=nullable bitmask
[7..15]=reserved.

Column regions sit in schema order; each holds ``T`` values of the
column's width (4 or 8 bytes — int32/uint32/float32/int64/float64,
round 5), 8-byte regions padded up to 8-byte file offsets so the
device decode is a pure bitcast.  Tuple *visibility* (the MVCC analog
the reference arbitrates per tuple, pgsql/nvme_strom.c:767-811) is a
per-tuple int32 mask column after the data regions when
``visibility_mode == 1``.  NULLABLE columns (round 5 — PG heap tuples
carry null bitmaps, `pgsql/nvme_strom.c:767-811` preserves them) each
append a VALIDITY bitmap after that: ``ceil(T/32)`` words, bit i set =
row i carries a real value (Arrow's convention); the stored word under
a NULL is zero, and NULL-awareness lives in the masks, never in
sentinel values.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["PAGE_SIZE", "HEAP_MAGIC", "HEADER_BYTES", "CHECKSUM_WORD",
           "HeapSchema", "build_heap_file", "pages_from_bytes",
           "validate_heap_header", "page_checksum",
           "verify_page_checksums"]

PAGE_SIZE = 8192                  # BLCKSZ, matching the reference
HEADER_BYTES = 64
HEADER_WORDS = HEADER_BYTES // 4
HEAP_MAGIC = 0x53545250           # 'PRTS'
#: header word carrying the page's crc32c (PR 1, torn-read detection) —
#: first of the reserved words [7..15]; 0 = unchecksummed (pre-PR-1 file,
#: or the 2^-32 crc that happens to be zero — treated as absent)
CHECKSUM_WORD = 7

_DTS_4 = (np.dtype(np.int32), np.dtype(np.uint32), np.dtype(np.float32))
_DTS_8 = (np.dtype(np.int64), np.dtype(np.float64))


# -- page checksums (PR 1) -------------------------------------------------
# crc32c (Castagnoli, the poly NVMe end-to-end protection and PG's data
# checksums use): the C wheel when the image carries one, else a
# table-driven software fallback — same polynomial, so files verify
# identically either way.
try:
    from google_crc32c import value as _crc32c          # C extension
except ImportError:   # pragma: no cover - depends on image
    try:
        from crc32c import crc32c as _crc32c
    except ImportError:
        _CRC32C_TABLE = []
        for _i in range(256):
            _c = _i
            for _ in range(8):
                _c = (_c >> 1) ^ (0x82F63B78 if _c & 1 else 0)
            _CRC32C_TABLE.append(_c)

        def _crc32c(data) -> int:
            crc = 0xFFFFFFFF
            for b in bytes(data):
                crc = (crc >> 8) ^ _CRC32C_TABLE[(crc ^ b) & 0xFF]
            return crc ^ 0xFFFFFFFF


def crc32c(data) -> int:
    """crc32c of an arbitrary buffer (the page-checksum polynomial) —
    the shared checksum for checkpoint leaves and write_verify
    read-back, so files verify identically whichever backend computed
    them."""
    return int(_crc32c(bytes(data)))


# incremental form (crc32c(a+b) == crc32c_update(crc32c(a), b)) for
# streaming verification over leaf spans that never assemble on host
try:
    from google_crc32c import extend as _crc32c_extend    # C extension

    def crc32c_update(crc: int, data) -> int:
        return int(_crc32c_extend(crc, bytes(data)))
except ImportError:   # pragma: no cover - depends on image
    try:
        from crc32c import crc32c as _crc32c_pkg

        def crc32c_update(crc: int, data) -> int:
            return int(_crc32c_pkg(bytes(data), crc))
    except ImportError:

        def crc32c_update(crc: int, data) -> int:
            c = crc ^ 0xFFFFFFFF
            for b in bytes(data):
                c = (c >> 8) ^ _CRC32C_TABLE[(c ^ b) & 0xFF]
            return c ^ 0xFFFFFFFF


def page_checksum(page) -> int:
    """crc32c of one page with its CHECKSUM_WORD zeroed (what the builder
    stores there and the verifier recomputes)."""
    buf = bytearray(bytes(page))
    if len(buf) != PAGE_SIZE:
        raise ValueError(f"page must be {PAGE_SIZE} bytes, got {len(buf)}")
    buf[CHECKSUM_WORD * 4:CHECKSUM_WORD * 4 + 4] = b"\0\0\0\0"
    return int(_crc32c(bytes(buf)))


def verify_page_checksums(data) -> List[int]:
    """Verify every whole heap page in *data* (bytes/memoryview/uint8
    array); returns the indices of pages whose stored crc32c mismatches.
    Pages without the heap magic or with a zero checksum word (legacy
    files) are skipped, so the check is safe to run over arbitrary chunk
    payloads.  A trailing partial page is ignored."""
    arr = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) \
        else data.reshape(-1)
    n = arr.size // PAGE_SIZE
    if n == 0:
        return []
    pages = arr[:n * PAGE_SIZE].reshape(n, PAGE_SIZE)
    words = pages.view(np.int32)
    stored = words[:, CHECKSUM_WORD].view(np.uint32)
    bad: List[int] = []
    for p in range(n):
        if int(words[p, 0]) != HEAP_MAGIC or int(stored[p]) == 0:
            continue
        if page_checksum(pages[p]) != int(stored[p]):
            bad.append(p)
    return bad


@lru_cache(maxsize=256)
def _layout(schema: "HeapSchema"):
    """(tuples_per_page, col word offsets, visibility offset|None,
    validity word offsets {col: off}) — the single layout derivation
    both the host builder and the device decode use."""
    widths = [schema.col_dtype(c).itemsize for c in range(schema.n_cols)]
    nullable = schema.nullable or (False,) * schema.n_cols
    fixed_words = sum(w // 4 for w in widths) \
        + (1 if schema.visibility else 0)

    def fits(t: int) -> Optional[tuple]:
        off = HEADER_WORDS
        col_off = []
        for w in widths:
            if w == 8 and off % 2:
                off += 1          # 8-byte regions start 8-aligned
            col_off.append(off)
            off += (w // 4) * t
        vis_off = None
        if schema.visibility:
            vis_off = off
            off += t
        nb = (t + 31) // 32
        valid_off = {}
        for c in range(schema.n_cols):
            if nullable[c]:
                valid_off[c] = off
                off += nb
        if off > PAGE_SIZE // 4:
            return None
        return t, tuple(col_off), vis_off, dict(valid_off)

    t = (PAGE_SIZE - HEADER_BYTES) * 8 // \
        (fixed_words * 32 + sum(nullable))
    while t > 0:
        got = fits(t)
        if got is not None:
            return got
        t -= 1
    raise ValueError("schema too wide for one page")


@dataclass(frozen=True)
class HeapSchema:
    """Fixed-width column schema.

    ``dtypes`` — optional per-column dtype strings (default: all
    int32); int32/uint32/float32 plus (round 5) int64/float64.
    ``nullable`` — optional per-column bools; nullable columns carry a
    validity bitmap per page."""

    n_cols: int
    visibility: bool = False       # append a per-tuple visibility column
    dtypes: Optional[tuple] = None
    nullable: Optional[tuple] = None

    def __post_init__(self):
        if self.dtypes is not None:
            if len(self.dtypes) != self.n_cols:
                raise ValueError(f"{len(self.dtypes)} dtypes for "
                                 f"{self.n_cols} columns")
            for d in self.dtypes:
                if np.dtype(d) not in _DTS_4 + _DTS_8:
                    raise ValueError(f"column dtype {d} not supported "
                                     f"(int32/uint32/float32/int64/"
                                     f"float64)")
        if self.nullable is not None:
            if len(self.nullable) != self.n_cols:
                raise ValueError(f"{len(self.nullable)} nullable flags "
                                 f"for {self.n_cols} columns")
            object.__setattr__(self, "nullable",
                               tuple(bool(b) for b in self.nullable))
        if (self.has_wide or any(self.nullable or ())) \
                and self.n_cols > 31:
            raise ValueError("wide/nullable schemas support up to 31 "
                             "columns (header bitmask width)")

    def col_dtype(self, c: int) -> np.dtype:
        return np.dtype(self.dtypes[c]) if self.dtypes else np.dtype(np.int32)

    def col_nullable(self, c: int) -> bool:
        return bool(self.nullable[c]) if self.nullable else False

    @property
    def has_wide(self) -> bool:
        return self.dtypes is not None and \
            any(np.dtype(d).itemsize == 8 for d in self.dtypes)

    @property
    def phys_cols(self) -> int:
        return self.n_cols + (1 if self.visibility else 0)

    @property
    def tuples_per_page(self) -> int:
        return _layout(self)[0]

    def col_word_range(self, c: int):
        """(start, stop) word offsets of column *c* within a page
        (``c == n_cols`` addresses the visibility column)."""
        t, col_off, vis_off, _valid = _layout(self)
        if c == self.n_cols:
            if vis_off is None:
                raise ValueError("schema has no visibility column")
            return vis_off, vis_off + t
        w = self.col_dtype(c).itemsize // 4
        return col_off[c], col_off[c] + w * t

    def validity_word_range(self, c: int):
        """(start, stop) word offsets of column *c*'s validity bitmap."""
        t, _col_off, _vis, valid = _layout(self)
        if c not in valid:
            raise ValueError(f"column {c} is not nullable")
        nb = (t + 31) // 32
        return valid[c], valid[c] + nb

    def _bitmask(self, pred) -> int:
        return sum(1 << c for c in range(self.n_cols) if pred(c))

    @property
    def wide_mask(self) -> int:
        return self._bitmask(lambda c: self.col_dtype(c).itemsize == 8)

    @property
    def null_mask(self) -> int:
        return self._bitmask(self.col_nullable)


def _pack_validity(mask: np.ndarray, t: int) -> np.ndarray:
    """(n,) present-bool -> ceil(t/32) int32 bitmap words; bit ``i % 32``
    of word ``i // 32`` set when row i holds a value — the same
    shift-and-mask the device decode applies."""
    nb = (t + 31) // 32
    bits = np.zeros(nb * 32, dtype=bool)
    bits[:len(mask)] = mask
    weights = (np.uint64(1) << np.arange(32, dtype=np.uint64))
    words = (bits.reshape(nb, 32).astype(np.uint64) * weights) \
        .sum(axis=1).astype(np.uint32)
    return words.view(np.int32)


def _unpack_validity(words: np.ndarray, n: int) -> np.ndarray:
    """Inverse of :func:`_pack_validity` for the first *n* rows."""
    w = words.astype(np.int64) & 0xFFFFFFFF
    bits = (w[:, None] >> np.arange(32, dtype=np.int64)[None, :]) & 1
    return bits.reshape(-1)[:n].astype(bool)


def build_pages(columns: Sequence[np.ndarray], schema: HeapSchema, *,
                visibility: Optional[np.ndarray] = None,
                nulls: Optional[dict] = None,
                start_page_id: int = 0) -> np.ndarray:
    """Pack column arrays (each shape (n_rows,), schema dtypes) into
    pages.  ``nulls`` — optional ``{col: (n_rows,) bool}`` NULL masks
    for nullable columns (True = NULL; stored word zeroed, validity bit
    cleared).  Returns a uint8 array of shape (n_pages, PAGE_SIZE)."""
    if len(columns) != schema.n_cols:
        raise ValueError(f"expected {schema.n_cols} columns, got {len(columns)}")
    nulls = dict(nulls or {})
    for c in nulls:
        if not schema.col_nullable(c):
            raise ValueError(f"column {c} is not nullable in the schema")
    n_rows = len(columns[0])
    for ci, c in enumerate(columns):
        if len(c) != n_rows:
            raise ValueError("ragged columns")
        if schema.dtypes is not None and c.dtype != schema.col_dtype(ci):
            raise ValueError(f"column {ci} dtype {c.dtype} != schema "
                             f"{schema.col_dtype(ci)}")
        if schema.dtypes is None and c.dtype.itemsize != 4:
            raise ValueError("columns must be 4-byte dtypes")
    if schema.visibility:
        if visibility is None:
            visibility = np.ones(n_rows, dtype=np.int32)
        if len(visibility) != n_rows:
            raise ValueError("visibility length mismatch")
    t, col_off, vis_off, valid_off = _layout(schema)
    n_pages = max((n_rows + t - 1) // t, 1)
    pages = np.zeros((n_pages, PAGE_SIZE // 4), dtype=np.int32)
    pages[:, 0] = HEAP_MAGIC
    pages[:, 1] = np.arange(start_page_id, start_page_id + n_pages)
    pages[:, 3] = schema.n_cols
    pages[:, 4] = 1 if schema.visibility else 0
    pages[:, 5] = schema.wide_mask
    pages[:, 6] = schema.null_mask
    nb = (t + 31) // 32
    for p in range(n_pages):
        lo, hi = p * t, min((p + 1) * t, n_rows)
        n = hi - lo
        pages[p, 2] = n
        for ci in range(schema.n_cols):
            vals = columns[ci][lo:hi]
            if ci in nulls:
                vals = np.where(nulls[ci][lo:hi],
                                vals.dtype.type(0), vals)
            w = schema.col_dtype(ci).itemsize // 4
            s = col_off[ci]
            pages[p, s:s + n * w] = vals.view(np.int32).reshape(-1)
        if schema.visibility:
            pages[p, vis_off:vis_off + n] = \
                visibility[lo:hi].astype(np.int32)
        for ci, s in valid_off.items():
            present = np.ones(n, dtype=bool)
            if ci in nulls:
                present = ~np.asarray(nulls[ci][lo:hi], dtype=bool)
            pages[p, s:s + nb] = _pack_validity(present, t)
    out = pages.view(np.uint8).reshape(n_pages, PAGE_SIZE)
    # stamp per-page crc32c into the reserved header word so torn/corrupt
    # reads are detectable end to end (config checksum_verify); computed
    # last, over the page with the word still zero
    csum = pages.view(np.uint32).reshape(n_pages, PAGE_SIZE // 4)
    for p in range(n_pages):
        csum[p, CHECKSUM_WORD] = page_checksum(out[p])
    return out


def build_heap_file(path: str, columns: Sequence[np.ndarray],
                    schema: HeapSchema, *,
                    visibility: Optional[np.ndarray] = None,
                    nulls: Optional[dict] = None) -> int:
    """Write a heap file; returns number of pages."""
    pages = build_pages(columns, schema, visibility=visibility,
                        nulls=nulls)
    with open(path, "wb") as f:
        f.write(pages.tobytes())
    return len(pages)


def validate_heap_header(path: str, schema: HeapSchema) -> None:
    """One 64-byte read checks the first page header against *schema*:
    magic, column count (header word 3), visibility mode (word 4), and
    the wide/nullable bitmasks (words 5/6) — the cheap guard that turns
    a wrong column count or a non-heap file into a clear error instead
    of silently garbled columns (pages carry their schema facts exactly
    so consumers CAN check; the reference trusts the catalog the same
    way, pgsql/nvme_strom.c:448-474).  Raises OSError (unreadable) or
    ValueError (mismatch)."""
    with open(path, "rb") as f:
        head = f.read(HEADER_BYTES)
    if len(head) < HEADER_BYTES:
        raise ValueError(f"{path}: not a heap file (short header)")
    w = np.frombuffer(head, np.int32)
    if int(w[0]) != HEAP_MAGIC:
        raise ValueError(f"{path}: bad heap magic "
                         f"0x{int(w[0]) & 0xffffffff:08x}")
    if int(w[3]) != schema.n_cols:
        raise ValueError(f"{path}: file pages carry {int(w[3])} columns, "
                         f"schema says {schema.n_cols}")
    vm = 1 if schema.visibility else 0
    if int(w[4]) != vm:
        raise ValueError(f"{path}: file visibility_mode {int(w[4])} != "
                         f"schema's {vm}")
    if int(w[5]) != schema.wide_mask:
        raise ValueError(f"{path}: file wide-column mask 0x{int(w[5]):x}"
                         f" != schema's 0x{schema.wide_mask:x}")
    if int(w[6]) != schema.null_mask:
        raise ValueError(f"{path}: file nullable mask 0x{int(w[6]):x} "
                         f"!= schema's 0x{schema.null_mask:x}")


def pages_from_bytes(raw: bytes | np.ndarray) -> np.ndarray:
    """View raw bytes as (n_pages, PAGE_SIZE) uint8 without copying."""
    arr = np.frombuffer(raw, dtype=np.uint8) if isinstance(raw, bytes) else raw
    if arr.size % PAGE_SIZE:
        raise ValueError(f"byte length {arr.size} not page-aligned")
    return arr.reshape(-1, PAGE_SIZE)


def read_column(pages: np.ndarray, schema: HeapSchema, c: int,
                dtype=None) -> np.ndarray:
    """Host-side column extraction (test oracle for the XLA kernels)."""
    dtype = dtype if dtype is not None else schema.col_dtype(c)
    words = pages.view(np.int32).reshape(pages.shape[0], PAGE_SIZE // 4)
    s, e = schema.col_word_range(c)
    out = []
    for p in range(pages.shape[0]):
        n = int(words[p, 2])
        w = np.dtype(dtype).itemsize // 4
        out.append(words[p, s:s + n * w].view(dtype))
    return np.concatenate(out) if out else np.empty(0, dtype)


def read_nulls(pages: np.ndarray, schema: HeapSchema,
               c: int) -> np.ndarray:
    """Host-side NULL-mask extraction (True = NULL) — the oracle twin
    of :func:`read_column` for nullable columns."""
    words = pages.view(np.int32).reshape(pages.shape[0], PAGE_SIZE // 4)
    s, e = schema.validity_word_range(c)
    out = []
    for p in range(pages.shape[0]):
        n = int(words[p, 2])
        out.append(~_unpack_validity(words[p, s:e], n))
    return np.concatenate(out) if out else np.empty(0, bool)
