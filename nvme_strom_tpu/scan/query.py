"""Declarative query layer: plan → stream → fold, transparently.

The reference's end-user surface is SQL-transparent — a query planner hook
decides per table whether the direct path is worth it and swaps in the
"NVMe Strom" CustomScan without the user changing a line of SQL
(`pgsql/nvme_strom.c:1642-1667`, cost model `:448-633`).  This module is
that surface for the TPU framework: one :class:`Query` builder that

* plans the access path (direct engine scan vs buffered VFS) with the
  planner's threshold + cost model (`scan/planner.py`),
* plans the compute kernel (Pallas single-pass vs XLA) by backend and
  operator support,
* executes by streaming batches through the async ring
  (:class:`..scan.executor.TableScanner`) or, given a mesh, through the
  sharded batch stream (:func:`..parallel.stream.distributed_scan_filter`)
  where XLA inserts the cross-device collectives,

and :meth:`Query.explain` shows the chosen plan the way ``EXPLAIN`` shows
the reference's custom scan node.

One terminal operator per query (it is one scan node): ``select`` |
``aggregate`` | ``group_by`` | ``top_k`` | ``order_by`` | ``quantiles``
| ``count_distinct`` | ``join``.  Predicates are plain jnp lambdas over
decoded columns — ``lambda cols: cols[0] > 10``.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import os
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..api import StromError
from ..scan.heap import PAGE_SIZE, HeapSchema
from .planner import (capability_cache, cost_direct_scan, cost_vfs_scan,
                      should_use_direct_scan)

__all__ = ["Query", "QueryPlan"]

_PALLAS_MAX_GROUPS = 64   # static unroll bound (ops/groupby_pallas.py)


@functools.lru_cache(maxsize=64)
def _fetch_gather_fn(schema: HeapSchema, cols: tuple):
    """Jitted point-lookup gather, cached per (schema, cols) so repeated
    fetches hit the jit cache instead of recompiling decode_pages (a
    per-call closure would make every sub-ms lookup pay a compile)."""
    import jax

    from ..ops.filter_xla import decode_pages

    @jax.jit
    def gather(pages_u8, page_idx, slot):
        dcols, valid = decode_pages(pages_u8, schema)
        out = {f"col{c}": dcols[c][page_idx, slot] for c in cols}
        for c in cols:
            if c in dcols.nulls:     # True = NULL (round 5)
                out[f"null{c}"] = dcols.nulls[c][page_idx, slot]
        out["valid"] = valid[page_idx, slot]
        return out

    return gather


class _ScanLimitReached(Exception):
    """Private control flow: the gather collected ``LIMIT`` rows early and
    the scan can stop issuing DMA (the executor stops pulling tuples once
    the plan's limit is satisfied)."""


class _GroupSpill(Exception):
    """Private control flow: key discovery crossed ``max_groups`` on a
    shape the sorted-aggregation path can serve (1-2 key columns) — the
    runner reroutes instead of failing with ENOMEM."""

    def __init__(self, seen: int):
        self.seen = seen
        super().__init__(f"group key discovery passed {seen} distinct")


class _HostCols(dict):
    """Host-side column mapping that quacks like the device decode's
    ``Cols`` for predicate evaluation: ``cols[c]`` values plus
    ``cols.nulls`` masks — the index-path recheck must see the same
    NULL facts the scan kernels see (review finding: a plain dict
    dropped them, and NULL rows' stored zeros matched residuals)."""

    def __init__(self, items, nulls=None):
        super().__init__(items)
        self.nulls = dict(nulls or {})


class _SortedGroupAcc:
    """Running sorted-aggregation state for the GROUP BY spill path:
    a sorted packed-key array plus per-key count/sums/sumsqs/mins/maxs,
    merged batch by batch — footprint O(distinct keys).  Accumulator
    dtypes follow :func:`..ops.groupby.acc_dtypes` exactly so the spill
    path and the one-hot kernels cannot drift (int sums wrap at the
    same width on both)."""

    def __init__(self, n_vals: int, acc_np, sq_np, lo, hi, cap: int):
        self.V, self.cap = n_vals, cap
        self.acc_np, self.sq_np, self.lo, self.hi = acc_np, sq_np, lo, hi
        self.keys: Optional[np.ndarray] = None
        self.count = self.sums = self.sumsqs = None
        self.mins = self.maxs = None

    def _batch_partial(self, kv: np.ndarray, vals: np.ndarray):
        """Sort one batch's (keys, (V, n) values) and segment-reduce."""
        order = np.argsort(kv, kind="stable")
        kv, vals = kv[order], vals[:, order]
        uk, starts = np.unique(kv, return_index=True)
        count = np.diff(np.append(starts, len(kv))).astype(np.int64)
        av = vals.astype(self.acc_np)
        sums = np.add.reduceat(av, starts, axis=1)
        fv = vals.astype(np.float64)
        sumsqs = np.add.reduceat(fv * fv, starts,
                                 axis=1).astype(self.sq_np)
        mins = np.minimum.reduceat(vals, starts, axis=1)
        maxs = np.maximum.reduceat(vals, starts, axis=1)
        return uk, count, sums, sumsqs, mins, maxs

    def add_batch(self, kv: np.ndarray, vals: np.ndarray) -> None:
        if not len(kv):
            return
        self.merge_state(dict(zip(
            ("keys", "count", "sums", "sumsqs", "mins", "maxs"),
            self._batch_partial(kv, vals))))

    def merge_state(self, st: dict) -> None:
        """Merge another sorted partial (a batch's, or a worker's whole
        state) into this one."""
        uk = st["keys"]
        if uk is None or not len(uk):
            return
        if self.keys is None:
            self.keys = uk
            self.count, self.sums = st["count"], st["sums"]
            self.sumsqs = st["sumsqs"]
            self.mins, self.maxs = st["mins"], st["maxs"]
        else:
            merged = np.union1d(self.keys, uk)
            if len(merged) > self.cap:
                raise StromError(12, f"group_by_cols: {len(merged)} "
                                     f"distinct keys exceed even the "
                                     f"sorted-aggregation cap "
                                     f"{self.cap} (unbounded key set)")
            io = np.searchsorted(merged, self.keys)
            iN = np.searchsorted(merged, uk)
            g = len(merged)
            count = np.zeros(g, np.int64)
            count[io] = self.count
            np.add.at(count, iN, st["count"])
            sums = np.zeros((self.V, g), self.acc_np)
            sumsqs = np.zeros((self.V, g), self.sq_np)
            mins = np.full((self.V, g), self.hi)
            maxs = np.full((self.V, g), self.lo)
            sums[:, io] = self.sums
            sumsqs[:, io] = self.sumsqs
            mins[:, io] = self.mins
            maxs[:, io] = self.maxs
            for v in range(self.V):
                np.add.at(sums[v], iN, st["sums"][v])
                np.add.at(sumsqs[v], iN, st["sumsqs"][v])
                np.minimum.at(mins[v], iN, st["mins"][v])
                np.maximum.at(maxs[v], iN, st["maxs"][v])
            self.keys, self.count = merged, count
            self.sums, self.sumsqs = sums, sumsqs
            self.mins, self.maxs = mins, maxs

    def state(self) -> dict:
        """Picklable state (the worker's return value / the leader's
        fold input) — empty-scan state is a zero-group result."""
        if self.keys is None:
            z = np.zeros(0, np.int64)
            return {"keys": z, "count": z,
                    "sums": np.zeros((self.V, 0), self.acc_np),
                    "sumsqs": np.zeros((self.V, 0), self.sq_np),
                    "mins": np.zeros((self.V, 0)),
                    "maxs": np.zeros((self.V, 0))}
        return {"keys": self.keys, "count": self.count,
                "sums": self.sums, "sumsqs": self.sumsqs,
                "mins": self.mins, "maxs": self.maxs}


@dataclass(frozen=True)
class QueryPlan:
    """What ``run()`` will do, decided before any I/O (EXPLAIN analog)."""
    operator: str          # aggregate | group_by | top_k | join | ...
    access_path: str       # direct | vfs | index
    kernel: str            # pallas | xla
    mode: str              # local | mesh
    n_pages: int
    cost_direct: float
    cost_vfs: float
    reason: str
    join_strategy: Optional[str] = None  # broadcast | partitioned(N)
    workers: int = 0       # parallel worker processes (0 = serial)
    cache_hit_ratio: float = 0.0  # expected residency-tier hit fraction
    hbm_hit_ratio: float = 0.0    # expected DEVICE-tier hit fraction
    pushdown: str = ""     # "" | chip | host | raw (packed-sidecar scan)

    def __str__(self) -> str:
        par = f", workers={self.workers}" if self.workers else ""
        cache = (f"  cache-resident: ~{self.cache_hit_ratio:.0%}"
                 if self.cache_hit_ratio > 0 else "")
        cache += (f"  hbm-resident: ~{self.hbm_hit_ratio:.0%}"
                  if self.hbm_hit_ratio > 0 else "")
        return (f"{self.operator} scan  [{self.access_path} path, "
                f"{self.kernel} kernel, {self.mode}{par}]\n"
                f"  pages: {self.n_pages}  cost: direct={self.cost_direct:.0f} "
                f"vfs={self.cost_vfs:.0f}{cache}\n"
                f"  {self.reason}")


class Query:
    """Fluent scan builder over one heap source.

    >>> q = (Query("/data/t.heap", schema)
    ...      .where(lambda cols: cols[0] > 10)
    ...      .group_by(lambda cols: cols[1] % 8, 8, agg_cols=[0]))
    >>> print(q.explain())
    >>> out = q.run()
    """

    def __init__(self, source, schema: HeapSchema, *,
                 stripe_chunk_size: int = 512 << 10, workers: int = 0):
        if isinstance(source, os.PathLike):
            source = str(source)
        elif isinstance(source, (list, tuple)):
            source = [str(p) for p in source]
        self.source = source
        self.schema = schema
        self._stripe_chunk = stripe_chunk_size
        self._workers = int(workers)   # >= 2: parallel worker processes
        self._pred_trees: List[tuple] = []   # picklable predicate trees
        self._opaque_pred = False            # a where() lambda w/o tree
        self._pred: Optional[Callable] = None
        self._residual: Optional[Callable] = None  # index-path recheck
        self._op = "aggregate"
        self._terminal_set = False
        self._agg_cols: Optional[Sequence[int]] = None
        self._agg_exprs: Optional[list] = None   # expression sums
        self._star: Optional[dict] = None        # multi-dim star join
        self._star_resolved: Optional[list] = None
        self._group: Optional[tuple] = None
        self._topk: Optional[tuple] = None
        self._order: Optional[tuple] = None
        self._join: Optional[tuple] = None
        self._join_src: Optional[tuple] = None  # on-disk build side
        self._join_how: str = "inner"           # inner | left | semi | anti
        self._group_cols: Optional[tuple] = None  # value-keyed GROUP BY
        self._select: Optional[tuple] = None
        self._quantiles: Optional[List[float]] = None
        self._eq: Optional[tuple] = None     # structured equality (col, v)
        self._range: Optional[tuple] = None  # structured range (col, lo, hi)
        self._in: Optional[tuple] = None     # structured IN (col, members)

    # -- builders -----------------------------------------------------------
    def where(self, predicate: Callable, *, _tree=None) -> "Query":
        """Row filter: ``predicate(cols) -> (B, T) bool`` (jnp ops only).

        Chained filters COMPOSE as a conjunction (the SQL-builder
        convention): ``where(a).where(b)`` selects rows passing both.
        Composed onto a STRUCTURED filter (:meth:`where_eq` /
        :meth:`where_range` / :meth:`where_in`), the predicate becomes a
        RESIDUAL — the seqscan applies the conjunction and the index
        path RECHECKS index-resolved rows against it (PG's Index Cond +
        Filter shape), so adding a predicate never demotes an
        index-capable query to a seqscan.  The structured setters
        replace the WHOLE filter (they define a new index condition).

        ``_tree`` (internal, set by the SQL facade) carries the
        predicate's picklable condition tree so worker processes can
        reconstruct it; a bare lambda marks the query non-parallel."""
        if _tree is not None:
            self._pred_trees.append(_tree)
        else:
            self._opaque_pred = True
        if self._pred is not None:
            old = self._pred
            self._pred = lambda cols: old(cols) & predicate(cols)
            if self._index_col() is not None:
                prev = self._residual
                self._residual = predicate if prev is None else \
                    (lambda cols, p=prev: p(cols) & predicate(cols))
            return self
        self._pred = predicate
        return self

    def _null_guard(self, pred, *cols_):
        """SQL comparison semantics on nullable columns: NULL cmp x is
        never true — wrap a structured predicate so NULL rows of the
        referenced columns can't match (their STORED word is 0, which a
        bare ``col == 0`` would otherwise select)."""
        nn = tuple(c for c in cols_ if self.schema.col_nullable(c))
        if not nn:
            return pred

        def wrapped(cols, base=pred, nn=nn):
            m = base(cols)
            for c in nn:
                m = m & ~cols.nulls[c]
            return m
        return wrapped

    def _set_structured(self, *, eq=None, rng=None, members=None) -> None:
        """Install exactly one structured filter (the others clear; a
        stale residual from a previous filter generation must never
        survive into the new one's index recheck)."""
        self._eq = eq
        self._range = rng
        self._in = members
        self._residual = None
        # the structured setters replace the WHOLE filter — any prior
        # opaque where() is gone, so the query is shippable again
        self._pred_trees = []
        self._opaque_pred = False

    def where_eq(self, col: int, value) -> "Query":
        """Structured equality filter: ``col == value``.  Unlike the
        opaque :meth:`where` lambda, the planner can SEE this one — when
        a fresh sorted index sidecar exists for *col* (built by
        :func:`..scan.index.build_index`), a :meth:`select` runs as an
        INDEX SCAN touching only matching pages; every other terminal
        (and a missing/stale index) falls back to the filtered seqscan,
        the way the reference's planner hook transparently swaps access
        paths (`pgsql/nvme_strom.c:1642-1667`).

        The literal is normalized to the COLUMN dtype up front so both
        access paths agree: a float literal against a float32 column
        compares as float32 (``0.1`` matches stored ``float32(0.1)``),
        and a non-integral literal against an integer column matches
        nothing — on the seqscan AND the index.

        **Composite equality**: *col* may be a pair ``(c0, c1)`` with
        *value* a matching pair ``(v0, v1)`` — SQL's
        ``c0 = v0 AND c1 = v1``.  With a fresh composite sidecar
        (``build_index(..., (c0, c1))``) the pair resolves in ONE packed-
        key probe; otherwise it seqscans with the conjunction."""
        if isinstance(col, (tuple, list)):
            if len(col) != 2 or not isinstance(value, (tuple, list)) \
                    or len(value) != 2:
                raise StromError(22, "composite where_eq takes a column "
                                     "PAIR and a value PAIR")
            c0, c1 = int(col[0]), int(col[1])
            for c in (c0, c1):
                if not 0 <= c < self.schema.n_cols:
                    raise StromError(22, f"where_eq column {c} out of range")
            v0 = self._representable(self.schema.col_dtype(c0), value[0])
            v1 = self._representable(self.schema.col_dtype(c1), value[1])
            if v0 is None or v1 is None:
                self._pred = lambda cols: cols[c0] != cols[c0]
                self._set_structured(eq=((c0, c1), None))  # index: empty
            else:
                self._pred = self._null_guard(
                    lambda cols: (cols[c0] == v0) & (cols[c1] == v1),
                    c0, c1)
                self._set_structured(eq=((c0, c1), (v0, v1)))
            return self
        if not 0 <= col < self.schema.n_cols:
            raise StromError(22, f"where_eq column {col} out of range")
        dt = self.schema.col_dtype(col)
        v = self._representable(dt, value)
        if v is None:
            # the literal has no exact representative in the column dtype
            # (non-integral or out-of-range vs int, e.g. 7.5 or 2**40):
            # SQL says no row matches — on BOTH paths, never a wraparound
            self._pred = lambda cols: cols[col] != cols[col]
            self._set_structured(eq=(int(col), None))  # index: empty
        else:
            self._pred = self._null_guard(
                lambda cols: cols[col] == v, col)
            self._set_structured(eq=(int(col), v))
        return self

    def where_in(self, col: int, values) -> "Query":
        """Structured membership filter: ``col IN values`` (SQL IN).
        Planner-visible like :meth:`where_eq`; with a fresh sidecar the
        index resolves every member's positions.  Members with no exact
        representative in the column dtype (7.5 against int32) can match
        no row and simply drop out."""
        if not 0 <= col < self.schema.n_cols:
            raise StromError(22, f"where_in column {col} out of range")
        dt = self.schema.col_dtype(col)
        reps = [self._representable(dt, v) for v in values]
        members = np.unique(np.array([r for r in reps if r is not None],
                                     dt))
        if dt.kind == "f":
            # a NaN member can never equal any row (IEEE; the seqscan's
            # isin agrees) — drop it so the index path cannot disagree
            # either (searchsorted would bracket NaN keys if a sidecar
            # ever carried them, e.g. one built outside build_index)
            members = members[~np.isnan(members)]
        if len(members) == 0:
            # identically False even for NaN rows (x != x alone would
            # select NaN on a float column)
            self._pred = lambda cols: (cols[col] == cols[col]) \
                & (cols[col] != cols[col])
            self._set_structured(members=(int(col), np.zeros(0, dt)))
            return self

        def pred(cols):
            import jax.numpy as jnp
            return jnp.isin(cols[col], members)

        self._pred = self._null_guard(pred, col)
        self._set_structured(members=(int(col), members))
        return self

    @staticmethod
    def _representable(dt: np.dtype, value):
        """The literal as an exact np scalar of *dt*, or None when no
        such value exists (non-integral/out-of-range against an int
        column — astype would silently WRAP, changing which rows match).
        Float columns always cast (the jnp weak-typing semantics the
        seqscan applies)."""
        if dt.kind in "iu":
            f = float(value)
            if not np.isfinite(f) or f != int(f):
                return None
            i = int(value)
            info = np.iinfo(dt)
            if not info.min <= i <= info.max:
                return None
            return dt.type(i)
        return dt.type(float(value))

    def where_range(self, col: int, lo=None, hi=None) -> "Query":
        """Structured range filter: ``lo <= col <= hi`` (either bound may
        be None for open-ended).  Planner-visible like :meth:`where_eq`:
        a fresh sidecar turns a :meth:`select` into an index RANGE scan
        reading only matching pages; everything else seqscans with the
        filter."""
        if not 0 <= col < self.schema.n_cols:
            raise StromError(22, f"where_range column {col} out of range")
        if lo is None and hi is None:
            raise StromError(22, "where_range needs at least one bound")
        dt = self.schema.col_dtype(col)
        # normalize bounds so the index searchsorted and the seqscan
        # predicate agree (and never overflow):
        #  - float column: bounds cast to the column dtype (the seqscan's
        #    weak-typing would compare at float32, so the index must too)
        #  - int column: fractional bounds tighten to the nearest integer
        #    (7.5 means ">= 8" / "<= 7") as exact dt scalars, so the
        #    seqscan (float32 weak typing) and the index searchsorted
        #    (float64) can never disagree at magnitudes > 2^24; bounds
        #    beyond the dtype's range clamp to open / empty instead of
        #    wrapping or raising
        never = False
        if dt.kind == "f":
            nlo = None if lo is None else dt.type(float(lo))
            nhi = None if hi is None else dt.type(float(hi))
        else:
            info = np.iinfo(dt)
            nlo = nhi = None
            if lo is not None:
                if float(lo) > info.max:
                    never = True           # nothing can be >= lo
                else:
                    ilo = int(math.ceil(float(lo)))
                    if ilo > info.min:
                        nlo = dt.type(min(ilo, info.max))
            if hi is not None and not never:
                if float(hi) < info.min:
                    never = True           # nothing can be <= hi
                else:
                    ihi = int(math.floor(float(hi)))
                    if ihi < info.max:
                        nhi = dt.type(max(ihi, info.min))
        if never:
            # an empty range encodes "never": lo > hi on both paths
            nlo, nhi = dt.type(1), dt.type(0)

        def pred(cols):
            m = cols[col] == cols[col] if dt.kind != "f" \
                else ~(cols[col] != cols[col])   # NaN rows never match
            if nlo is not None:
                m = m & (cols[col] >= nlo)
            if nhi is not None:
                m = m & (cols[col] <= nhi)
            return m

        self._pred = self._null_guard(pred, col)
        self._set_structured(rng=(int(col), nlo, nhi))
        return self

    def select(self, cols: Optional[Sequence[int]] = None, *,
               limit: Optional[int] = None, offset: int = 0) -> "Query":
        """Terminal: materialize the selected rows themselves — projected
        column values + global row positions, the face the reference scan
        actually exposes (tuples handed back to the executor,
        `pgsql/nvme_strom.c:941-979`).  ``cols=None`` projects every
        column.  ``limit`` stops the scan early once enough rows are
        gathered; row order is physical arrival order (SQL without ORDER
        BY — use :meth:`order_by`/:meth:`top_k` for ordered heads)."""
        self._require_no_terminal()
        if limit is not None and limit < 0:
            raise StromError(22, "select limit must be >= 0")
        if offset < 0:
            raise StromError(22, "select offset must be >= 0")
        self._op = "select"
        self._terminal_set = True
        self._select = (None if cols is None else [int(c) for c in cols],
                        limit, int(offset))
        return self

    def aggregate(self, cols: Optional[Sequence[int]] = None) -> "Query":
        """Terminal: selected-row count + per-column masked sums."""
        self._require_no_terminal()
        self._op = "aggregate"
        self._terminal_set = True
        self._agg_cols = cols
        return self

    def group_by(self, key_fn: Callable, n_groups: int, *,
                 agg_cols: Optional[Sequence[int]] = None,
                 having: Optional[Callable] = None) -> "Query":
        """Terminal: per-group count/sum/min/max/avg/var/stddev.
        ``key_fn(cols) -> (B, T) int32`` ids in ``[0, n_groups)``.

        ``having(groups) -> (G,) bool`` filters groups AFTER aggregation
        (SQL HAVING): it receives the finished numpy result
        (``count (G,)``, ``sums/sumsqs/mins/maxs/avgs/vars/stds (V, G)``)
        and surviving groups are compressed out, their original ids in
        ``"groups"``."""
        self._require_no_terminal()
        self._op = "group_by"
        self._terminal_set = True
        self._group = (key_fn, int(n_groups), agg_cols, having)
        return self

    def group_by_cols(self, key_cols, *,
                      agg_cols: Optional[Sequence[int]] = None,
                      having: Optional[Callable] = None,
                      max_groups: int = 1 << 16) -> "Query":
        """Terminal: SQL ``GROUP BY col[, col2]`` over actual column
        VALUES — no key function, no group-count guess.  Two passes:
        the distinct key set is discovered first (from a fresh sidecar
        at zero table I/O when one exists, else a streamed projection
        scan), then aggregation rides the normal GROUP BY kernels with
        a ``searchsorted`` key function over the discovered keys.

        Result = :meth:`group_by`'s (count/sums/mins/maxs/avgs/...)
        plus ``key_cols``: one array per key column, aligned with the
        surviving groups — the SELECT-list face SQL gives GROUP BY.
        Groups that select no rows are dropped (SQL semantics); *having*
        then filters like :meth:`group_by`'s.  One or two integer
        columns; discovery beyond *max_groups* distinct keys fails with
        ENOMEM instead of silently truncating."""
        self._require_no_terminal()
        cols_ = [int(c) for c in (key_cols if isinstance(
            key_cols, (tuple, list)) else [key_cols])]
        if not 1 <= len(cols_) <= 4:
            raise StromError(22, "group_by_cols takes 1-4 key columns")
        for c in cols_:
            if not 0 <= c < self.schema.n_cols:
                raise StromError(22, f"group_by_cols column {c} out of "
                                     f"range")
            if self.schema.col_dtype(c).kind not in "iu" \
                    or self.schema.col_dtype(c).itemsize != 4:
                raise StromError(22, "group_by_cols keys must be 4-byte "
                                     "integer columns")
            if self.schema.col_nullable(c):
                raise StromError(22, f"group_by_cols: c{c} is nullable "
                                     f"(NULL group keys are outside "
                                     f"this subset)")
        if max_groups < 1:
            raise StromError(22, "max_groups must be >= 1")
        self._op = "group_by"
        self._terminal_set = True
        # key_fn None = unresolved; run() discovers the keys first
        self._group = (None, 0, agg_cols, None)
        self._group_cols = (cols_, agg_cols, having, int(max_groups))
        return self

    def _resolve_group_keys(self, session, device) -> None:
        """Pass 1 of :meth:`group_by_cols`: discover the sorted distinct
        key set, then install the derived ``searchsorted`` key function,
        the group count, and the composed HAVING (empty groups dropped —
        discovery may be a SUPERSET of the selected rows' keys when it
        comes from a sidecar) into ``self._group``."""
        self._install_group_keys(self._discover_group_keys(session,
                                                           device))

    def _discover_group_keys(self, session, device) -> np.ndarray:
        """Discovery half of :meth:`_resolve_group_keys`: the sorted
        distinct key set (packed uint64 for pairs, (g, N) lex rows for
        3-4 keys) from a fresh sidecar at zero table I/O, else a
        streamed projection scan.  Raises :class:`_GroupSpill` past
        ``max_groups`` when the sorted-aggregation fallback can serve
        this shape (run() catches it); ENOMEM otherwise."""
        from .index import pack_pair
        cols_, agg, user_having, max_groups = self._group_cols
        dts = [self.schema.col_dtype(c) for c in cols_]
        discovered = None
        if isinstance(self.source, str) and len(cols_) <= 2:
            # fresh sidecar shortcut: the distinct keys are the sorted
            # sidecar's uniques — zero table I/O.  Composite (c0, c1)
            # sidecars serve PAIR grouping the same way (their packed
            # uint64 keys use the same pack_pair ordering discovery
            # derives by scanning)
            from .index import index_path_for, open_index, probe_index
            want = cols_[0] if len(cols_) == 1 else tuple(cols_)
            ip = index_path_for(self.source, want)
            try:
                if probe_index(ip, self.source, expect_col=want,
                               allow_prefix=False):
                    idx = open_index(ip, table_path=self.source)
                    discovered = np.unique(idx.keys)
            except Exception:   # raced away: fall to the scan
                discovered = None
        if discovered is not None and len(discovered) > max_groups:
            if len(cols_) <= 2:
                raise _GroupSpill(len(discovered))
            raise StromError(12, f"group_by_cols: {len(discovered)} "
                                 f"distinct keys exceed max_groups="
                                 f"{max_groups}")
        if discovered is None:
            gather, _f, _d = self._make_gather_fn(cols_,
                                                  want_positions=False)
            nk = len(cols_)
            if nk <= 2:
                merged = np.zeros(0, np.uint64 if nk == 2 else dts[0])
            else:   # N-column keys: (k, N) row array, lexicographic
                merged = np.zeros((0, nk), np.int64)

            def collect(pages_dev):
                nonlocal merged
                out = gather(pages_dev)
                m = np.asarray(out["mask"]).astype(bool)
                vs = [np.asarray(out[f"f{i}"])[m]
                      for i in range(nk)]
                if nk == 1:
                    merged = np.union1d(merged, np.unique(vs[0]))
                elif nk == 2:
                    merged = np.union1d(merged, np.unique(
                        pack_pair(vs[0], vs[1], dts[0], dts[1])))
                else:
                    u = np.unique(np.stack(
                        [v.astype(np.int64) for v in vs], 1), axis=0)
                    merged = np.unique(
                        np.concatenate([merged, u]), axis=0)
                if len(merged) > max_groups:
                    if nk <= 2:
                        raise _GroupSpill(len(merged))
                    raise StromError(
                        12, f"group_by_cols: more than {max_groups} "
                            f"distinct keys (raise max_groups, or use "
                            f"group_by with a key function)")
                return {}

            self._stream_collect(self._explain_inner(), collect, device,
                                 session)
            discovered = merged
        return discovered

    def _install_group_keys(self, discovered: np.ndarray) -> None:
        """Installation half of :meth:`_resolve_group_keys`: derive the
        ``searchsorted`` key function + group count from the (already
        discovered, possibly worker-shipped) sorted key set and compose
        the empty-group-dropping HAVING into ``self._group``."""
        import jax.numpy as jnp

        from .index import unpack_second
        cols_, agg, user_having, _max_groups = self._group_cols
        dts = [self.schema.col_dtype(c) for c in cols_]
        self._group_discovered = discovered   # worker spec ships this
        if len(cols_) == 1:
            keys = discovered.astype(dts[0])
            g = len(keys)
            kj = jnp.asarray(keys) if g else None

            def key_fn(cols, kj=kj, g=g):
                v = cols[cols_[0]]
                if kj is None:       # empty table: one dropped bucket
                    return jnp.zeros(v.shape, jnp.int32)
                return jnp.clip(jnp.searchsorted(kj, v), 0,
                                g - 1).astype(jnp.int32)

            n_groups = max(g, 1)
            self._gk_decode = lambda gids, keys=keys: [keys[gids]]
        elif len(cols_) == 2:
            packed = discovered                      # sorted uint64
            g = len(packed)
            hi = (packed >> np.uint64(32))
            if dts[0] == np.dtype(np.int32):
                k0 = (hi.astype(np.int64) - (1 << 31)).astype(np.int32)
            else:
                k0 = hi.astype(np.uint32)
            k1 = unpack_second(packed, dts[1])
            u0, u1 = np.unique(k0), np.unique(k1)
            if len(u0) * max(len(u1), 1) > (1 << 22):
                raise StromError(
                    12, "group_by_cols: dense pair table over 4M "
                        "entries; use group_by with a key function")
            # dense (rank0, rank1) -> group-id table; absent pairs (and
            # masked rows) land in the sentinel bucket g, dropped by the
            # count>0 HAVING
            table = np.full((max(len(u0), 1), max(len(u1), 1)), g,
                            np.int32)
            if g:
                table[np.searchsorted(u0, k0),
                      np.searchsorted(u1, k1)] = \
                    np.arange(g, dtype=np.int32)
            u0j, u1j = jnp.asarray(u0), jnp.asarray(u1)
            tj = jnp.asarray(table)

            def key_fn(cols, u0j=u0j, u1j=u1j, tj=tj):
                if u0j.shape[0] == 0:
                    return jnp.zeros(cols[cols_[0]].shape, jnp.int32)
                i0 = jnp.clip(jnp.searchsorted(u0j, cols[cols_[0]]), 0,
                              u0j.shape[0] - 1)
                i1 = jnp.clip(jnp.searchsorted(u1j, cols[cols_[1]]), 0,
                              u1j.shape[0] - 1)
                return tj[i0, i1].astype(jnp.int32)

            n_groups = g + 1
            self._gk_decode = lambda gids, k0=k0, k1=k1: [k0[gids],
                                                          k1[gids]]

        if len(cols_) >= 3:
            krows = discovered.astype(np.int64)      # (g, N) lex-sorted
            g = len(krows)
            uniqs = [np.unique(krows[:, j]) for j in range(len(cols_))]
            dims = [max(len(u), 1) for u in uniqs]
            total = 1
            for dnn in dims:
                total *= dnn
            if total > (1 << 22):
                raise StromError(
                    12, "group_by_cols: dense rank table over 4M "
                        "entries; use group_by with a key function")
            # mixed-radix flat table: rank tuple -> group id (sentinel
            # g for combinations that never occur / masked rows)
            table = np.full(total, g, np.int32)
            if g:
                flat = np.zeros(g, np.int64)
                for j in range(len(cols_)):
                    flat = flat * dims[j] + np.searchsorted(
                        uniqs[j], krows[:, j])
                table[flat] = np.arange(g, dtype=np.int32)
            ujs = [jnp.asarray(u.astype(np.int64).astype(np.int32)
                               if dts[j].kind == "i"
                               else u.astype(np.uint32))
                   for j, u in enumerate(uniqs)]
            tjN = jnp.asarray(table)

            def key_fn(cols, ujs=ujs, tjN=tjN, dims=tuple(dims)):
                if ujs[0].shape[0] == 0:
                    return jnp.zeros(cols[cols_[0]].shape, jnp.int32)
                flat = None
                for j, cj in enumerate(cols_):
                    r = jnp.clip(jnp.searchsorted(ujs[j], cols[cj]), 0,
                                 max(ujs[j].shape[0] - 1, 0))
                    flat = r if flat is None else flat * dims[j] + r
                return tjN[flat].astype(jnp.int32)

            n_groups = g + 1
            self._gk_decode = lambda gids, krows=krows, dts=dts: [
                krows[:, j][gids].astype(dts[j])
                for j in range(len(cols_))]

        def hv(res, user=user_having):
            m = np.asarray(res["count"]) > 0
            if user is not None:
                m = m & np.asarray(user(res)).astype(bool)
            return m

        self._group = (key_fn, n_groups, agg, hv)

    def top_k(self, col: int, k: int, *, largest: bool = True) -> "Query":
        """Terminal: k best values of *col* + their global row positions."""
        self._require_no_terminal()
        if 0 <= int(col) < self.schema.n_cols:
            if self.schema.col_nullable(int(col)):
                raise StromError(22, f"top_k over the nullable c{col} "
                                     f"is outside this subset (no NULL "
                                     f"ordering)")
            if self.schema.col_dtype(int(col)).itemsize != 4:
                raise StromError(22, f"top_k supports 4-byte columns "
                                     f"(c{col} is 8-byte)")
        self._op = "top_k"
        self._terminal_set = True
        self._topk = (int(col), int(k), largest)
        return self

    def order_by(self, col, *, descending: bool = False,
                 limit: Optional[int] = None, offset: int = 0) -> "Query":
        """Terminal: the full ordering over selected rows — sorted primary
        column values + their global row positions.  *col* may be a
        sequence of column indices (ORDER BY c_a, c_b, ...): later
        columns break ties of earlier ones; ``descending`` applies to the
        whole ordering.  ``limit``/``offset`` slice the sorted output
        (ORDER BY ... LIMIT n OFFSET m; for a small head :meth:`top_k`
        streams without materializing the whole order).  With a mesh,
        runs the distributed sample sort (single key column only); device
        *b* ends up owning the *b*-th key range — the
        ``per_device_count`` info key always describes that full
        pre-slice distribution, not the sliced arrays."""
        self._require_no_terminal()
        if limit is not None and limit < 0:
            raise StromError(22, "order_by limit must be >= 0")
        if offset < 0:
            raise StromError(22, "order_by offset must be >= 0")
        cols = [int(col)] if isinstance(col, (int, np.integer)) \
            else [int(c) for c in col]
        if not cols:
            raise StromError(22, "order_by needs at least one column")
        self._op = "order_by"
        self._terminal_set = True
        self._order = (cols, descending, limit, int(offset))
        return self

    def quantiles(self, col: int, qs: Sequence[float]) -> "Query":
        """Terminal: exact quantiles of *col* over selected rows (nearest-
        rank on the true sorted order — percentile/median without
        materializing the ordering for the caller).  With a mesh, rides
        the distributed sample sort: only the per-device bucket holding
        each rank is touched, using the bucket count distribution."""
        self._require_no_terminal()
        qs = [float(q) for q in qs]
        if not qs:
            raise StromError(22, "quantiles needs at least one q")
        for q in qs:
            if not 0.0 <= q <= 1.0:
                raise StromError(22, f"quantile {q} outside [0, 1]")
        self._op = "quantiles"
        self._terminal_set = True
        self._order = ([int(col)], False, None, 0)  # reuses the sort shape
        self._quantiles = qs
        return self

    def count_distinct(self, col: int) -> "Query":
        """Terminal: exact COUNT(DISTINCT col) of selected rows — the
        distributed sort + per-bucket run count under a mesh, a local
        unique count otherwise (each float NaN counts as distinct on
        both paths)."""
        self._require_no_terminal()
        self._op = "count_distinct"
        self._terminal_set = True
        # reuses the order_by gather shape
        self._order = ([int(col)], False, None, 0)
        return self

    def join(self, probe_col: int, build_keys: np.ndarray,
             build_values: np.ndarray, *, materialize: bool = False,
             limit: Optional[int] = None, offset: int = 0,
             how: str = "inner") -> "Query":
        """Terminal: join against a host-side dimension table.

        ``how`` — ``"inner"`` (default), ``"left"`` (every selected
        probe row; unpartnered rows carry payload 0 and a False
        ``matched`` NULL indicator), ``"semi"`` (EXISTS — partnered rows,
        build payload not exposed), or ``"anti"`` (NOT EXISTS — rows
        without a partner).  Every strategy (broadcast, Grace local
        passes, mesh partitioned, index-served) serves every face.

        Default: fold aggregates over emitted rows — ``matched``/
        ``sums``, plus ``payload_sum`` (inner/left) and ``null_count``
        (left).  ``materialize=True`` returns the rows themselves —
        ``{"positions", "keys", "count"}`` plus ``payload`` (inner/left)
        and ``matched`` (left) — with ``limit``/``offset`` slicing like
        :meth:`select` (the early DMA cut-off included)."""
        from ..ops.join import check_join_how
        self._require_no_terminal()
        try:
            check_join_how(how)
        except ValueError as e:
            raise StromError(22, str(e)) from None
        if 0 <= int(probe_col) < self.schema.n_cols \
                and self.schema.col_nullable(int(probe_col)):
            raise StromError(22, f"join probe column c{probe_col} is "
                                 f"nullable (NULL keys never match; "
                                 f"outside this subset)")
        if limit is not None and limit < 0:
            raise StromError(22, "join limit must be >= 0")
        if offset < 0:
            raise StromError(22, "join offset must be >= 0")
        if not materialize and (limit is not None or offset):
            # silently aggregating the whole table under a "limit" would
            # be a lie; row slicing only means something for rows
            raise StromError(22, "join limit/offset require "
                                 "materialize=True")
        self._op = "join"
        self._terminal_set = True
        self._join = (int(probe_col), build_keys, build_values,
                      materialize, limit, int(offset))
        self._join_how = how
        return self

    def join_table(self, probe_col: int, build_table, build_schema,
                   key_col: int, value_col: int, *,
                   materialize: bool = False,
                   limit: Optional[int] = None, offset: int = 0,
                   how: str = "inner") -> "Query":
        """Terminal: join (``how`` as in :meth:`join`) whose build side
        is an ON-DISK heap
        table instead of host arrays (the bounded-build face, VERDICT
        r3 #8).  A build table that broadcasts (fits
        ``config join_broadcast_max``) is loaded with one projection
        scan and then behaves exactly like :meth:`join`.  A larger one
        is NEVER fully materialized on the host: the mesh path streams
        it into hash partitions in Grace passes
        (:func:`..parallel.pjoin.partition_build_sharded_from_table`);
        the local path streams one partition per probe pass — host RAM
        stays bounded to one partition plus a scan batch either way."""
        if isinstance(build_table, os.PathLike):
            build_table = str(build_table)
        # validate BEFORE claiming the terminal slot: a rejected call
        # must leave the query reusable
        for c in (key_col, value_col):
            if not 0 <= int(c) < build_schema.n_cols:
                raise StromError(22, f"join_table column {c} out of range")
        if build_schema.col_dtype(int(key_col)) != np.dtype(np.int32):
            raise StromError(22, "join_table key column must be int32")
        if build_schema.col_dtype(int(value_col)).kind not in "iuf":
            raise StromError(22, "join_table value column must be "
                                 "int32/uint32/float32")
        # header check up front: a missing file, a non-heap file, or a
        # schema whose column count disagrees with what the pages carry
        # must fail HERE with a clear error, not surface later as a raw
        # OSError or silently garbled keys
        from .heap import validate_heap_header
        try:
            validate_heap_header(build_table, build_schema)
        except (OSError, ValueError) as e:
            raise StromError(getattr(e, "errno", None) or 22,
                             f"join_table build table: {e}") from e
        self.join(probe_col, None, None, materialize=materialize,
                  limit=limit, offset=offset, how=how)
        self._join_src = (build_table, build_schema, int(key_col),
                          int(value_col))
        return self

    def aggregate_exprs(self, exprs) -> "Query":
        """Terminal: selected-row count + masked sums of EXPRESSIONS
        over fact columns — SQL's ``SUM(c1*c2)`` / ``AVG(c0+5)`` face.
        *exprs* are picklable trees in the :mod:`.sql` expression
        grammar (``("col", c) | ("lit", v) | ("neg", e) |
        ("bin", op, l, r)``); each evaluates per row on device (int32
        arithmetic wraps at the storage width, float math runs at
        float32) and sums under the scan mask.  The reference's scan
        gets this for free from the executor above it
        (pgsql/nvme_strom.c:941-979); here the expressions fuse INTO the
        scan kernel.  Result: ``{"count", "esums": [scalar per expr]}``.
        """
        from .sql import _expr_info
        self._require_no_terminal()
        exprs = list(exprs)
        if not exprs:
            raise StromError(22, "aggregate_exprs needs >= 1 expression")
        for e in exprs:
            _dt, cs = _expr_info(e, self.schema)
            for c in cs:
                if self.schema.col_nullable(c):
                    # a NULL operand makes the whole expression NULL —
                    # the fused kernel has no per-row NULL propagation,
                    # so refuse instead of summing stored zeros
                    raise StromError(22, f"SQL: expression aggregates "
                                         f"over the nullable c{c} are "
                                         f"outside this subset (NULL "
                                         f"propagation)")
        self._op = "aggregate"
        self._terminal_set = True
        self._agg_exprs = exprs
        return self

    def star_join(self, joins, *, materialize: bool = False,
                  fact_cols: Optional[Sequence[int]] = None,
                  exprs: Optional[Sequence] = None,
                  limit: Optional[int] = None, offset: int = 0) -> "Query":
        """Terminal: probe SEVERAL broadcast dimension tables in ONE
        scan pass — the star-schema query shape the reference gets from
        the executor above its scan (`pgsql/nvme_strom.c:941-979`
        composes any joins over the handed-up tuples).

        *joins* — a sequence of dicts, one per dimension::

            {"probe_col": int,          # fact column carrying the key
             "table": path, "schema": HeapSchema,   # on-disk dim table
             "key_col": int,            # int32 unique-key column
             "value_col": int | None,   # payload column (None: no
                                        #  payload face — semi/anti)
             "how": "inner"|"left"|"semi"|"anti"}

        Every dimension must fit ``config join_broadcast_max`` (each is
        loaded once and probed as a sorted broadcast table); a larger
        build refuses with EINVAL — join it singly (the partitioned
        path) and CTAS the result instead.

        Default face: additive aggregates — ``count`` (rows passing all
        dims + the filter), ``sums`` (every fact column), ``pay_sums``
        (per-dim payload over partnered emitted rows), ``null_counts``
        (per-dim unpartnered emitted rows — the LEFT NULL face), and
        ``esums`` for optional expression trees (*exprs*, the
        :meth:`aggregate_exprs` grammar).  ``materialize=True`` returns
        the rows: requested *fact_cols*, per-dim payload + partner mask,
        positions, with ``limit``/``offset`` slicing like
        :meth:`select`."""
        from ..config import config as _cfg
        from ..ops.join import check_join_how
        from .heap import validate_heap_header
        self._require_no_terminal()
        joins = [dict(j) for j in joins]
        if len(joins) < 1:
            raise StromError(22, "star_join needs >= 1 dimension")
        cap = int(_cfg.get("join_broadcast_max"))
        for j in joins:
            try:
                check_join_how(j.get("how", "inner"))
            except ValueError as e:
                raise StromError(22, str(e)) from None
            j.setdefault("how", "inner")
            pc = int(j["probe_col"])
            if not 0 <= pc < self.schema.n_cols:
                raise StromError(22, f"star_join probe column {pc} out "
                                     f"of range")
            if self.schema.col_dtype(pc) != np.dtype(np.int32) \
                    or self.schema.col_nullable(pc):
                raise StromError(22, "star_join probe columns must be "
                                     "non-nullable int32")
            bs = j["schema"]
            if isinstance(j["table"], os.PathLike):
                j["table"] = str(j["table"])
            kc, vc = int(j["key_col"]), j["value_col"]
            if not 0 <= kc < bs.n_cols:
                raise StromError(22, f"star_join key column {kc} out of "
                                     f"range")
            if bs.col_dtype(kc) != np.dtype(np.int32):
                raise StromError(22, "star_join key columns must be "
                                     "int32")
            if vc is not None:
                vc = int(vc)
                if not 0 <= vc < bs.n_cols:
                    raise StromError(22, f"star_join value column {vc} "
                                         f"out of range")
                if bs.col_dtype(vc).kind not in "iuf":
                    raise StromError(22, "star_join value columns must "
                                         "be int32/uint32/float32")
                if j["how"] in ("semi", "anti"):
                    raise StromError(22, f"star_join: {j['how']} "
                                         f"dimensions expose no payload "
                                         f"(EXISTS semantics)")
                j["value_col"] = vc
            try:
                validate_heap_header(j["table"], bs)
            except (OSError, ValueError) as e:
                raise StromError(getattr(e, "errno", None) or 22,
                                 f"star_join build table: {e}") from e
            rows = (os.path.getsize(j["table"]) // PAGE_SIZE) \
                * bs.tuples_per_page
            if rows * 8 > cap:
                raise StromError(22, f"star_join: dimension "
                                     f"{j['table']} (~{rows} rows) is "
                                     f"above join_broadcast_max — join "
                                     f"it singly (the partitioned path) "
                                     f"and CTAS the result")
        if exprs:
            from .sql import _expr_info
            for e in exprs:
                _dt, cs = _expr_info(e, self.schema)
                for c in cs:
                    if self.schema.col_nullable(c):
                        raise StromError(22, f"SQL: expression "
                                             f"aggregates over the "
                                             f"nullable c{c} are "
                                             f"outside this subset "
                                             f"(NULL propagation)")
        if materialize:
            if limit is not None and limit < 0:
                raise StromError(22, "star_join limit must be >= 0")
            if offset < 0:
                raise StromError(22, "star_join offset must be >= 0")
            fact_cols = [int(c) for c in (fact_cols or [])]
            for c in fact_cols:
                if not 0 <= c < self.schema.n_cols:
                    raise StromError(22, f"star_join fact column {c} "
                                         f"out of range")
        elif limit is not None or offset:
            raise StromError(22, "star_join limit/offset require "
                                 "materialize=True")
        self._op = "star"
        self._terminal_set = True
        self._star = {"joins": joins, "materialize": bool(materialize),
                      "fact_cols": list(fact_cols or []),
                      "exprs": list(exprs or []), "limit": limit,
                      "offset": int(offset)}
        self._star_resolved = None
        return self

    def _resolve_star_builds(self, session, device) -> None:
        """Load every dimension (one projection scan each) into the
        sorted host-array form the star kernels capture; idempotent."""
        from ..ops.join import _sorted_build
        if getattr(self, "_star_resolved", None) is not None:
            return
        resolved = []
        for j in self._star["joins"]:
            bs, kc, vc = j["schema"], j["key_col"], j["value_col"]
            cols = [kc] if vc is None or vc == kc else [kc, vc]
            out = Query(j["table"], bs).select(cols).run(session=session,
                                                         device=device)
            bk = np.asarray(out[f"col{kc}"], np.int32)
            bv = None if vc is None else np.asarray(
                out[f"col{vc}"], bs.col_dtype(vc))
            try:
                keys, vals = _sorted_build(
                    bk, bk if bv is None else bv, self.schema,
                    j["probe_col"])
            except ValueError as e:
                raise StromError(22, f"star_join {j['table']}: {e}") \
                    from None
            resolved.append((j["probe_col"], keys,
                             None if bv is None else vals, j["how"]))
        self._star_resolved = resolved

    def _star_expr_parts(self):
        """(expr_fns, expr_zeros, expr_accs) for the star/expr kernels."""
        from ..ops.groupby import acc_dtypes
        from .sql import _eval_expr, _expr_info
        fns, zeros, accs = [], [], []
        for e in self._star["exprs"] if self._op == "star" \
                else self._agg_exprs:
            dt, _cols = _expr_info(e, self.schema)
            fns.append(lambda cols, e=e: _eval_expr(e, cols))
            zeros.append(dt.type(0))
            accs.append(acc_dtypes(dt)[0])
        return fns, zeros, accs

    def _run_star_rows(self, plan: QueryPlan, device, session) -> dict:
        """Star row face: stream the scan, probe every dimension per
        batch, hand the emitted rows back (fact cols + per-dim payload/
        partner + positions)."""
        from ..ops.join import make_star_rows_fn
        st = self._star
        pred = self._pred
        run = make_star_rows_fn(
            self.schema, self._star_resolved,
            predicate=(lambda cols: pred(cols)) if pred else None,
            fact_cols=st["fact_cols"])
        fields = [f"c{c}" for c in st["fact_cols"]]
        dtypes = [self.schema.col_dtype(c) for c in st["fact_cols"]]
        for i, (pc, _k, vals, how) in enumerate(self._star_resolved):
            if vals is not None:
                fields.append(f"pay{i}")
                dtypes.append(vals.dtype)
            fields.append(f"m{i}")
            dtypes.append(np.dtype(bool))
        fields.append("positions")
        dtypes.append(self._pos_dtype())
        arrs = self._collect_rows(plan, run, "hit", fields, dtypes,
                                  device, session, limit=st["limit"],
                                  offset=st["offset"])
        out = dict(zip(fields, arrs))
        out["count"] = np.int64(len(out["positions"]))
        return out

    def _require_no_terminal(self) -> None:
        if self._terminal_set:
            raise StromError(22, "one terminal operator per query "
                                 "(it is one scan node)")

    # -- planning -----------------------------------------------------------
    def _source_facts(self):
        if isinstance(self.source, str):
            path = self.source
            size = os.path.getsize(path)
        elif isinstance(self.source, (list, tuple)):
            path = self.source[0]
            size = sum(os.path.getsize(p) for p in self.source)
        else:  # live Source object
            path = getattr(self.source, "path", None)
            size = self.source.size
        return path, size

    def _open_owned(self):
        """(live Source, owned?) — multi-file sets open as RAID-0 stripes
        with the query's stripe geometry."""
        from ..engine import open_source
        if hasattr(self.source, "size"):
            return self.source, False
        if isinstance(self.source, (list, tuple)):
            return open_source(self.source,
                               stripe_chunk_size=self._stripe_chunk), True
        return open_source(self.source), True

    def _kernel_choice(self, mode: str):
        import jax

        # operator validity is mode-independent — check BEFORE any mode
        # early-return so mesh plans surface 'invalid' too
        if self._op == "group_by":
            from ..ops.groupby import _check_agg_cols
            try:
                _check_agg_cols(self.schema, self._group[2])
            except ValueError as e:
                # EXPLAIN must show the problem, not raise; run() refuses
                return "invalid", str(e)
        if self._op == "aggregate" and self._agg_cols is not None:
            bad = [c for c in self._agg_cols
                   if not 0 <= c < self.schema.n_cols]
            if bad:   # both access paths must refuse identically
                return "invalid", (f"aggregate column {bad[0]} out of "
                                   f"range (schema has "
                                   f"{self.schema.n_cols})")
        if self._op == "star":
            n = len(self._star["joins"])
            face = "row materialization" if self._star["materialize"] \
                else "additive aggregate"
            return "xla", (f"star join: {n} broadcast dimension"
                           f"{'s' if n != 1 else ''} probed per batch "
                           f"(sorted searchsorted probes fused in one "
                           f"kernel), {face} face")
        if self._op == "aggregate" and self._agg_exprs is not None:
            return "xla", (f"{len(self._agg_exprs)} expression "
                           f"aggregate(s) fuse into the scan kernel "
                           f"(XLA elementwise + masked sum)")
        if self._op == "top_k" \
                and not 0 <= self._topk[0] < self.schema.n_cols:
            return "invalid", (f"top_k column {self._topk[0]} out of "
                               f"range (schema has {self.schema.n_cols})")
        if self._op in ("order_by", "quantiles", "count_distinct"):
            for c in self._order[0]:
                try:
                    self._check_sortable_col(c, self._op)
                except StromError as e:
                    return "invalid", str(e)
        if self._op == "select":
            bad = [c for c in (self._select[0] or [])
                   if not 0 <= c < self.schema.n_cols]
            if bad:   # EXPLAIN must show the problem, not raise
                return "invalid", (f"select column {bad[0]} out of range "
                                   f"(schema has {self.schema.n_cols})")
            return "xla", ("row materialization: decode + mask-compress "
                           "gather, rows return to the host like tuples "
                           "to the executor" +
                           ("; gather runs on a local device (no mesh "
                            "reduction in a materialization)"
                            if mode == "mesh" else ""))
        on_tpu = jax.default_backend() == "tpu"
        if mode == "mesh":
            return "xla", "mesh mode: XLA partitions the reduction and " \
                          "inserts collectives (pallas does not auto-shard)"
        if self.schema.has_wide or any(self.schema.nullable or ()):
            # the Mosaic kernels decode the 4-byte non-null layout;
            # wide (int64/float64) regions and validity bitmaps decode
            # on the XLA path (round 5)
            return "xla", ("wide/nullable page layout decodes on the "
                           "XLA path (the pallas kernels serve the "
                           "4-byte non-null layout)")
        if self._op == "aggregate":
            if on_tpu:
                return "pallas", "single-pass SMEM-accumulator kernel " \
                                 "(bench: pallas_vs_xla > 1 on chip)"
            return "xla", "non-TPU backend: interpret-mode pallas would " \
                          "be pure overhead"
        if self._op == "group_by":
            _, g, agg, _hv = self._group
            if self._group_cols is not None:
                # value-keyed GROUP BY: the derived key function closes
                # over the DISCOVERED key table (a device array), and
                # pallas_call rejects captured array constants — found
                # live on TPU driving `--sql ... GROUP BY c0` (round 5)
                return "xla", ("value-keyed GROUP BY: the discovered "
                               "key table is a captured array (Mosaic "
                               "kernels take arrays as inputs only); "
                               "XLA serves the searchsorted key path")
            if jax.config.jax_enable_x64:
                # acc_dtypes widens sums/sumsqs to i64/f64 under x64 —
                # dtypes Mosaic cannot hold in SMEM on real hardware
                return "xla", "x64 accumulators (i64/f64) exceed the " \
                              "pallas kernel's SMEM dtype support"
            from ..ops.groupby import _check_agg_cols as _cac
            from ..ops.groupby import groupby_kernel_auto
            # measured routing decision (VERDICT r4 weak #4 / next #8):
            # the auto-selector keys on BENCH_MATRIX's live
            # pallas_vs_xla_groupby ratio, crossover at 1.0
            gk, gwhy = groupby_kernel_auto(_cac(self.schema, agg)[1].kind)
            if gk == "xla":
                return "xla", gwhy
            if on_tpu and g <= _PALLAS_MAX_GROUPS:
                return "pallas", f"G={g} within the static-unroll bound " \
                                 f"({_PALLAS_MAX_GROUPS})"
            return "xla", (f"G={g} exceeds the pallas unroll bound"
                           if g > _PALLAS_MAX_GROUPS
                           else "non-TPU backend")
        if self._op in ("order_by", "count_distinct", "quantiles"):
            return "xla", ("distributed sample sort (splitter election + "
                           "all_to_all)" if mode == "mesh"
                           else "single-device lax sort")
        return "xla", f"{self._op} runs on lax.top_k/searchsorted (XLA)"

    def _resolve_join_build(self, session, device) -> None:
        """Load a broadcast-sized on-disk build side (one projection
        scan) into the host-array form the broadcast paths consume;
        idempotent across repeated run() calls."""
        bt, bs, kc, vc = self._join_src
        out = Query(bt, bs).select([kc, vc]).run(session=session,
                                                 device=device)
        pc, _bk, _bv, mat, lim, off = self._join
        self._join = (pc, np.asarray(out[f"col{kc}"], np.int32),
                      np.asarray(out[f"col{vc}"],
                                 bs.col_dtype(vc)), mat, lim, off)
        self._join_src = None

    def _join_strategy(self) -> Optional[tuple]:
        """(strategy, n_parts) for a join terminal: "broadcast" while the
        build side (keys+values bytes) fits ``config join_broadcast_max``
        per device; above it, "partitioned" with the part count that
        bounds resident build memory to the cap — hash-repartition both
        sides, sorted-probe per partition, degrade instead of OOM."""
        if self._join is None:
            return None
        from ..config import config
        if self._join_src is not None:
            # on-disk build: estimate keys+values bytes from the row
            # count (8 bytes/row — two int32 columns)
            bt, bs, _kc, _vc = self._join_src
            rows = (os.path.getsize(bt) // PAGE_SIZE) * bs.tuples_per_page
            nbytes = rows * 8
        else:
            bk, bv = self._join[1], self._join[2]
            nbytes = (np.asarray(bk).nbytes + np.asarray(bv).nbytes)
        cap = int(config.get("join_broadcast_max"))
        if nbytes <= cap:
            return ("broadcast", 1)
        return ("partitioned", max(2, -(-nbytes // cap)))

    def _index_col(self) -> Optional[int]:
        """The column a structured (eq/range/in) filter targets."""
        for f in (self._eq, self._range, self._in):
            if f is not None:
                return f[0]
        return None

    def _eq_order_combo_path(self) -> Optional[str]:
        """Composite sidecar path serving ``WHERE c0 = v ORDER BY c1``
        (single-column structured equality + single-column order_by over
        a DIFFERENT integer column), or None."""
        if (self._op != "order_by" or self._eq is None
                or self._residual is not None
                or isinstance(self._eq[0], (tuple, list))
                or not isinstance(self.source, str)):
            # a residual where() disqualifies the span shortcut: the
            # prefix span is read straight off the sidecar with no row
            # recheck, so it would silently ignore the predicate
            return None
        oc = self._order[0]
        if len(oc) != 1:
            return None
        ce, c1 = int(self._eq[0]), int(oc[0])
        if ce == c1:
            return None
        for c in (ce, c1):
            if not 0 <= c < self.schema.n_cols \
                    or self.schema.col_dtype(c).kind not in "iu":
                return None
        from .index import index_path_for
        return index_path_for(self.source, (ce, c1))

    def _order_key(self):
        """(order columns, sidecar key) for the op's ordered terminal —
        THE single derivation explain() and run() both use, so the
        EXPLAIN promise and run()'s acceptance check cannot drift."""
        ocols = [self._topk[0]] if self._op == "top_k" else self._order[0]
        okey = ocols[0] if len(ocols) == 1 else tuple(ocols[:2])
        return ocols, okey

    def _order_index_path(self) -> Optional[str]:
        """Sidecar path that can serve this ordered terminal directly:
        unfiltered local ``order_by`` (the sorted order IS the index
        order), ``top_k`` (the k best keys are the sidecar's head/tail),
        ``quantiles`` (nearest-rank reads of the sorted keys), or
        ``count_distinct`` (adjacent-diff over the sorted keys) —
        single integer column, or the two integer columns of a composite
        sidecar for order_by.  None when no index could apply."""
        if (self._op not in ("order_by", "quantiles", "count_distinct",
                             "top_k")
                or self._pred is not None
                or not isinstance(self.source, str)):
            return None
        cols, _okey = self._order_key()
        want = (1, 2) if self._op == "order_by" else (1,)
        if len(cols) not in want:
            return None
        for c in cols:
            if not 0 <= c < self.schema.n_cols \
                    or self.schema.col_dtype(c).kind not in "iu":
                # float sidecars strip NaN keys (index.py build), so they
                # cannot reproduce the full row set an ORDER BY owes —
                # index presence must never change query results
                return None
        from .index import index_path_for
        key = cols[0] if len(cols) == 1 else (cols[0], cols[1])
        return index_path_for(self.source, key)

    def _index_path_candidates(self) -> List[str]:
        """Sidecars that could serve the structured filter, preferred
        first: the filter column's own, then — for single-column filters
        — any composite sidecar whose FIRST column is the filter column
        (the SQL leftmost-prefix rule; its packed keys hold the filter
        column's range contiguously).  The directory glob runs once per
        Query (memoized): freshness is re-probed per use anyway, and the
        planner path must stay I/O-cheap."""
        col = self._index_col()
        if col is None or not isinstance(self.source, str):
            return []
        from .index import index_path_for
        out = [index_path_for(self.source, col)]
        if not isinstance(col, (tuple, list)):
            cached = getattr(self, "_prefix_cands", None)
            if cached is None:
                import glob as _glob
                import re as _re
                # escape the table path (metacharacter paths must not
                # become character classes) and accept ONLY the exact
                # .idx<c0>_<c1> shape — never .tmp litter or lookalikes
                pat = _glob.escape(self.source) + f".idx{int(col)}_*"
                rx = _re.compile(
                    _re.escape(self.source) + rf"\.idx{int(col)}_\d+$")
                cached = sorted(p for p in _glob.glob(pat)
                                if rx.fullmatch(p))
                self._prefix_cands = cached
            out += cached
        return out

    def _replan_scan(self, plan: QueryPlan) -> QueryPlan:
        """An index promised at EXPLAIN raced away before run(): choose
        the SCAN access path afresh (falling into vfs unconditionally
        would demote large tables off the direct DMA path)."""
        path, size = self._source_facts()
        return dataclasses.replace(
            plan, access_path="direct"
            if path is not None and should_use_direct_scan(
                path, table_size=size) else "vfs")

    def _index_fresh_for_eq(self) -> bool:
        """Header-only planner probe (no key/position load — EXPLAIN
        stays I/O-cheap); missing/stale/corrupt all mean False.  Any
        candidate (own sidecar or a composite leftmost-prefix match)
        counts — validated against the HEADER's column field, so EXPLAIN
        never claims an index path run() would refuse."""
        from .index import probe_index
        col = self._index_col()
        return any(probe_index(p, self.source, expect_col=col)
                   for p in self._index_path_candidates())

    def _index_for_eq(self):
        """A FRESH sorted-index sidecar serving the structured filter, or
        None (missing/stale/corrupt all mean seqscan fallback, silently —
        the planner never fails a query over an optional accelerator).
        Candidates in preference order: the filter column's own sidecar,
        then composite ones usable via the leftmost-prefix rule."""
        from .index import open_index
        col = self._index_col()
        for ipath in self._index_path_candidates():
            try:
                idx = open_index(ipath, table_path=self.source)
            except Exception:  # corrupt sidecars included, not just Strom/OS
                continue
            # the header is authoritative, not the filename: a sidecar
            # built for other columns (index_path= override) must never
            # serve this filter
            want = tuple(col) if isinstance(col, (tuple, list)) else col
            if idx.col == want or (idx.composite
                                   and not isinstance(want, tuple)
                                   and idx.col[0] == want):
                return idx
        return None

    def explain(self, *, mesh=None) -> QueryPlan:
        plan = self._explain_inner(mesh=mesh)
        if self._workers >= 2 and mesh is None:
            from .planner import _parallel_divisor
            plan = dataclasses.replace(
                plan, workers=self._workers,
                reason=plan.reason +
                f"; parallel: {self._workers} worker processes claim "
                f"chunks from ONE shared cursor (per-worker Sessions, "
                f"partials fold on the leader; cost divisor "
                f"{_parallel_divisor(self._workers):.1f})")
        if self._group_cols is not None:
            plan = dataclasses.replace(
                plan, reason=plan.reason +
                "; value-keyed GROUP BY: distinct keys discovered first "
                "(fresh sidecar at zero table I/O, else one projection "
                "scan), empty groups dropped")
        js = self._join_strategy()
        if js is not None:
            strat, n_parts = js
            label = "broadcast" if strat == "broadcast" else \
                f"partitioned({n_parts})"
            how = ("build side replicated per device"
                   if strat == "broadcast" else
                   (f"build side above join_broadcast_max: hash-"
                    f"repartitioned over the mesh dp axis, all_to_all "
                    f"row exchange, local sorted-probe"
                    if mesh is not None else
                    f"build side above join_broadcast_max: {n_parts} "
                    f"hash partitions probed as sequential passes "
                    f"(Grace join), resident build bounded to the cap"))
            if self._join_src is not None and strat == "partitioned":
                how += ("; build side STREAMED from the on-disk table "
                        "in partition passes (host RAM bounded by "
                        "join_build_host_max)")
            plan = dataclasses.replace(
                plan, join_strategy=label,
                reason=plan.reason + f"; join type {self._join_how}"
                       f"; join strategy {label}: {how}")
        return plan

    def _explain_inner(self, *, mesh=None) -> QueryPlan:
        path, size = self._source_facts()
        n_pages = size // PAGE_SIZE
        t = self.schema.tuples_per_page
        direct = path is not None and should_use_direct_scan(
            path, table_size=size)
        mode = "mesh" if mesh is not None else "local"
        kernel, why = self._kernel_choice(mode)
        nw = self._workers if self._workers >= 2 else 0
        cd = cost_direct_scan(n_pages, n_pages * t, workers=nw)
        cv = cost_vfs_scan(n_pages, n_pages * t, workers=nw)
        if mode == "local" and kernel != "invalid":
            comb = self._eq_order_combo_path()
            if comb is not None and self._eq[1] is not None:
                from .index import probe_index
                if probe_index(comb, self.source,
                               expect_col=(int(self._eq[0]),
                                           int(self._order[0][0]))):
                    ce, _v = self._eq
                    oc = self._order[0][0]
                    return QueryPlan(
                        operator=self._op, access_path="index",
                        kernel=kernel, mode=mode, n_pages=n_pages,
                        cost_direct=cd.total, cost_vfs=cv.total,
                        reason=f"fresh composite index on col({ce}, "
                               f"{oc}): WHERE col{ce} = ... ORDER BY "
                               f"col{oc} is ONE pinned-prefix span of "
                               f"the sidecar (keys within the prefix "
                               f"are already in col{oc} order) — no "
                               f"sort, no table I/O; " + why)
            oip = self._order_index_path()
            if oip is not None:
                from .index import probe_index
                ocols, okey = self._order_key()
                # exact header match, no prefix: these terminals read
                # the KEYS as values, so a composite sidecar can only
                # serve the exact pair ordering
                if probe_index(oip, self.source, expect_col=okey,
                               allow_prefix=False):
                    cols_ = ocols
                    what = {
                        "order_by": "the sorted order IS the index "
                                    "order — positions read from the "
                                    "sidecar, no sort, and LIMIT reads "
                                    "only the head",
                        "quantiles": "nearest-rank reads of the sorted "
                                     "sidecar keys — no table I/O at all",
                        "count_distinct": "adjacent-diff over the sorted "
                                          "sidecar keys — no table I/O "
                                          "at all",
                        "top_k": "the k best keys are the sidecar's "
                                 "head/tail — no scan, no table I/O",
                    }[self._op]
                    return QueryPlan(
                        operator=self._op, access_path="index",
                        kernel=kernel, mode=mode, n_pages=n_pages,
                        cost_direct=cd.total, cost_vfs=cv.total,
                        reason=f"fresh index on col{cols_}: {what}; "
                               + why)
        if (self._op in ("select", "aggregate", "top_k", "quantiles",
                         "count_distinct", "group_by", "join")
                and mode == "local"
                and kernel != "invalid" and self._index_fresh_for_eq()):
            if self._eq is not None:
                c, v = self._eq
                cond = f"equality col{c} == {v!r}"
            elif self._in is not None:
                c, members = self._in
                cond = f"membership col{c} IN ({len(members)} values)"
            else:
                c, lo, hi = self._range
                cond = f"range {lo!r} <= col{c} <= {hi!r}"
            recheck = ("" if self._residual is None else
                       " + residual filter RECHECKED on index-resolved "
                       "rows (Index Cond + Filter)")
            return QueryPlan(
                operator=self._op, access_path="index", kernel=kernel,
                mode=mode, n_pages=n_pages, cost_direct=cd.total,
                cost_vfs=cv.total,
                reason=f"fresh index on col{c}: {cond} resolves "
                       f"positions from the sidecar and reads only "
                       f"matching pages{recheck}; " + why)
        if direct:
            reason = ("table above the direct-scan threshold and backing "
                      "eligible; " + why)
        else:
            info = capability_cache.probe(path) if path else None
            if info is not None and not info.supported:
                reason = "source not direct-load capable (CHECK_FILE); " + why
            else:
                reason = "table below the direct-scan threshold " \
                         "(page cache wins for small tables); " + why
        # cache-aware planning (ISSUE 9): report the residency tier's
        # expected hit ratio for this table — at 1.0 the scan is served
        # entirely from pinned slabs and skips engine submission
        from ..tiering import extent_space
        ratio = 0.0
        hbm_ratio = 0.0
        if extent_space.lookup_active and size:
            if isinstance(self.source, (list, tuple)):
                cpaths = list(self.source)
            elif path is not None:
                cpaths = [path]
            else:
                cpaths = []
            # unified residency surface (ISSUE 20): one dict of
            # per-tier expected hit fractions — the engine consults
            # HBM FIRST, so its share surfaces separately; those
            # chunks cost one device->dest memcpy, not even a
            # host-slab touch
            fr = extent_space.resident_fraction(cpaths, size)
            ratio = fr.get("ram", 0.0)
            hbm_ratio = fr.get("hbm", 0.0)
        if hbm_ratio > 0:
            reason += (f"; hbm tier holds ~{hbm_ratio:.0%} of the table "
                       f"(device hits, checked before the host tier)")
        if ratio >= 1.0:
            reason += ("; fully cache-resident: served from the "
                       "residency tier, engine submission skipped")
        elif ratio > 0:
            reason += (f"; residency tier holds ~{ratio:.0%} of the "
                       f"table (memcpy hits, no mincore probe)")
        # compute pushdown (ISSUE 14): a fresh packed sidecar re-plans
        # the scan over compressed extents; the per-column host/chip
        # decision and the wire-byte prediction surface here so EXPLAIN
        # shows exactly what will cross the transport
        pd = ""
        if mode == "local" and kernel != "invalid":
            probe = self._pushdown_probe()
            if probe is not None:
                dec, _meta = probe
                pd = dec.mode
                reason += "; " + dec.explain()
        return QueryPlan(operator=self._op,
                         access_path="direct" if direct else "vfs",
                         kernel=kernel, mode=mode, n_pages=n_pages,
                         cost_direct=cd.total, cost_vfs=cv.total,
                         reason=reason,
                         cache_hit_ratio=round(ratio, 4),
                         hbm_hit_ratio=round(hbm_ratio, 4),
                         pushdown=pd)

    # -- compute builders ---------------------------------------------------
    def _build_fn(self, kernel: str):
        """Returns (fn(pages)->dict, combine or None)."""
        pred = self._pred
        if self._op == "star":
            from ..ops.join import make_star_fn
            fns, zeros, accs = self._star_expr_parts()
            run = make_star_fn(
                self.schema, self._star_resolved,
                predicate=(lambda cols: pred(cols)) if pred else None,
                expr_fns=fns, expr_zeros=zeros, expr_accs=accs)
            return (lambda pages: run(pages)), None
        if self._op == "aggregate" and self._agg_exprs is not None:
            import jax
            import jax.numpy as jnp

            from ..ops.filter_xla import decode_pages
            fns, zeros, accs = self._star_expr_parts()

            @jax.jit
            def efn(pages):
                cols, valid = decode_pages(pages, self.schema)
                sel = valid if pred is None else valid & pred(cols)
                return {"count": jnp.sum(sel.astype(jnp.int32)),
                        "esums": [jnp.sum(jnp.where(sel, f(cols), z),
                                          dtype=a)
                                  for f, z, a in zip(fns, zeros, accs)]}
            return efn, None
        if self._op == "aggregate":
            import jax.numpy as jnp

            # no predicate = every valid row.  NOT cols[0]==cols[0]: that
            # is False for float NaN and would silently drop NaN rows
            all_rows = lambda cols: jnp.ones(cols[0].shape, bool)
            if kernel == "pallas":
                from ..ops.filter_pallas import make_filter_fn_pallas
                p = (lambda cols, th: pred(cols)) if pred is not None \
                    else (lambda cols, th: all_rows(cols))
                run = make_filter_fn_pallas(self.schema, p)
                fn = lambda pages: run(pages, np.int32(0))
            else:
                from ..ops.filter_xla import make_filter_fn
                p = pred if pred is not None else all_rows
                fn = make_filter_fn(self.schema, p)
            if self._agg_cols is not None:
                keep = list(self._agg_cols)
                inner = fn

                def project(o, keep=keep):
                    out = {"count": o["count"],
                           "sums": [o["sums"][c] for c in keep]}
                    if "nncounts" in o:   # NULL-aware denominators
                        out["nncounts"] = [o["nncounts"][c]
                                           for c in keep]
                    return out
                fn = lambda pages: project(inner(pages))
            return fn, None
        if self._op == "group_by":
            key_fn, g, agg, _having = self._group
            kw = dict(agg_cols=agg,
                      predicate=(lambda cols: pred(cols)) if pred else None)
            if kernel == "pallas" and self._group_cols is not None:
                # an explicit kernel="pallas" override must refuse
                # cleanly, not die inside pallas_call tracing
                raise StromError(22, "value-keyed GROUP BY cannot run "
                                     "on the pallas kernel (the "
                                     "discovered key table is a "
                                     "captured array); use kernel="
                                     "'xla' or 'auto'")
            if kernel == "pallas":
                from ..ops.groupby_pallas import make_groupby_fn_pallas
                run = make_groupby_fn_pallas(self.schema, lambda cols: key_fn(cols),
                                             g, **kw)
            else:
                from ..ops.groupby import make_groupby_fn
                run = make_groupby_fn(self.schema, lambda cols: key_fn(cols),
                                      g, **kw)
            from ..ops.groupby import combine_groupby
            return (lambda pages: run(pages)), combine_groupby
        if self._op == "top_k":
            from ..ops.topk import make_topk_fn
            col, k, largest = self._topk
            run = make_topk_fn(self.schema, col, k, largest=largest,
                               predicate=(lambda cols: pred(cols))
                               if pred else None)
            return (lambda pages: run(pages)), run.combine
        # join
        from ..ops.join import make_join_fn
        probe_col, bk, bv = self._join[:3]
        run = make_join_fn(self.schema, probe_col, bk, bv,
                           predicate=(lambda cols: pred(cols))
                           if pred else None, how=self._join_how)
        return (lambda pages: run(pages)), None

    # -- compute pushdown (ISSUE 14) ----------------------------------------
    def _pushdown_need_cols(self):
        """Columns the packed scan must expand: the aggregate projection
        when no predicate can read other columns, else all (an opaque
        ``where()`` lambda may touch any column)."""
        if self._pred is None and self._agg_cols is not None:
            return tuple(self._agg_cols)
        return None

    def _pushdown_probe(self):
        """(PushdownDecision, PackedMeta) when a fresh packed sidecar can
        serve this query, else None.

        Structural eligibility mirrors what the fused decode kernels
        implement: plain aggregate (no expression sums), 4-byte non-null
        layout, serial local scan over one table file.  Freshness is the
        sidecar's size+mtime stamp (the scan/index.py contract), so any
        table write silently retires the packed plan."""
        if self._op != "aggregate" or self._agg_exprs is not None:
            return None
        if not isinstance(self.source, str) or self._workers >= 2:
            return None
        if self.schema.has_wide or any(self.schema.nullable or ()):
            return None
        from .colpack import probe_packed
        meta = probe_packed(self.source)
        if meta is None:
            return None
        from .planner import decide_pushdown
        return decide_pushdown(meta, self._pushdown_need_cols()), meta

    def _run_pushdown(self, dec, meta, device, session,
                      kernel: str = "auto") -> dict:
        """Aggregate over the packed sidecar instead of the heap table.

        ``chip``: the ``.cpk`` pages stream SSD -> landing buffer ->
        device UNEXPANDED and the fused decode->filter->project kernel
        expands them in VMEM — the h2d link (the measured ceiling) only
        ever carries wire bytes.  ``host``: the SSD is the ceiling
        instead, so packed bytes leave the disk, expand to heap pages on
        the host, and the ordinary XLA filter kernel consumes them.
        Integer aggregates are byte-identical to the unpacked scan on
        both legs (same accumulator dtypes, same masked-sum shape)."""
        import time as _time

        import jax

        from ..engine import open_source
        from ..stats import stats
        from ..trace import recorder
        need = self._pushdown_need_cols()
        scale = meta.logical_bytes / max(meta.packed_bytes, 1)
        src = open_source(meta.path)
        # residency-tier identity: packed extents are a DIFFERENT
        # representation of the table, so the cache key carries a repr
        # tag + the encode generation — a re-encoded sidecar can never
        # alias a stale cached extent, and capacity accounting can
        # credit the tier with the LOGICAL bytes each packed slab serves
        src.cache_key_extra = ("#repr=cpk", f"#gen={meta.table_mtime_ns}")
        src.logical_scale = scale
        t0 = _time.monotonic_ns()
        try:
            if dec.mode == "chip":
                use_pallas = kernel == "pallas" or (
                    kernel == "auto" and jax.default_backend() == "tpu")
                if use_pallas:
                    from ..ops.decode_pallas import \
                        make_decode_filter_fn_pallas
                    run = make_decode_filter_fn_pallas(
                        meta, self.schema, self._pred, need_cols=need)
                else:
                    from ..ops.decode_xla import make_decode_filter_fn_xla
                    run = make_decode_filter_fn_xla(
                        meta, self._pred, need_cols=need)

                # counted OUTSIDE the jitted decode (a traced stats.add
                # would fire once at trace time, not per batch) — so no
                # dispatch coalescing on this path
                def fn(pages):
                    stats.add("nr_pushdown_decode_chip")
                    stats.add("bytes_wire_saved",
                              int(pages.shape[0] * PAGE_SIZE
                                  * (scale - 1.0)))
                    return run(pages)

                from .executor import TableScanner
                with TableScanner(src, self.schema, session=session) as sc:
                    out = sc.scan_filter(fn, device=device)
                    self._last_scan_h2d_depth = getattr(
                        sc, "last_h2d_depth", 0)
            else:   # host expansion (SSD-bound)
                from .colpack import decode_pages_numpy
                from .executor import fold_results
                from .heap import build_pages
                fn, _combine = self._build_fn("xla")
                dev = device or jax.local_devices()[0]
                n_pages = src.size // PAGE_SIZE
                batch = max((8 << 20) // PAGE_SIZE, 1)
                acc = None
                for p0 in range(0, n_pages, batch):
                    n = min(batch, n_pages - p0)
                    raw = bytearray(n * PAGE_SIZE)
                    src.read_buffered(p0 * PAGE_SIZE, memoryview(raw))
                    packed = np.frombuffer(raw, np.uint8).reshape(
                        n, PAGE_SIZE)
                    cols, nr = decode_pages_numpy(packed, meta)
                    stats.add("nr_pushdown_decode_host")
                    stats.add("bytes_wire_saved",
                              int(n * PAGE_SIZE * (scale - 1.0)))
                    if nr == 0:
                        continue
                    pages = build_pages(cols, self.schema)
                    acc = fold_results(
                        acc, fn(jax.device_put(pages, dev)), None)
                out = jax.tree.map(np.asarray, acc) if acc else {}
        finally:
            src.close()
            recorder.span("pushdown_decode", t0, _time.monotonic_ns(),
                          length=meta.packed_bytes,
                          args={"mode": dec.mode,
                                "wire_bytes": dec.wire_bytes,
                                "logical_bytes": dec.logical_bytes})
        if dec.mode == "chip" and out and self._agg_cols is not None:
            # the fused kernel returns every schema column's sum slot
            # (un-needed ones as zeros); project like _build_fn does
            out = {"count": out["count"],
                   "sums": [out["sums"][c] for c in self._agg_cols]}
        return self._finalize(out)

    # -- execution ----------------------------------------------------------
    def run(self, *, mesh=None, device=None, kernel: str = "auto",
            batch_pages: Optional[int] = None, session=None,
            analyze: bool = False, workers: Optional[int] = None) -> dict:
        """Execute the planned scan and return numpy results.

        ``kernel`` overrides the planner's pallas/XLA choice ("auto" |
        "pallas" | "xla").  With *mesh*, batches stream sharded over the
        mesh's ``dp`` axis and XLA inserts the reduction collectives.
        ``workers=N`` (or ``Query(..., workers=N)``) runs the scan as N
        worker PROCESSES sharing one atomic chunk cursor — the Gather
        analog (`pgsql/nvme_strom.c:582-595,1057-1112`); each worker
        scans with its own Session and the partial results fold on the
        leader.  ``analyze=True`` attaches an ``"_analyze"`` key —
        elapsed wall time plus the engine's stage counters for this run
        (the EXPLAIN ANALYZE face of the STAT_INFO registry,
        kmod/nvme_strom.c:2056-2103)."""
        if analyze:
            import time as _time

            from ..stats import stats as _stats

            def _fold(sess):
                # a caller-supplied session keeps its native-engine
                # counters until stat_info/close; fold them so both
                # snapshots see this run's I/O (not some later window's)
                if sess is not None and getattr(sess, "_native", None) \
                        is not None:
                    sess._fold_native_stats()

            _fold(session)
            before = _stats.snapshot(reset_max=False).counters
            # per-run attribution: an index-served run must report 0, not
            # a previous scan's depth
            self._last_scan_h2d_depth = 0
            t0 = _time.monotonic()
            out = self.run(mesh=mesh, device=device, kernel=kernel,
                           batch_pages=batch_pages, session=session,
                           workers=workers)
            dt = _time.monotonic() - t0
            _fold(session)
            after = _stats.snapshot(reset_max=False).counters
            d = {k: after.get(k, 0) - before.get(k, 0)
                 for k in ("total_dma_length", "nr_submit_dma",
                           "nr_ioctl_memcpy_wait", "nr_wrong_wakeup",
                           "nr_enter_dma", "nr_kernel_dispatch")}
            nsub = max(d["nr_submit_dma"], 1)
            out["_analyze"] = {
                "elapsed_s": round(dt, 6),
                "bytes_direct": int(d["total_dma_length"]),
                "requests": int(d["nr_submit_dma"]),
                "avg_dma_bytes": int(d["total_dma_length"] // nsub),
                "waits": int(d["nr_ioctl_memcpy_wait"]),
                "submit_syscalls": int(d["nr_enter_dma"]),
                # jitted kernel calls this run issued: coalescing makes
                # this ~batches/K on streamed kernel paths
                "kernel_dispatches": int(d["nr_kernel_dispatch"]),
                # per-RUN value from this run's scanner (the registry
                # gauge is process-lifetime and would misattribute a
                # previous scan's pipelining to an index-served query)
                "h2d_depth_reached": int(
                    getattr(self, "_last_scan_h2d_depth", 0)),
                "scan_GBps": round(d["total_dma_length"] / dt / (1 << 30), 3)
                if dt > 0 else None,
            }
            return out
        nw = self._workers if workers is None else int(workers)
        if nw >= 2 and mesh is None:
            return self._run_workers(nw, session=session, device=device)
        if self._group_cols is not None and self._group[0] is None:
            # value-keyed GROUP BY: discover the distinct key set first
            # (sidecar when fresh, streamed scan otherwise), then run as
            # a normal group_by with a searchsorted key function; past
            # max_groups the sorted-aggregation path takes over (the
            # one-hot kernels' footprint grows with the group count)
            try:
                self._resolve_group_keys(session, device)
            except _GroupSpill:
                return self._run_groupby_sorted(device, session)
        plan = self.explain(mesh=mesh)
        if plan.kernel == "invalid":
            raise StromError(22, f"query not executable: {plan.reason}")
        if self._op == "join" and self._join_src is not None \
                and self._join_strategy()[0] == "broadcast":
            # broadcast-sized on-disk build: one projection scan loads
            # it, then every downstream join path (incl. indexed) sees
            # plain host arrays
            self._resolve_join_build(session, device)
        if self._op == "star":
            self._resolve_star_builds(session, device)
            if self._star["materialize"]:
                return self._run_star_rows(plan, device, session)
        if plan.access_path == "index" and self._op == "order_by" \
                and self._eq is not None:
            comb = self._eq_order_combo_path()
            idx = None
            if comb is not None:
                from .index import open_index
                try:
                    cand = open_index(comb, table_path=self.source)
                    ce, oc = int(self._eq[0]), int(self._order[0][0])
                    if cand.composite and cand.col == (ce, oc):
                        idx = cand
                except Exception:   # raced away: fall to the sort path
                    idx = None
            if idx is not None:
                return self._run_order_by_prefix(idx)
            plan = self._replan_scan(plan)
        if plan.access_path == "index" and self._op in (
                "order_by", "quantiles", "count_distinct", "top_k") \
                and self._index_col() is None:
            oip = self._order_index_path()
            idx = None
            if oip is not None:
                from .index import open_index
                try:
                    idx = open_index(oip, table_path=self.source)
                except Exception:   # raced away: fall to the sort path
                    idx = None
            if idx is not None:
                # header authoritative (same contract as the probe):
                # these terminals read keys as VALUES, exact match only
                _ocols, okey = self._order_key()
                if idx.col != okey:
                    idx = None
            if idx is not None:
                if self._op == "order_by":
                    return self._run_order_by_indexed(idx, device, session)
                if self._op == "quantiles":
                    return self._run_quantiles_sidecar(idx)
                if self._op == "top_k":
                    return self._run_topk_sidecar(idx)
                return self._run_count_distinct_sidecar(idx)
            plan = self._replan_scan(plan)
        if plan.access_path == "index":
            idx = self._index_for_eq()
            # explicit per-op dispatch: an op added to the planner's
            # index-capable list but not here must fall to the (always
            # correct) scan path, never to another op's result shape
            runner = {"select": self._run_select_indexed,
                      "top_k": self._run_topk_indexed,
                      "quantiles": self._run_column_indexed,
                      "count_distinct": self._run_column_indexed,
                      "aggregate": self._run_aggregate_indexed,
                      "group_by": self._run_groupby_indexed,
                      "join": self._run_join_indexed,
                      }.get(self._op)
            if self._op == "aggregate" and self._agg_exprs is not None:
                # expression sums have no host emulation (the fused
                # kernel IS the implementation); scan instead of
                # returning the wrong result shape
                runner = None
            if (self._op == "join" and self._join_src is not None
                    and self._join_strategy()[0] == "partitioned"):
                # index-served joins probe the build host-side; a
                # partitioned-sized ON-DISK build must keep join_table's
                # bounded-RAM contract, so it takes the scan path's
                # streamed Grace passes instead of resolving here
                runner = None
            if idx is not None and runner is not None:
                return runner(idx, device, session)
            plan = self._replan_scan(plan)
        if self._op == "select":
            return self._run_select(plan, device, session)
        if self._op == "join":
            js = self._join_strategy()
            if js is not None and js[0] == "partitioned":
                return self._run_join_partitioned(plan, mesh, device,
                                                  session, js[1],
                                                  batch_pages)
            if self._join[3]:   # materialize=True
                return self._run_join_rows(plan, device, session)
        if self._op == "order_by":
            return self._run_order_by(plan, mesh, device, session)
        if self._op == "count_distinct":
            return self._run_count_distinct(plan, mesh, device, session)
        if self._op == "quantiles":
            return self._run_quantiles(plan, mesh, device, session)
        if plan.pushdown in ("chip", "host") and mesh is None \
                and self._op == "aggregate":
            # packed-sidecar scan: re-probe (the sidecar may have been
            # retired between EXPLAIN and now) and fall through to the
            # heap path when it raced away
            probe = self._pushdown_probe()
            if probe is not None and probe[0].mode in ("chip", "host"):
                return self._run_pushdown(probe[0], probe[1], device,
                                          session, kernel)
        chosen = plan.kernel if kernel == "auto" else kernel
        fn, combine = self._build_fn(chosen)
        if mesh is not None:
            import jax

            from ..parallel.stream import distributed_scan_filter
            from .executor import fold_results
            n_shards = mesh.shape["dp"]
            src, own = self._open_owned()
            try:
                n_pages = src.size // PAGE_SIZE
                bp = batch_pages or max(
                    n_shards, (1 << 20) // PAGE_SIZE * n_shards)
                # round to a shard multiple (user-supplied values included,
                # never below one page per shard) and shrink to the largest
                # batch that fits, so a small table or an odd batch_pages
                # still scans; the remainder rides the tail path below
                bp = max(bp // n_shards * n_shards, n_shards)
                bp = min(bp, n_pages // n_shards * n_shards)
                acc = None
                covered = 0
                if bp >= n_shards:
                    out = distributed_scan_filter(src, mesh, fn,
                                                  batch_pages=bp,
                                                  combine=combine,
                                                  session=session)
                    if out:
                        acc = out
                    covered = (n_pages // bp) * bp
                # the stream drops any partial final batch (it cannot fill
                # every shard evenly); scan the tail on a local device so
                # mesh results cover every page, like the local path does.
                # Batched reads: a table smaller than batch_pages arrives
                # whole on this path and must not become one giant alloc
                dev = jax.local_devices()[0]
                tail_batch = max((8 << 20) // PAGE_SIZE, 1)
                for p0 in range(covered, n_pages, tail_batch):
                    npg = min(tail_batch, n_pages - p0)
                    raw = bytearray(npg * PAGE_SIZE)
                    src.read_buffered(p0 * PAGE_SIZE, memoryview(raw))
                    pages = np.frombuffer(raw, np.uint8).reshape(
                        -1, PAGE_SIZE)
                    acc = fold_results(acc, fn(jax.device_put(pages, dev)),
                                       combine)
                if acc is None:
                    return {}
                return self._finalize(
                    jax.tree.map(np.asarray, acc))
            finally:
                if own:
                    src.close()
        if plan.access_path == "direct":
            from ..config import config as _cfg
            from .executor import TableScanner
            src, own = self._open_owned()
            try:
                with TableScanner(src, self.schema,
                                  session=session) as sc:
                    # kernel paths are jit-safe end to end (jitted page
                    # kernels, jnp combines) — coalesce their dispatches
                    out = sc.scan_filter(
                        fn, device=device, combine=combine,
                        dispatch_coalesce=int(
                            _cfg.get("scan_dispatch_batch")))
                    self._last_scan_h2d_depth = getattr(
                        sc, "last_h2d_depth", 0)
                    return self._finalize(out)
            finally:
                if own:
                    src.close()
        return self._finalize(self._vfs_scan(fn, combine, device))

    def _finalize(self, out: dict) -> dict:
        """Post-aggregation decoration for group_by: derived ``avgs``
        (sum/count), ``vars``/``stds`` (population variance via
        E[x²]−E[x]², NaN for empty groups) and the HAVING filter — applied
        AFTER the cross-batch/cross-device fold, which is what gives it
        SQL's post-aggregation semantics."""
        if self._op != "group_by" or not out:
            return out
        having = self._group[3]
        count = np.asarray(out["count"])
        sums = np.asarray(out["sums"])
        # AVG/VAR denominators: per-column non-NULL counts when the
        # kernel emitted them (nullable aggregate columns), else the
        # group row count — an all-NULL group's average is NaN (SQL
        # NULL), exactly like an empty group's
        nn = np.asarray(out["nncounts"]) if "nncounts" in out else None
        base = nn if nn is not None else count
        with np.errstate(divide="ignore", invalid="ignore"):
            denom = np.maximum(base, 1)
            avgs = np.where(base > 0, sums / denom, np.nan)
        res = {"count": count, "sums": sums,
               "mins": np.asarray(out["mins"]),
               "maxs": np.asarray(out["maxs"]), "avgs": avgs}
        if nn is not None:
            res["nncounts"] = nn
            if (nn == 0).any():
                # all-NULL groups: SQL says MIN/MAX/SUM are NULL, not
                # the kernel's ±INT_MAX / 0 accumulator identities —
                # surface NULL as NaN at the result edge (the same face
                # avgs already wears), converting to float only when an
                # all-NULL group actually exists
                void = nn == 0
                for k in ("sums", "mins", "maxs"):
                    res[k] = np.where(void, np.nan,
                                      res[k].astype(np.float64))
        if "sumsqs" in out:
            sumsqs = np.asarray(out["sumsqs"], dtype=np.float64)
            with np.errstate(divide="ignore", invalid="ignore"):
                # clamp: E[x^2]-E[x]^2 can dip epsilon-negative in floats
                vars_ = np.maximum(
                    np.where(base > 0, sumsqs / denom - np.square(avgs),
                             np.nan), 0.0)
            res["sumsqs"] = sumsqs
            res["vars"] = vars_
            res["stds"] = np.sqrt(vars_)
        if having is None:
            return res
        mask = np.asarray(having(res)).astype(bool)
        if mask.shape != count.shape:
            raise StromError(22, f"having must return a ({len(count)},) "
                                 f"bool mask, got shape {mask.shape}")
        res = {k: (v[mask] if v.ndim == 1 else v[..., mask])
               for k, v in res.items()}
        res["groups"] = np.flatnonzero(mask).astype(np.int32)
        if self._group_cols is not None and \
                getattr(self, "_gk_decode", None) is not None:
            # the SELECT-list face of GROUP BY: actual key values per
            # surviving group (group_by_cols contract)
            res["key_cols"] = self._gk_decode(res["groups"])
        return res

    # -- sorted (spill) GROUP BY -------------------------------------------
    _SPILL_HARD_MAX = 1 << 24   # truly-unbounded guard (ENOMEM past this)

    def _sorted_group_ctx(self):
        """Shared setup for the sorted-aggregation GROUP BY (serial and
        worker halves): ``(key_cols, agg_idx, packer, accumulator)``."""
        from ..ops.groupby import _check_agg_cols, acc_dtypes
        from .index import pack_pair
        cols_, agg, _user_having, _mg = self._group_cols
        agg_idx, agg_dt = _check_agg_cols(self.schema, agg)
        for c in agg_idx:
            if self.schema.col_nullable(c):
                raise StromError(22, f"group_by_cols: c{c} is nullable "
                                     f"and the key set exceeded "
                                     f"max_groups — high-cardinality "
                                     f"GROUP BY over nullable "
                                     f"aggregates is outside this "
                                     f"subset")
        acc_np, sq_np, lo, hi = acc_dtypes(agg_dt)
        dts = [self.schema.col_dtype(c) for c in cols_]
        if len(cols_) == 1:
            packer = lambda ks: ks[0]
        else:
            packer = lambda ks: pack_pair(ks[0], ks[1], dts[0], dts[1])
        acc = _SortedGroupAcc(len(agg_idx), acc_np, sq_np, lo, hi,
                              self._SPILL_HARD_MAX)
        return cols_, agg_idx, packer, acc

    def _sorted_group_scan(self, acc, cols_, agg_idx, packer, device,
                           session, *, scanner=None) -> None:
        """Stream the scan through the sorted accumulator: gather key +
        aggregate columns, pack keys, sort-reduce per batch, merge."""
        gather, _f, _d = self._make_gather_fn(list(cols_) + list(agg_idx),
                                              want_positions=False)
        nk = len(cols_)

        def collect(pages_dev):
            out = gather(pages_dev)
            m = np.asarray(out["mask"]).astype(bool)
            ks = [np.asarray(out[f"f{i}"])[m] for i in range(nk)]
            vals = np.stack([np.asarray(out[f"f{nk + j}"])[m]
                             for j in range(len(agg_idx))])
            acc.add_batch(packer(ks), vals)
            return {}

        if scanner is not None:
            scanner.scan_filter(collect, device=device)
        else:
            self._stream_collect(self._explain_inner(), collect, device,
                                 session)

    def _sorted_group_result(self, acc) -> dict:
        """Fold the accumulator state into the group_by result contract
        (same faces as the one-hot kernels + ``key_cols``), via
        :meth:`_finalize` so HAVING/avgs/vars compose identically."""
        from .index import unpack_second
        cols_, agg, user_having, _mg = self._group_cols
        dts = [self.schema.col_dtype(c) for c in cols_]
        st = acc.state()
        keys = st.pop("keys")

        def hv(res, user=user_having):
            m = np.asarray(res["count"]) > 0
            if user is not None:
                m = m & np.asarray(user(res)).astype(bool)
            return m

        self._group = (None, len(keys), agg, hv)
        if len(cols_) == 1:
            self._gk_decode = lambda gids, keys=keys: [
                keys.astype(dts[0])[gids]]
        else:
            hi_w = (keys >> np.uint64(32))
            if dts[0] == np.dtype(np.int32):
                k0 = (hi_w.astype(np.int64) - (1 << 31)).astype(np.int32)
            else:
                k0 = hi_w.astype(np.uint32)
            k1 = unpack_second(keys, dts[1])
            self._gk_decode = lambda gids, k0=k0, k1=k1: [k0[gids],
                                                          k1[gids]]
        return self._finalize(st)

    def _run_groupby_sorted(self, device, session) -> dict:
        """GROUP BY past the one-hot budget (``max_groups``): sort-then-
        segment-reduce — each batch's selected rows sort by packed key
        and ``reduceat`` into per-key partials, merged into a running
        sorted state whose footprint is O(distinct keys), not
        O(rows x groups) like the one-hot contraction.  The SQL executor
        the reference sits under switches to sort-aggregation for
        high-cardinality keys the same way.  Local host path (the mesh
        one-hot path keeps its own budget); result contract identical to
        the kernel path."""
        cols_, agg_idx, packer, acc = self._sorted_group_ctx()
        self._sorted_group_scan(acc, cols_, agg_idx, packer, device,
                                session)
        return self._sorted_group_result(acc)

    # -- parallel worker processes (the Gather analog) ----------------------
    _WORKER_OPS = ("aggregate", "group_by", "top_k", "select", "star")

    def _worker_spec(self, discovered=None) -> dict:
        """Picklable reconstruction recipe for worker processes: the
        structured filter, SQL predicate trees, terminal, and (for
        value-keyed GROUP BY) the leader-discovered key set."""
        import jax

        from ..config import config as _cfg
        spec = {
            "source": self.source,
            "schema": (self.schema.n_cols, self.schema.visibility,
                       self.schema.dtypes, self.schema.nullable),
            "chunk_size": int(_cfg.get("chunk_size")),
            # leader-side runtime state workers must mirror: the config
            # snapshot (join_broadcast_max, scan knobs, ...) and the
            # x64 flag (acc_dtypes widens int sums under x64 — a worker
            # accumulating at a different width would fold silently
            # different partials)
            "config": _cfg.snapshot(),
            "x64": bool(jax.config.jax_enable_x64),
            "eq": self._eq, "rng": self._range, "in": self._in,
            "trees": list(self._pred_trees),
            "op": self._op,
            "agg_cols": (None if self._agg_cols is None
                         else list(self._agg_cols)),
            "agg_exprs": self._agg_exprs,
            "select": self._select,
            "topk": self._topk,
        }
        if self._op == "star":
            spec["star"] = self._star
        if self._op == "group_by":
            cols_, agg, _hv, max_groups = self._group_cols
            spec["group"] = (list(cols_), None if agg is None
                             else list(agg), int(max_groups))
            spec["discovered"] = discovered
        return spec

    @classmethod
    def _from_worker_spec(cls, spec: dict) -> "Query":
        """Rebuild the leader's query inside a worker process from the
        picklable spec (inverse of :meth:`_worker_spec`)."""
        n_cols, vis, dts, nullable = spec["schema"]
        schema = HeapSchema(n_cols=n_cols, visibility=vis, dtypes=dts,
                            nullable=nullable)
        q = cls(spec["source"], schema)
        if spec["eq"] is not None:
            col, v = spec["eq"]
            if v is None:    # no representable literal: matches nothing
                c0 = int(col[0]) if isinstance(col, (tuple, list)) \
                    else int(col)
                q._pred = lambda cols: cols[c0] != cols[c0]
                q._set_structured(eq=(col, None))
            elif isinstance(col, (tuple, list)):
                q.where_eq(tuple(col), tuple(v))
            else:
                q.where_eq(col, v)
        elif spec["rng"] is not None:
            c, lo, hi = spec["rng"]
            q.where_range(c, lo, hi)
        elif spec["in"] is not None:
            c, members = spec["in"]
            q.where_in(c, members)
        from .sql import _tree_mask
        for t in spec["trees"]:
            q.where(lambda cols, t=t: _tree_mask(t, cols), _tree=t)
        op = spec["op"]
        if op == "aggregate":
            if spec.get("agg_exprs"):
                q.aggregate_exprs(spec["agg_exprs"])
            else:
                q.aggregate(spec["agg_cols"])
        elif op == "star":
            st = spec["star"]
            q.star_join(st["joins"], exprs=st["exprs"])
        elif op == "top_k":
            tc, tk, tl = spec["topk"]
            q.top_k(tc, tk, largest=tl)
        elif op == "select":
            cols, limit, offset = spec["select"]
            # offset applies on the LEADER (rows split across workers);
            # each worker gathers up to offset+limit and the leader
            # slices the concatenation
            stop = None if limit is None else limit + offset
            q.select(cols, limit=stop, offset=0)
        elif op in ("group_by", "group_sorted"):
            cols_, agg, max_groups = spec["group"]
            q.group_by_cols(cols_, agg_cols=agg, max_groups=max_groups)
            if op == "group_by":
                q._install_group_keys(spec["discovered"])
            else:    # spill: workers sort-aggregate, no key table
                q._op = "group_sorted"
        else:
            raise StromError(22, f"worker spec op {op!r}")
        return q

    def _run_worker_partial(self, spec: dict, cursor) -> dict:
        """Worker-side execution: scan chunks claimed from the SHARED
        cursor with this process's own Session and return the picklable
        partial (raw accumulator — the leader folds and finalizes).
        ``scan_s`` rides along: the worker's own scan wall time, net of
        process spawn/jit, so the leader can report how the scan work
        actually divided."""
        import time as _time

        from .executor import TableScanner
        t0 = _time.monotonic()
        out = self._worker_partial_inner(spec, cursor, TableScanner)
        out["scan_s"] = _time.monotonic() - t0
        return out

    def _worker_partial_inner(self, spec: dict, cursor,
                              TableScanner) -> dict:
        with TableScanner(self.source, self.schema, cursor=cursor,
                          chunk_size=spec["chunk_size"],
                          numa_bind=False) as sc:
            if self._op == "group_sorted":
                cols_, agg_idx, packer, acc = self._sorted_group_ctx()
                self._sorted_group_scan(acc, cols_, agg_idx, packer,
                                        None, None, scanner=sc)
                return {"sorted": acc.state()}
            if self._op in ("aggregate", "group_by", "top_k", "star"):
                if self._op == "star":
                    # each worker loads the (broadcast-sized) dims once
                    self._resolve_star_builds(None, None)
                fn, combine = self._build_fn("xla")
                return {"acc": sc.scan_filter(fn, combine=combine)}
            # select: the shared row-collection machinery, fed from
            # THIS scanner (the spec already folded offset into stop)
            cols, stop, _off = self._select
            if cols is None:
                cols = list(range(self.schema.n_cols))
            gather, fields, dtypes = self._make_gather_fn(cols)
            arrs = self._collect_rows(None, gather, "mask", fields,
                                      dtypes, None, None, limit=stop,
                                      offset=0, scanner=sc)
            return {"rows": arrs}

    def _run_workers(self, n_workers: int, *, session=None,
                     device=None) -> dict:
        """Leader side of the parallel scan: validate the query is
        worker-shippable, resolve GROUP BY keys once (workers must share
        one key space), fan out via :func:`.parallel.run_query_workers`,
        and fold the partials exactly like the batch fold."""
        from .executor import fold_results
        from .parallel import run_query_workers
        if not isinstance(self.source, str):
            raise StromError(22, "workers: parallel scan takes a single "
                                 "on-disk table path (striped sets scan "
                                 "serially or via a mesh)")
        # plan validation BEFORE spawning: a query the serial path
        # refuses with a clean StromError must refuse identically here,
        # not crash inside N worker processes
        plan = self.explain()
        if plan.kernel == "invalid":
            raise StromError(22, f"query not executable: {plan.reason}")
        if self._join is not None or self._join_src is not None:
            raise StromError(22, "workers: JOIN is not worker-servable "
                                 "yet (use the mesh partitioned join)")
        if self._opaque_pred:
            raise StromError(22, "workers: an opaque where() lambda "
                                 "cannot ship to worker processes — use "
                                 "where_eq/where_range/where_in or the "
                                 "SQL facade (predicate trees travel)")
        spill = False
        discovered = None
        if self._op == "group_by":
            if self._group_cols is None:
                raise StromError(22, "workers: group_by needs "
                                     "group_by_cols (key-function "
                                     "closures cannot ship)")
            if self._group[0] is None:
                try:
                    discovered = self._discover_group_keys(session,
                                                           device)
                    self._install_group_keys(discovered)
                except _GroupSpill:
                    spill = True
            else:
                discovered = getattr(self, "_group_discovered", None)
                if discovered is None:
                    raise StromError(22, "workers: group keys resolved "
                                         "without a shippable key set")
        elif self._op == "star" and self._star["materialize"]:
            raise StromError(22, "workers: the star row face is not "
                                 "worker-servable (aggregate face "
                                 "only)")
        elif self._op not in self._WORKER_OPS:
            raise StromError(22, f"workers: terminal {self._op!r} is "
                                 f"not worker-servable "
                                 f"({'/'.join(self._WORKER_OPS)})")
        spec = self._worker_spec(discovered)
        if spill:
            spec["op"] = "group_sorted"
        partials = run_query_workers(spec, n_workers)
        winfo = {"n": n_workers,
                 "scan_s": [round(p.pop("scan_s", 0.0), 6)
                            for p in partials]}

        def _tag(out: dict) -> dict:
            # per-worker scan seconds (net of spawn/jit) — the Gather
            # observability face; assemblers drop it like "_analyze"
            if isinstance(out, dict) and out:
                out["_workers"] = winfo
            return out
        if spill:
            _c, _a, _p, acc = self._sorted_group_ctx()
            for p in partials:
                acc.merge_state(p["sorted"])
            return _tag(self._sorted_group_result(acc))
        if self._op == "select":
            cols, limit, offset = self._select
            if cols is None:
                cols = list(range(self.schema.n_cols))
            _g, fields, dtypes = self._make_gather_fn(cols)
            rows = [p["rows"] for p in partials]
            arrs = [np.concatenate([r[i] for r in rows])
                    if rows else np.zeros(0, dtypes[i])
                    for i in range(len(fields))]
            stop = None if limit is None else offset + limit
            arrs = [a[offset:stop] for a in arrs]
            named = dict(zip(fields, arrs))
            out = {f"col{c}": named[f"f{i}"]
                   for i, c in enumerate(cols)}
            for i, c in enumerate(cols):
                if f"n{i}" in named:
                    out[f"null{c}"] = named[f"n{i}"]
            out["positions"] = named["pos"]
            out["count"] = np.int64(len(out["positions"]))
            return _tag(out)
        accs = [p["acc"] for p in partials if p["acc"]]
        if not accs:
            # empty table: no worker claimed a chunk, so no partial
            # accumulator exists.  Synthesize the terminal's normal
            # zero-row result (count=0, zero sums/nncounts, empty
            # groups) by running its kernel over one all-zero page —
            # n_tuples=0 decodes to zero valid rows, so the shapes,
            # dtypes and keys match a real scan exactly; a bare {}
            # crashed every consumer that indexed the result
            import jax
            from .heap import PAGE_SIZE
            if self._op == "star":
                self._resolve_star_builds(None, None)
            fn0, _combine0 = self._build_fn("xla")
            acc0 = fn0(np.zeros((1, PAGE_SIZE), np.uint8))
            return _tag(self._finalize(jax.tree.map(np.asarray, acc0)))
        if self._op == "group_by":
            from ..ops.groupby import combine_groupby
            combine = combine_groupby
        elif self._op == "top_k":
            _fn, combine = self._build_fn("xla")
        else:
            combine = None
        folded = None
        for a in accs:
            folded = fold_results(folded, a, combine)
        import jax
        return _tag(self._finalize(jax.tree.map(np.asarray, folded)))

    def _check_sortable_col(self, col: int, opname: str) -> np.dtype:
        if not 0 <= col < self.schema.n_cols:
            raise StromError(22, f"{opname} column {col} out of range")
        dt = self.schema.col_dtype(col)
        if dt not in (np.dtype(np.int32), np.dtype(np.uint32),
                      np.dtype(np.float32)):
            raise StromError(22, f"{opname} supports int32/uint32/"
                                 f"float32 columns (got {dt})")
        if self.schema.col_nullable(col):
            raise StromError(22, f"{opname} over the nullable c{col} is "
                                 f"outside this subset (no NULL "
                                 f"ordering)")
        return dt

    @staticmethod
    def _pos_dtype():
        import jax
        return np.int64 if jax.config.jax_enable_x64 else np.int32

    def _make_gather_fn(self, cols: Sequence[int],
                        want_positions: bool = True):
        """Jitted per-batch gather of projected columns (+ global
        positions) with the query predicate folded in.  Returns
        ``(batch_fn, field_names, empty_dtypes)`` for
        :meth:`_collect_rows`; field ``f<i>`` is ``cols[i]``, positions
        (if requested) are last."""
        import jax

        from ..ops.filter_xla import decode_pages, global_row_positions
        pred = self._pred
        cols = list(cols)

        @jax.jit
        def gather(pages):
            dcols, valid = decode_pages(pages, self.schema)
            if pred is not None:
                valid = valid & pred(dcols)
            out = {"mask": valid.reshape(-1)}
            for i, c in enumerate(cols):
                out[f"f{i}"] = dcols[c].reshape(-1)
                if c in dcols.nulls:   # NULL masks ride along (round 5)
                    out[f"n{i}"] = dcols.nulls[c].reshape(-1)
            if want_positions:   # distinct never reads them — skip the
                out["pos"] = global_row_positions(   # decode + D2H
                    pages, self.schema).reshape(-1)
            return out

        fields = [f"f{i}" for i in range(len(cols))]
        dtypes = [self.schema.col_dtype(c) for c in cols]
        for i, c in enumerate(cols):
            if self.schema.col_nullable(c):
                fields.append(f"n{i}")
                dtypes.append(np.dtype(bool))
        if want_positions:
            fields.append("pos")
            dtypes.append(self._pos_dtype())
        return gather, fields, dtypes

    def _collect_rows(self, plan: Optional[QueryPlan], batch_fn,
                      mask_key: str,
                      fields: Sequence[str], empty_dtypes, device,
                      session, *, limit: Optional[int] = None,
                      offset: int = 0, scanner=None) -> List[np.ndarray]:
        """Shared row-materialization engine (SELECT and the join's row
        face): stream batches, compress rows by ``batch_fn``'s *mask_key*
        output host-side (one concat at the end — a fold-style growing
        device concat would copy the accumulator once per batch), stop
        issuing I/O once ``offset+limit`` rows are gathered, and slice.
        Returns one array per field."""
        stop = None if limit is None else offset + limit
        chunks = []
        gathered = 0

        def collect(pages_dev):
            nonlocal gathered
            out = batch_fn(pages_dev)
            mask = np.asarray(out[mask_key]).astype(bool)
            chunks.append([np.asarray(out[f])[mask] for f in fields])
            gathered += int(mask.sum())
            if stop is not None and gathered >= stop:
                raise _ScanLimitReached
            return {}   # nothing to fold

        self._stream_collect(plan, collect, device, session,
                             scanner=scanner)
        if chunks:
            arrs = [np.concatenate([c[i] for c in chunks])
                    for i in range(len(fields))]
        else:
            arrs = [np.zeros(0, dt) for dt in empty_dtypes]
        return [a[offset:stop] for a in arrs]

    def _stream_collect(self, plan: Optional[QueryPlan], collect, device,
                        session, *, scanner=None) -> None:
        """Stream the planned access path through a host-side collector
        (shared by the SELECT gather and the materializing join); a
        :class:`_ScanLimitReached` from *collect* stops the scan.  A
        caller-supplied *scanner* (the worker path's shared-cursor
        TableScanner) replaces plan-driven source opening."""
        try:
            if scanner is not None:
                scanner.scan_filter(collect, device=device)
            elif plan.access_path == "direct":
                from .executor import TableScanner
                src, own = self._open_owned()
                try:
                    with TableScanner(src, self.schema,
                                      session=session) as sc:
                        sc.scan_filter(collect, device=device)
                        self._last_scan_h2d_depth = getattr(
                            sc, "last_h2d_depth", 0)
                finally:
                    if own:
                        src.close()
            else:
                self._vfs_scan(collect, None, device)
        except _ScanLimitReached:
            pass

    def fetch(self, positions, cols: Optional[Sequence[int]] = None, *,
              session=None, device=None,
              max_batch_pages: int = 4096) -> dict:
        """Point lookup by global row position — the index-access face
        the seqscan-only reference lacks: ONLY the pages containing
        *positions* are read (8KB page grid; the engine's merge planner
        consolidates contiguous pages into ``dma_max`` requests,
        `kmod/nvme_strom.c:1473-1505`), decoded on device, and the
        requested rows gathered in caller order.

        Returns ``{"col<i>": values, "valid": mask}`` — ``valid`` is
        False for rows whose slot is past the page's tuple count or
        marked invisible.  Duplicate and unsorted positions are fine.
        Not a terminal: usable on any Query (e.g. feed ``top_k``
        positions back to fetch the full rows)."""
        import jax

        from ..engine import read_chunk_ids
        if cols is None:
            cols = list(range(self.schema.n_cols))
        for c in cols:
            if not 0 <= c < self.schema.n_cols:
                raise StromError(22, f"fetch column {c} out of range")
        pos = np.asarray(positions, np.int64).reshape(-1)
        t = self.schema.tuples_per_page
        src, own = self._open_owned()
        try:
            n_pages = src.size // PAGE_SIZE
            if len(pos) and (pos.min() < 0 or pos.max() >= n_pages * t):
                raise StromError(34, f"position outside the table "
                                     f"({n_pages * t} rows)")
            if not len(pos):
                out = {f"col{c}": np.zeros(0, self.schema.col_dtype(c))
                       for c in cols}
                out["valid"] = np.zeros(0, bool)
                return out
            uniq = np.unique(pos // t)          # pages to touch, sorted
            dev = device or jax.local_devices()[0]
            gather = _fetch_gather_fn(self.schema, tuple(cols))

            from ..engine import Session as _S
            own_sess = session is None
            sess = session or _S()
            parts = []
            try:
                for b0 in range(0, len(uniq), max_batch_pages):
                    batch_pages = uniq[b0:b0 + max_batch_pages]
                    handle, buf = sess.alloc_dma_buffer(
                        len(batch_pages) * PAGE_SIZE)
                    try:
                        raw = read_chunk_ids(sess, src, batch_pages,
                                             PAGE_SIZE, handle, buf.view())
                        parts.append(np.array(raw).reshape(-1, PAGE_SIZE))
                    finally:
                        sess.unmap_buffer(handle)
                        buf.close()
            finally:
                if own_sess:
                    sess.close()
            pages = np.concatenate(parts) if len(parts) > 1 else parts[0]
            page_idx = np.searchsorted(uniq, pos // t).astype(np.int32)
            slot = (pos % t).astype(np.int32)
            out = gather(jax.device_put(pages, dev),
                         jax.device_put(page_idx, dev),
                         jax.device_put(slot, dev))
            return {k: np.asarray(v) for k, v in out.items()}
        finally:
            if own:
                src.close()

    def _run_select_indexed(self, idx, device, session) -> dict:
        """INDEX SCAN select: positions from the sidecar, then only the
        matching pages are read (``fetch``'s merge-planned lookups).
        Same result contract as :meth:`_run_select`; row order is index
        order (ascending key, build order within duplicates)."""
        cols, limit, offset = self._select
        if cols is None:
            cols = list(range(self.schema.n_cols))
        pos = self._index_positions(idx, session, device)
        # index rows were valid at build time and the table is stamped
        # unchanged; keep the defensive mask anyway — applied BEFORE the
        # offset/limit window, matching the seqscan's filter-then-slice
        # ordering (_collect_rows), so a hypothetical invalid row can only
        # shrink the candidate set, never shift the window.  The early
        # cut-off limit promises is preserved by fetching in batches and
        # stopping once offset+limit VALID rows are in hand (the batched
        # fetch-with-early-stop discipline, not fetch-everything).
        need = None if limit is None else offset + limit
        got_cols: dict = {f"col{c}": [] for c in cols}
        got_pos: list = []
        n_valid = 0
        step = max(1, len(pos)) if need is None else max(need, 1024)
        for b0 in range(0, len(pos), step):
            batch = pos[b0:b0 + step]
            out = self.fetch(batch, cols=cols, session=session,
                             device=device)
            keep = out.pop("valid")
            for c in cols:
                got_cols[f"col{c}"].append(out[f"col{c}"][keep])
            got_pos.append(batch[keep])
            n_valid += int(keep.sum())
            if need is not None and n_valid >= need:
                break
        end = None if limit is None else offset + limit
        res = {k: np.concatenate(v)[offset:end] if v else
               np.zeros(0, self.schema.col_dtype(int(k[3:])))
               for k, v in got_cols.items()}
        res["positions"] = (np.concatenate(got_pos)[offset:end]
                            if got_pos else np.zeros(0, np.int64))
        res["count"] = np.int64(len(res["positions"]))
        return res

    def _index_positions(self, idx, session=None,
                         device=None) -> np.ndarray:
        """Positions matching the structured filter via the sidecar —
        then RECHECKED against any residual :meth:`where` predicate
        (the PG Index Cond + Filter shape): the candidate rows' columns
        are fetched once (on the caller's session/device) and the
        residual mask applied, so every index runner downstream sees
        only fully-qualified rows."""
        pos = self._index_positions_cond(idx)
        if self._residual is None or len(pos) == 0:
            return pos
        pos = np.asarray(pos, np.int64)
        cols_all = list(range(self.schema.n_cols))
        # batched recheck: host memory stays bounded to one batch of
        # candidate rows however large the index cond's result is
        keep_parts = []
        batch = 1 << 16
        for b0 in range(0, len(pos), batch):
            pb = pos[b0:b0 + batch]
            out = self.fetch(pb, cols=cols_all, session=session,
                             device=device)
            colsd = _HostCols(
                {c: np.asarray(out[f"col{c}"]) for c in cols_all},
                nulls={c: np.asarray(out[f"null{c}"]).astype(bool)
                       for c in cols_all if f"null{c}" in out})
            mask = np.asarray(self._residual(colsd)) \
                .astype(bool).reshape(-1)
            # an invisible row's decoded values are garbage: never let
            # the residual resurrect one (downstream keeps would drop it
            # anyway; COUNT-style runners trust the position list)
            keep_parts.append(
                pb[mask & np.asarray(out["valid"]).astype(bool)])
        return np.concatenate(keep_parts)

    def _index_positions_cond(self, idx) -> np.ndarray:
        """The structured (index-cond) half of :meth:`_index_positions`."""
        prefix = idx.composite and not isinstance(self._index_col(),
                                                  (tuple, list))
        if self._eq is not None:
            # value None = the normalized literal can match no row (e.g.
            # 7.5 against an int column) — the seqscan's empty answer
            if self._eq[1] is None:
                return np.zeros(0, np.int64)
            if prefix:   # c0-only equality over a (c0, c1) sidecar
                v = self._eq[1]
                return idx.prefix_range(v, v)
            # composite pair and single value both arrive as ONE probe;
            # SortedIndex.lookup handles the packing when composite
            return idx.lookup([self._eq[1]])
        if self._in is not None:
            if prefix:
                parts = [idx.prefix_range(m, m) for m in self._in[1]]
                return np.concatenate(parts) if parts \
                    else np.zeros(0, np.int64)
            return idx.lookup(self._in[1])
        _c, lo, hi = self._range
        if prefix:
            return idx.prefix_range(lo, hi)
        return idx.range(lo, hi)

    @staticmethod
    def _nearest_ranks(qs, n: int):
        """Nearest-rank indices into a sorted order of *n* elements."""
        return [min(n - 1, max(0, int(np.ceil(q * n)) - 1)) for q in qs]

    def _run_column_indexed(self, idx, device, session) -> dict:
        """quantiles / count_distinct over index-resolved rows (p99
        WHERE key = X): only matching pages are read; the math is the
        local path's exactly."""
        col = self._order[0][0]
        self._check_sortable_col(col, self._op)
        pos = self._index_positions(idx, session, device)
        out = self.fetch(pos, cols=[col], session=session, device=device)
        vals = out[f"col{col}"][np.asarray(out["valid"]).astype(bool)]
        if self._op == "count_distinct":
            return {"distinct": np.int32(len(
                np.unique(vals, equal_nan=False)))}
        qs = self._quantiles
        n = len(vals)
        if n == 0:
            return {"quantiles": np.full(len(qs), np.nan, np.float64),
                    "n": np.int64(0)}
        svals = np.sort(vals)
        return {"quantiles": svals[self._nearest_ranks(qs, n)],
                "n": np.int64(n)}

    def _run_groupby_indexed(self, idx, device, session) -> dict:
        """GROUP BY over index-resolved rows (GROUP BY x WHERE key = v):
        only matching pages are read; per-group accumulation follows the
        kernel contract — count int32, integer sums EXACT in the shared
        accumulator dtype (ufunc.at, never float bincount), float sums/
        sumsqs equal up to summation order (sequential here, tree-reduced
        on device), min/max sentinels for empty groups — and the shared
        :meth:`_finalize` adds avgs/vars/HAVING on top."""
        from ..ops.groupby import _check_agg_cols, acc_dtypes
        key_fn, g, agg, _having = self._group
        cols_idx, agg_dt = _check_agg_cols(self.schema, agg)
        pos = self._index_positions(idx, session, device)
        # key_fn is an opaque lambda over ALL columns: fetch every column
        out = self.fetch(pos, session=session, device=device)
        keep = np.asarray(out["valid"]).astype(bool)
        cols = [np.asarray(out[f"col{c}"])[keep].reshape(1, -1)
                for c in range(self.schema.n_cols)]
        keys = np.asarray(key_fn(cols)).reshape(-1).astype(np.int64)
        sel = (keys >= 0) & (keys < g)
        keys = keys[sel]
        acc_t, sq_t, lo, hi = acc_dtypes(agg_dt)
        count = np.bincount(keys, minlength=g).astype(np.int32)
        V = len(cols_idx)
        sums = np.zeros((V, g), acc_t)
        sumsqs = np.zeros((V, g), sq_t)
        mins = np.full((V, g), hi, agg_dt)
        maxs = np.full((V, g), lo, agg_dt)
        any_null = any(self.schema.col_nullable(c)
                       for c in range(self.schema.n_cols))
        nncounts = np.zeros((V, g), np.int32)
        for vi, ci in enumerate(cols_idx):
            v = cols[ci].reshape(-1)[sel]
            # NULL exclusion mirrors the kernel: NULL rows add nothing
            # to sums and never touch min/max/sumsq (review finding:
            # the host emulation absorbed the stored zeros)
            if f"null{ci}" in out:
                nv = ~np.asarray(out[f"null{ci}"])[keep] \
                    .reshape(-1)[sel]
            else:
                nv = np.ones(len(v), bool)
            vv, kk = v[nv], keys[nv]
            np.add.at(sums[vi], kk, vv.astype(acc_t))
            np.add.at(sumsqs[vi], kk, vv.astype(sq_t) * vv.astype(sq_t))
            np.minimum.at(mins[vi], kk, vv)
            np.maximum.at(maxs[vi], kk, vv)
            np.add.at(nncounts[vi], kk, 1)
        res = {"count": count, "sums": sums, "sumsqs": sumsqs,
               "mins": mins, "maxs": maxs}
        if any_null:
            res["nncounts"] = nncounts
        return self._finalize(res)

    def _run_join_indexed(self, idx, device, session) -> dict:
        """Join over index-resolved rows (JOIN ... WHERE key = v): only
        matching fact pages are read; the probe is the same sorted-
        searchsorted discipline as the page kernel, and the aggregate
        face reproduces its accumulation dtypes via ``acc_dtypes``."""
        from ..ops.groupby import acc_dtypes
        from ..ops.join import _sorted_build
        if self._join_src is not None:
            # only broadcast-sized on-disk builds reach this runner (the
            # dispatch routes partitioned-sized ones to the scan path's
            # streamed passes); resolving here is therefore bounded
            self._resolve_join_build(session, device)
        probe_col, bk, bv, materialize, limit, offset = self._join
        how = self._join_how
        # the kernel path's exact build-side validation + sort (host
        # arrays; the probe column is int32 by that validation)
        keys, vals = _sorted_build(bk, bv, self.schema, probe_col)
        pos_all = np.sort(self._index_positions(idx, session, device))

        def probe_host(probe):
            if len(keys) == 0:
                return (np.zeros(len(probe), bool),
                        np.zeros(len(probe), np.int32))
            i = np.clip(np.searchsorted(keys, probe), 0, len(keys) - 1)
            return keys[i] == probe, vals[i]

        def emit_of(hit):
            # THE kernel emit derivation (ops.join._emit_mask works on
            # numpy operands too); rows here are already selected, so
            # sel = all-ones
            from ..ops.join import _emit_mask
            return np.asarray(_emit_mask(how, np.ones_like(hit), hit))

        if materialize:
            # batched fetch of ONLY the probe column, stopping once
            # offset+limit emitted rows are found (the early DMA cut-off
            # the seqscan face has)
            end = None if limit is None else offset + limit
            parts, got = [], 0
            batch = 65536
            for b0 in range(0, len(pos_all), batch):
                pb = pos_all[b0:b0 + batch]
                out = self.fetch(pb, cols=[probe_col], session=session,
                                 device=device)
                keep = np.asarray(out["valid"]).astype(bool)
                probe = np.asarray(out[f"col{probe_col}"])[keep]
                pb = pb[keep]
                hit, pay = probe_host(probe)
                emit = emit_of(hit)
                parts.append((pb[emit], probe[emit],
                              np.where(hit, pay, 0)[emit], hit[emit]))
                got += int(emit.sum())
                if end is not None and got >= end:
                    break
            if parts:
                pos_c = np.concatenate([p[0] for p in parts])
                key_c = np.concatenate([p[1] for p in parts])
                pay_c = np.concatenate([p[2] for p in parts])
                hit_c = np.concatenate([p[3] for p in parts])
            else:
                pos_c = np.zeros(0, np.int64)
                key_c = np.zeros(0, np.int32)
                pay_c = np.zeros(0, self._join_value_dtype())
                hit_c = np.zeros(0, bool)
            sl = slice(offset, end)
            return self._join_rows_result(
                how, pos_c[sl].astype(self._pos_dtype()),
                key_c[sl].astype(np.int32),
                pay_c[sl].astype(self._join_value_dtype()),
                hit_c[sl])
        # aggregate face: emitted count + per-column sums over EVERY
        # fact column (the kernel's run.sum_cols set, each in its
        # acc_dtypes accumulator — the GROUP BY convention) + the
        # per-how extras (payload_sum inner/left, null_count left)
        cols = list(range(self.schema.n_cols))
        out = self.fetch(pos_all, cols=cols, session=session,
                         device=device)
        keep = np.asarray(out["valid"]).astype(bool)
        probe = np.asarray(out[f"col{probe_col}"])[keep]
        hit, pay = probe_host(probe)
        emit = emit_of(hit)
        sums = [np.sum(np.asarray(out[f"col{c}"])[keep][emit],
                       dtype=acc_dtypes(self.schema.col_dtype(c))[0])
                for c in cols]
        res = {"matched": np.int32(int(emit.sum())),
               "sums": sums}
        if how in ("inner", "left"):
            res["payload_sum"] = np.sum(
                pay[hit], dtype=acc_dtypes(self._join_value_dtype())[0])
        if how == "left":
            res["null_count"] = np.int32(int((emit & ~hit).sum()))
        return res

    def _run_aggregate_indexed(self, idx, device, session) -> dict:
        """COUNT/SUM over index-resolved rows — the most common index
        query shape: only matching pages are read, and the sums
        reproduce the kernel path's accumulation dtypes exactly (column
        dtype for floats; 4-byte int accumulate without x64, 8-byte
        with — the same wrap semantics the MXU contraction has)."""
        from ..ops.groupby import acc_dtypes
        agg_cols = list(self._agg_cols) if self._agg_cols is not None \
            else list(range(self.schema.n_cols))
        pos = self._index_positions(idx, session, device)
        out = self.fetch(pos, cols=agg_cols, session=session,
                         device=device)
        keep = np.asarray(out["valid"]).astype(bool)
        any_null = any(self.schema.col_nullable(c)
                       for c in range(self.schema.n_cols))
        sums, nncounts = [], []
        for c in agg_cols:
            v = out[f"col{c}"][keep]
            acc = acc_dtypes(self.schema.col_dtype(c))[0]
            # stored NULL words are zero, so plain sums already skip
            # them; the DENOMINATORS must not (COUNT(c)/AVG(c))
            sums.append(np.sum(v, dtype=acc))
            if f"null{c}" in out:
                nncounts.append(np.int32(int(
                    (keep & ~np.asarray(out[f"null{c}"])).sum())))
            else:
                nncounts.append(np.int32(int(keep.sum())))
        res = {"count": np.int32(int(keep.sum())), "sums": sums}
        if any_null:    # key present iff the kernel path would emit it
            res["nncounts"] = nncounts
        return res

    def _run_topk_indexed(self, idx, device, session) -> dict:
        """top_k over index-resolved rows: fetch only matching pages,
        then rank through the SAME kernel ranking (``ops.topk.rank_topk``)
        the page path uses — one implementation, so the two access paths
        cannot drift on tie-breaking, NaN ranking, or the sentinel
        squash.  Candidates are pre-sorted by ascending position so
        first-occurrence tie-breaking means lowest position, exactly the
        scan-order contract."""
        import jax.numpy as jnp

        from ..ops.topk import rank_topk
        col, k, largest = self._topk
        dt = self.schema.col_dtype(col)
        pos = np.sort(self._index_positions(idx, session, device))
        out = self.fetch(pos, cols=[col], session=session, device=device)
        keep = np.asarray(out["valid"]).astype(bool)
        vals = out[f"col{col}"][keep]
        pos = pos[keep].astype(self._pos_dtype())
        v, p = rank_topk(jnp.asarray(vals), jnp.asarray(pos), k, dt,
                         largest)
        return {"values": np.asarray(v), "positions": np.asarray(p)}

    def _run_select(self, plan: QueryPlan, device, session) -> dict:
        """SELECT: stream the scan and hand the matching rows back —
        ``{"col<i>": values, "positions": rows, "count": n}``.  Mesh mode
        gathers on a local device (materialization has no reduction for
        the mesh to partition)."""
        cols, limit, offset = self._select
        if cols is None:
            cols = list(range(self.schema.n_cols))
        # out-of-range columns already surfaced by explain() as an
        # invalid plan; run() refused before reaching here
        gather, fields, dtypes = self._make_gather_fn(cols)
        arrs = self._collect_rows(plan, gather, "mask", fields, dtypes,
                                  device, session, limit=limit,
                                  offset=offset)
        named = dict(zip(fields, arrs))
        out = {f"col{c}": named[f"f{i}"] for i, c in enumerate(cols)}
        for i, c in enumerate(cols):
            if f"n{i}" in named:    # True = NULL (round 5)
                out[f"null{c}"] = named[f"n{i}"]
        out["positions"] = named["pos"]
        out["count"] = np.int64(len(out["positions"]))
        return out

    def _run_join_rows(self, plan: QueryPlan, device, session) -> dict:
        """SELECT-with-JOIN: stream the scan, probe the broadcast build
        table per batch, and hand the emitted rows back —
        ``{"positions", "keys", "count"}`` plus ``payload`` (inner/left)
        and ``matched`` (left)."""
        from ..ops.join import make_join_rows_fn
        probe_col, bk, bv, _mat, limit, offset = self._join
        how = self._join_how
        pred = self._pred
        run = make_join_rows_fn(
            self.schema, probe_col, bk, bv,
            predicate=(lambda cols: pred(cols)) if pred else None,
            how=how)
        fields, dtypes = self._join_row_fields(how)
        arrs = self._collect_rows(
            plan, run, "hit", fields, dtypes,
            device, session, limit=limit, offset=offset)
        return self._join_rows_result(how, *arrs)

    def _join_value_dtype(self) -> np.dtype:
        """The build payload's dtype (int32/uint32/float32)."""
        from ..ops.join import _value_dtype
        if self._join_src is not None:
            _bt, bs, _kc, vc = self._join_src
            return bs.col_dtype(vc)
        bv = self._join[2]
        return _value_dtype(bv) if bv is not None else np.dtype(np.int32)

    def _join_row_fields(self, how: str):
        """Kernel output fields the row face collects under *how* —
        faces that drop a column (semi/anti: payload+partner; inner:
        partner) never D2H-transfer or concatenate it."""
        fields = ["positions", "key"]
        dtypes = [self._pos_dtype(), np.int32]
        if how in ("inner", "left"):
            fields.append("payload")
            dtypes.append(self._join_value_dtype())
        if how == "left":
            fields.append("partner")
            dtypes.append(np.bool_)
        return fields, dtypes

    def _join_rows_result(self, how: str, poss, keyv, payl=None,
                          partner=None) -> dict:
        """One row-face result contract for every join strategy: the
        per-*how* key set (payload only where the face exposes the build
        side; the left face's ``matched`` NULL indicator)."""
        out = {"positions": poss, "keys": keyv,
               "count": np.int64(len(poss))}
        if how in ("inner", "left"):
            out["payload"] = payl
        if how == "left":
            out["matched"] = np.asarray(partner).astype(bool)
        return out

    @staticmethod
    def _sidecar_descending_perm(ka: np.ndarray, lo_i: int,
                                 hi_i: int) -> np.ndarray:
        """[lo_i, hi_i) of the STABLE descending permutation of an
        ascending-sorted key array: key groups reverse, rows WITHIN an
        equal-key group keep ascending (physical) order — matching the
        seqscan's stable lexsort over negated keys (a plain array
        reversal would flip duplicate groups internally and make index
        presence change the answer)."""
        n = len(ka)
        starts = np.flatnonzero(
            np.concatenate(([True], ka[1:] != ka[:-1])))
        group_ends = np.append(starts[1:], n)
        if hi_i <= 4096:
            # small head: walk key groups from the tail, stop once
            # offset+limit rows are in hand — honoring the plan's
            # "reads only the head" without an O(n log n) sort
            parts = []
            got = 0
            for gi in range(len(starts) - 1, -1, -1):
                parts.append(np.arange(starts[gi], group_ends[gi]))
                got += group_ends[gi] - starts[gi]
                if got >= hi_i:
                    break
            return np.concatenate(parts)[lo_i:hi_i]
        # large/unbounded output: one vectorized stable argsort over the
        # group ids beats a Python walk of every group
        g = np.cumsum(np.concatenate(
            ([0], (ka[1:] != ka[:-1]).astype(np.int64))))
        return np.argsort(-g, kind="stable")[lo_i:hi_i]

    def _run_topk_sidecar(self, idx) -> dict:
        """Unfiltered top_k over an indexed integer column: the k best
        keys are the sidecar's head (smallest) or stable-descending tail
        (largest) — no scan.  Candidates then pass through the SAME
        ``rank_topk`` as every other access path, so padding (worst
        sentinel, position -1) and the sentinel squash cannot drift."""
        import jax.numpy as jnp

        from ..ops.topk import rank_topk
        col, k, largest = self._topk
        dt = self.schema.col_dtype(col)
        n = len(idx.keys)
        take = min(k, n)
        if largest:
            perm = self._sidecar_descending_perm(idx.keys, 0, take)
            vals, pos = idx.keys[perm], idx.positions[perm]
        else:
            vals, pos = idx.keys[:take], idx.positions[:take]
        v, p = rank_topk(jnp.asarray(np.ascontiguousarray(vals)),
                         jnp.asarray(np.ascontiguousarray(pos)
                                     .astype(self._pos_dtype())),
                         k, dt, largest)
        return {"values": np.asarray(v), "positions": np.asarray(p)}

    def _run_quantiles_sidecar(self, idx) -> dict:
        """Unfiltered exact quantiles with ZERO table I/O: the sidecar's
        sorted keys ARE the order, nearest-rank picks read straight from
        it (integer columns only — float sidecars strip NaN)."""
        qs = self._quantiles
        n = len(idx.keys)
        if n == 0:
            return {"quantiles": np.full(len(qs), np.nan, np.float64),
                    "n": np.int64(0)}
        ranks = self._nearest_ranks(qs, n)
        return {"quantiles": np.ascontiguousarray(idx.keys[ranks]),
                "n": np.int64(n)}

    def _run_count_distinct_sidecar(self, idx) -> dict:
        """Unfiltered COUNT(DISTINCT) with ZERO table I/O: adjacent-diff
        over the sidecar's sorted keys."""
        k = idx.keys
        d = 0 if len(k) == 0 else int((k[1:] != k[:-1]).sum()) + 1
        return {"distinct": np.int32(d)}

    def _run_order_by_prefix(self, idx) -> dict:
        """``WHERE c0 = v ORDER BY c1`` from a composite (c0, c1)
        sidecar: the matching rows are ONE contiguous sidecar span,
        already sorted by c1 (packed-key low word) — no sort, no table
        I/O; values unpack straight from the keys."""
        from .index import unpack_second
        _ce, v = self._eq
        _cols, descending, limit, offset = self._order
        a, b = idx.prefix_span(v) if v is not None else (0, 0)
        span_keys = idx.keys[a:b]
        span_pos = idx.positions[a:b]
        n = b - a
        end = n if limit is None else min(n, offset + limit)
        lo_i, hi_i = min(offset, n), min(end, n)
        if descending:
            # the group walk needs the whole span's key order
            vals1 = unpack_second(span_keys, idx.key_dtypes[1])
            perm = self._sidecar_descending_perm(vals1, lo_i, hi_i)
            pos = span_pos[perm]
            vals = vals1[perm]
        else:
            # LIMIT touches only the head: slice BEFORE unpacking
            pos = span_pos[lo_i:hi_i]
            vals = unpack_second(span_keys[lo_i:hi_i], idx.key_dtypes[1])
        return {"values": np.ascontiguousarray(vals),
                "positions": np.ascontiguousarray(pos)
                .astype(self._pos_dtype())}

    def _run_order_by_indexed(self, idx, device, session) -> dict:
        """ORDER BY served from a fresh sidecar: the index order IS the
        answer — no sort, no full-column gather; a LIMIT touches only the
        head of the sidecar (and, for composite keys, only the head's
        pages).  Result contract matches :meth:`_run_order_by` local mode
        (``values`` = primary column, ``positions``); duplicate ordering
        is the build's physical order, same as the stable seqscan sort."""
        cols, descending, limit, offset = self._order
        self._check_sortable_col(cols[0], "order_by")
        n = len(idx.positions)
        end = n if limit is None else min(n, offset + limit)
        lo_i, hi_i = min(offset, n), min(end, n)
        if descending:
            perm = self._sidecar_descending_perm(idx.keys, lo_i, hi_i)
            pos = idx.positions[perm]
            keys = idx.keys[perm]
        else:
            pos = idx.positions[lo_i:hi_i]
            keys = idx.keys[lo_i:hi_i]
        pos = np.ascontiguousarray(pos)
        if not idx.composite:
            return {"values": np.ascontiguousarray(keys),
                    "positions": pos.astype(self._pos_dtype())}
        # composite sidecar: keys are packed pairs — fetch the primary
        # column's values for the (already sliced) head only
        out = self.fetch(pos, cols=[cols[0]], session=session,
                         device=device)
        keep = np.asarray(out["valid"]).astype(bool)
        return {"values": out[f"col{cols[0]}"][keep],
                "positions": pos[keep].astype(self._pos_dtype())}

    def _run_join_partitioned(self, plan: QueryPlan, mesh, device,
                              session, n_parts: int,
                              batch_pages: Optional[int] = None) -> dict:
        """Partitioned hash join — the build side is too large to
        broadcast (EXPLAIN's ``join_strategy``).

        Mesh: one scan; the build lives hash-sharded 1/dp per device and
        every batch all_to_all-routes rows to their key's owner
        (:mod:`..parallel.pjoin`).  Local: Grace-style sequential passes,
        one hash partition of the build resident at a time (n_parts
        scans, build memory bounded by ``join_broadcast_max``).  Results
        add across partitions because every build key lives in exactly
        one — and, for the left/anti faces, because each pass restricts
        itself to the probe rows its partition OWNS (an unpartnered row
        must be emitted by exactly one pass, not every pass).
        Materialized row order is per-partition arrival order —
        unspecified, like SQL without ORDER BY; parity with broadcast is
        set-equality."""
        probe_col, bk, bv, materialize, limit, offset = self._join
        how = self._join_how
        pred = self._pred
        from .executor import fold_results
        if mesh is not None and materialize:
            return self._run_join_partitioned_mesh_rows(
                mesh, session, device, batch_pages, probe_col, bk, bv,
                limit, offset)
        if mesh is not None and not materialize:
            from ..parallel.pjoin import make_partitioned_join_step
            step = make_partitioned_join_step(
                mesh, self.schema, probe_col, bk, bv,
                predicate=(lambda cols: pred(cols)) if pred else None,
                build_parts=self._streamed_build_parts(mesh, session,
                                                       device),
                how=how)
            src, own = self._open_owned()
            try:
                acc = None
                for pages in self._mesh_page_batches(src, mesh,
                                                     batch_pages, session):
                    acc = fold_results(acc, step(pages), None)
                import jax as _jax
                return {} if acc is None else \
                    _jax.tree.map(np.asarray, acc)
            finally:
                if own:
                    src.close()
        # local: Grace sequential passes (both faces)
        from ..ops.join import (hash_split_build, make_join_fn,
                                make_join_rows_fn)
        if self._join_src is not None:
            # on-disk build side: stream ONE partition per pass (hash
            # predicate pushdown) — host RAM bounded to a partition, and
            # a LIMIT early-exit below never even scans the build rows
            # of the partitions it skips
            parts = self._streamed_build_partitions(n_parts, session,
                                                    device)
        else:
            parts = hash_split_build(bk, bv, n_parts)
        if materialize:
            # LIMIT early-exit across Grace passes (VERDICT r3 #3): each
            # partition scan stops issuing I/O at its remaining row
            # budget, and partitions past the budget are never scanned
            # at all — matching the broadcast row face's early DMA
            # cut-off.  Row order is per-partition arrival order
            # (unspecified, like SQL without ORDER BY), so taking the
            # first offset+limit rows in partition order is a valid
            # instance of the contract.
            stop = None if limit is None else offset + limit
            fields, dtypes = self._join_row_fields(how)
            cols_acc = [[] for _ in fields]
            gathered = 0
            own_needed = how in ("left", "anti")
            for p, (pk, pv) in enumerate(parts):
                remaining = None if stop is None else stop - gathered
                if remaining is not None and remaining <= 0:
                    break
                run = make_join_rows_fn(
                    self.schema, probe_col, pk, pv,
                    predicate=(lambda cols: pred(cols)) if pred else None,
                    how=how,
                    owner_part=(n_parts, p) if own_needed else None)
                got = self._collect_rows(
                    plan, run, "hit", fields, dtypes,
                    device, session, limit=remaining)
                gathered += len(got[0])
                for acc, a in zip(cols_acc, got):
                    acc.append(a)
            end = None if limit is None else offset + limit
            if cols_acc[0]:
                arrs = [np.concatenate(a)[offset:end] for a in cols_acc]
            else:   # limit=0 breaks before any partition scans
                arrs = [np.zeros(0, dt) for dt in dtypes]
            return self._join_rows_result(how, *arrs)
        acc = None
        own_needed = how in ("left", "anti")
        for p, (pk, pv) in enumerate(parts):
            run = make_join_fn(
                self.schema, probe_col, pk, pv,
                predicate=(lambda cols: pred(cols)) if pred else None,
                how=how, owner_part=(n_parts, p) if own_needed else None)
            fn = lambda pages, run=run: run(pages)
            if plan.access_path == "direct":
                from ..config import config as _cfg
                from .executor import TableScanner
                src, own = self._open_owned()
                try:
                    with TableScanner(src, self.schema,
                                      session=session) as sc:
                        out = sc.scan_filter(
                            fn, device=device,
                            dispatch_coalesce=int(
                                _cfg.get("scan_dispatch_batch")))
                        self._last_scan_h2d_depth = getattr(
                            sc, "last_h2d_depth", 0)
                finally:
                    if own:
                        src.close()
            else:
                out = self._vfs_scan(fn, None, device)
            acc = fold_results(acc, out, None)
        import jax as _jax
        # per-leaf: the heterogeneous sums list keeps its acc dtypes
        return {} if acc is None else _jax.tree.map(np.asarray, acc)

    def _mesh_page_batches(self, src, mesh, batch_pages, session):
        """Yield dp-divisible page batches covering EVERY page of *src*:
        the double-buffered sharded stream for the batch-aligned body,
        then zero-padded host reads for the tail (zero pages decode as
        no valid tuples, so the shard_map'ed step covers them too).
        One implementation of the batch-rounding + tail discipline,
        shared by the partitioned join's aggregate and row faces."""
        from ..parallel.stream import ShardedBatchStream
        n_shards = mesh.shape["dp"]
        n_pages = src.size // PAGE_SIZE
        bp = batch_pages or max(
            n_shards, (1 << 20) // PAGE_SIZE * n_shards)
        bp = max(bp // n_shards * n_shards, n_shards)
        bp = min(bp, n_pages // n_shards * n_shards)
        covered = 0
        if bp >= n_shards:
            with ShardedBatchStream(src, mesh, batch_pages=bp,
                                    session=session) as stream:
                for _first, arr in stream:
                    yield arr
            covered = (n_pages // bp) * bp
        tail_batch = max((8 << 20) // PAGE_SIZE, n_shards)
        for p0 in range(covered, n_pages, tail_batch):
            npg = min(tail_batch, n_pages - p0)
            raw = bytearray(npg * PAGE_SIZE)
            src.read_buffered(p0 * PAGE_SIZE, memoryview(raw))
            pages = np.frombuffer(raw, np.uint8).reshape(-1, PAGE_SIZE)
            padn = (-npg) % n_shards
            if padn:
                pages = np.concatenate(
                    [pages, np.zeros((padn, PAGE_SIZE), np.uint8)])
            yield pages

    def _streamed_build_parts(self, mesh, session, device):
        """Mesh build parts for an on-disk build side (None when the
        build is host arrays): partition-sized Grace passes bounded by
        ``config join_build_host_max``."""
        if self._join_src is None:
            return None
        from ..parallel.pjoin import partition_build_sharded_from_table
        bt, bs, kc, vc = self._join_src
        return partition_build_sharded_from_table(
            bt, bs, kc, vc, mesh, session=session, device=device)

    def _streamed_build_partitions(self, n_parts: int, session, device):
        """Yield the local Grace passes' (keys, values) partitions from
        the on-disk build side.  Under ``join_build_host_max`` the table
        loads with ONE projection scan and partitions in memory (the
        same budget fast path as the mesh builder); above it, one
        hash-predicate scan per partition, host RAM bounded to a
        partition — with a size+mtime stamp re-checked between passes so
        a build table mutated mid-query fails (EIO) instead of silently
        double-counting keys that moved partitions."""
        import jax.numpy as jnp

        from ..config import config
        from ..ops.join import hash_split_build, key_hash32
        bt, bs, kc, vc = self._join_src
        if os.path.getsize(bt) <= int(config.get("join_build_host_max")):
            out = Query(bt, bs).select([kc, vc]).run(session=session,
                                                     device=device)
            yield from hash_split_build(
                np.asarray(out[f"col{kc}"], np.int32),
                np.asarray(out[f"col{vc}"], bs.col_dtype(vc)), n_parts)
            return

        def owner(cols):
            return (key_hash32(cols[kc]) % jnp.uint32(n_parts)) \
                .astype(jnp.int32)

        def stamp():
            st = os.stat(bt)
            return int(st.st_size), int(st.st_mtime_ns)

        s0 = stamp()
        for p in range(n_parts):
            part = Query(bt, bs) \
                .where(lambda cols, p=p: owner(cols) == p) \
                .select([kc, vc]).run(session=session, device=device)
            if stamp() != s0:
                raise StromError(5, f"build table {bt} changed between "
                                    f"partition passes")
            yield (np.asarray(part[f"col{kc}"], np.int32),
                   np.asarray(part[f"col{vc}"], bs.col_dtype(vc)))

    def _run_join_partitioned_mesh_rows(self, mesh, session, device,
                                        batch_pages,
                                        probe_col, bk, bv,
                                        limit: Optional[int],
                                        offset: int) -> dict:
        """Mesh partitioned join, row face (VERDICT r3 #3): the build
        lives hash-sharded 1/dp per device, every batch all_to_all-routes
        rows (key + position words) to their owner, and each owner's
        per-row outcomes come back for host-side compression — same
        result contract as the broadcast row face, with the same LIMIT
        early-exit (the stream stops issuing SSD DMA once offset+limit
        emitted rows are in hand)."""
        from ..parallel.pjoin import (combine_pos_words,
                                      make_partitioned_join_rows_step)
        how = self._join_how
        pred = self._pred
        step = make_partitioned_join_rows_step(
            mesh, self.schema, probe_col, bk, bv,
            predicate=(lambda cols: pred(cols)) if pred else None,
            build_parts=self._streamed_build_parts(mesh, session,
                                                   device),
            how=how)
        stop = None if limit is None else offset + limit
        chunks: List[tuple] = []
        gathered = 0

        fields, dtypes = self._join_row_fields(how)
        # positions arrive as exchange words; the remaining fields come
        # straight off the step's per-how output set
        tail_fields = fields[1:]

        def take(out) -> bool:
            nonlocal gathered
            emit = np.asarray(out["hit"]).astype(bool)
            lo = np.asarray(out["pos_lo"])[emit]
            hi = np.asarray(out["pos_hi"])[emit]
            chunks.append(
                (combine_pos_words(lo, hi, self._pos_dtype()),)
                + tuple(np.asarray(out[f])[emit] for f in tail_fields))
            gathered += int(emit.sum())
            return stop is not None and gathered >= stop
        src, own = self._open_owned()
        try:
            # LIMIT early-exit: the break closes the generator, which
            # shuts the sharded stream down and stops issuing SSD DMA
            for pages in self._mesh_page_batches(src, mesh, batch_pages,
                                                 session):
                if take(step(pages)):
                    break
        finally:
            if own:
                src.close()
        if chunks:
            arrs = [np.concatenate([c[i] for c in chunks])[offset:stop]
                    for i in range(len(fields))]
        else:
            arrs = [np.zeros(0, dt) for dt in dtypes]
        return self._join_rows_result(how, *arrs)

    @staticmethod
    def _mesh_sort_loop(mesh, factory, *arrays):
        """Shared capacity-resize loop of the distributed sort family:
        start at 2.5x balance slack over perfectly uniform buckets,
        double and rerun whenever skewed keys overflow a bucket.
        ``factory(devices, capacity) -> run``; returns ``(out, dp)``."""
        sort_devices = list(mesh.devices.reshape(-1))
        dp = len(sort_devices)
        n = len(arrays[0])
        capacity = max(64, -(-n * 5 // (2 * dp * dp)))
        while True:
            run = factory(sort_devices, capacity)
            out = run(*arrays)
            if int(out["n_dropped"]) == 0:
                return out, dp
            capacity *= 2

    def _run_quantiles(self, plan: QueryPlan, mesh, device,
                       session) -> dict:
        """Exact nearest-rank quantiles: gather the column, sort (locally
        or via the distributed sample sort), and read one value per rank
        from the bucket distribution — ``{"quantiles", "n"}``."""
        col = self._order[0][0]
        dt = self._check_sortable_col(col, "quantiles")
        gather, fields, dtypes = self._make_gather_fn(
            [col], want_positions=False)
        (vals,) = self._collect_rows(plan, gather, "mask", fields,
                                     dtypes, device, session)
        qs = self._quantiles
        n = len(vals)
        if n == 0:
            return {"quantiles": np.full(len(qs), np.nan, np.float64),
                    "n": np.int64(0)}
        # nearest-rank: index = ceil(q*n) - 1, clamped into the order
        ranks = self._nearest_ranks(qs, n)
        if mesh is None:
            svals = np.sort(vals)
            return {"quantiles": svals[ranks], "n": np.int64(n)}
        from ..parallel.sort import make_distributed_sort
        out, _dp = self._mesh_sort_loop(
            mesh,
            lambda devs, cap: make_distributed_sort(
                devs, capacity=cap, dtype=dt, with_payload=False)[0],
            vals)
        counts = np.asarray(out["count"])
        cum = np.cumsum(counts)
        picked = []
        for r in ranks:
            b = int(np.searchsorted(cum, r + 1))
            within = r - (int(cum[b - 1]) if b else 0)
            # fetch only the bucket row holding the rank, not the whole
            # (dp, dp*capacity) sorted array (the docstring's contract)
            picked.append(np.asarray(out["values"][b])[within])
        return {"quantiles": np.array(picked, dt), "n": np.int64(n)}

    def _run_count_distinct(self, plan: QueryPlan, mesh, device,
                            session) -> dict:
        """Exact COUNT(DISTINCT col): gathered values dedupe via the
        distributed sort + ppermute boundary count under a mesh, or a
        host unique count locally."""
        col = self._order[0][0]
        dt = self._check_sortable_col(col, "count_distinct")
        gather, fields, dtypes = self._make_gather_fn(
            [col], want_positions=False)
        (vals,) = self._collect_rows(plan, gather, "mask", fields,
                                     dtypes, device, session)
        if mesh is None:
            # equal_nan=False: each NaN is its own value (IEEE !=), the
            # same semantics the mesh kernel's adjacent-diff implements
            return {"distinct": np.int32(len(
                np.unique(vals, equal_nan=False)))}
        from ..parallel.sort import make_distributed_distinct
        out, _dp = self._mesh_sort_loop(
            mesh,
            lambda devs, cap: make_distributed_distinct(
                devs, capacity=cap, dtype=dt)[0],
            vals)
        return {"distinct": np.int32(out["distinct"])}

    def _run_order_by(self, plan: QueryPlan, mesh, device, session) -> dict:
        """ORDER BY: gather (values, global positions, validity) through
        the planned access path, then sort — distributed sample sort on a
        mesh, one-device lax sort locally.  Returns the flat global order
        ``{"values", "positions"}`` (+ ``per_device_count``/``n_dropped``
        info keys in mesh mode).

        The gather phase runs on one local device even in mesh mode (the
        sort collectives are the distributed piece); for multi-host
        gather-side sharding, stream via ``load_pages_sharded`` and feed
        :func:`..parallel.sort.make_distributed_sort` directly."""
        cols, descending, limit, offset = self._order
        end = None if limit is None else offset + limit
        if mesh is not None and len(cols) > 1:
            raise StromError(
                95,  # EOPNOTSUPP
                "mesh order_by sorts one key column (the slab exchange "
                "carries a single key); sort multi-column orderings "
                "locally, or pre-combine the keys into one column")
        dts = [self._check_sortable_col(c, "order_by") for c in cols]
        dt = dts[0]
        gather, fields, dtypes = self._make_gather_fn(cols)
        arrs = self._collect_rows(plan, gather, "mask", fields, dtypes,
                                  device, session)
        keys, poss = arrs[:-1], arrs[-1]
        # positions normalize to int32 on the mesh path (slab payload
        # width); keep the empty case's dtype consistent with that
        pos_np_t = np.int32 if mesh is not None else self._pos_dtype()
        vals = keys[0]
        if len(vals) == 0:   # empty source or nothing selected
            out = {"values": vals, "positions": poss.astype(pos_np_t)}
            if mesh is not None:   # keep the mesh contract's info keys
                out["per_device_count"] = np.zeros(
                    int(np.prod(list(mesh.shape.values()))), np.int32)
                out["n_dropped"] = np.int32(0)
            return out

        if mesh is None:
            # np.lexsort: LAST key is primary and the sort is stable, so
            # reversed keys give ORDER BY cols[0], cols[1], ...
            def sort_key(k):
                if not descending:
                    return k
                return -k if k.dtype.kind == "f" else ~k
            order = np.lexsort(tuple(sort_key(k)
                                     for k in reversed(keys)))[offset:end]
            return {"values": vals[order], "positions": poss[order]}

        from ..parallel.sort import make_distributed_sort
        n = len(vals)
        if poss.dtype != np.int32:
            # slab payloads are int32; past 2^31 rows a cast would wrap
            # row identity silently — refuse instead
            if n and int(poss.max()) > (1 << 31) - 1:
                raise StromError(
                    34, "mesh order_by row positions exceed int32; "
                    "tables past 2^31 rows need the local sort path")
            poss = poss.astype(np.int32)
        # the sort flattens the caller's (sp, dp) mesh into its own 1-D
        # dp axis — the concat below must walk ALL its buckets, not the
        # caller mesh's dp size
        out, dp = self._mesh_sort_loop(
            mesh,
            lambda devs, cap: make_distributed_sort(
                devs, capacity=cap, dtype=dt, descending=descending)[0],
            vals, poss)
        counts = np.asarray(out["count"])
        v = np.concatenate([np.asarray(out["values"])[b][:counts[b]]
                            for b in range(dp)])
        p = np.concatenate([np.asarray(out["payload"])[b][:counts[b]]
                            for b in range(dp)])
        return {"values": v[offset:end], "positions": p[offset:end],
                "per_device_count": counts, "n_dropped": np.int32(0)}

    def _vfs_scan(self, fn, combine, device) -> dict:
        """Buffered fallback below the planner threshold (the conventional
        path the reference leaves small tables on).  Reads through the
        Source abstraction, so multi-file stripe sets and live Source
        objects scan identically to the direct path."""
        import jax

        from .executor import fold_results
        dev = device or jax.local_devices()[0]
        src, own = self._open_owned()
        try:
            n_pages = src.size // PAGE_SIZE
            batch = max((8 << 20) // PAGE_SIZE, 1)
            acc = None
            for p0 in range(0, n_pages, batch):
                n = min(batch, n_pages - p0)
                raw = bytearray(n * PAGE_SIZE)
                src.read_buffered(p0 * PAGE_SIZE, memoryview(raw))
                pages = np.frombuffer(raw, np.uint8).reshape(n, PAGE_SIZE)
                acc = fold_results(acc, fn(jax.device_put(pages, dev)),
                                   combine)
        finally:
            if own:
                src.close()
        if acc is None:
            return {}
        # per-leaf: the heterogeneous sums list keeps its acc dtypes
        return jax.tree.map(np.asarray, acc)
