"""Scan path planning: eligibility cache, size threshold, cost model.

Capability analog of the pgsql extension's planner integration
(`pgsql/nvme_strom.c:217-633`):

* **capability cache** — per-directory CHECK_FILE *capability* probes
  (can this filesystem do direct load, which NUMA node, DMA64) cached with
  a TTL and an explicit ``invalidate()`` (the reference caches per
  tablespace with a syscache callback + 1-entry MRU, `:217-348`).
  Per-file facts (size) are always read fresh.
* **size threshold** — the direct path only pays off when the table cannot
  live in the host page cache; the reference gates on
  ``(RAM − shared_buffers)·⅔ + shared_buffers`` (`:1544-1559`), overridable
  by ``debug_no_threshold``.  Here RAM comes from /proc MemTotal and the
  "shared_buffers" analog is the configured staging pool size.
* **cost model** — per-page cost with the reduced ``seq_page_cost`` GUC
  (default ¼ of the conventional cost, `:1614-1625`) and a parallel divisor
  capped at 4 for the disk component (`:491-517`) so I/O cost does not
  shrink linearly with workers.
"""

from __future__ import annotations

import os
import threading
import time
import dataclasses
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from ..api import FileInfo
from ..config import config
from ..engine import check_file

__all__ = ["CapabilityCache", "capability_cache", "direct_scan_threshold",
           "should_use_direct_scan", "ScanCost", "cost_direct_scan",
           "cost_vfs_scan", "PushdownDecision", "decide_pushdown",
           "transport_rates"]

# conventional-path reference cost per 8KB page (PG's seq_page_cost = 1.0)
VFS_PAGE_COST = 1.0
CPU_TUPLE_COST = 0.01
_MAX_PARALLEL_DISK_DIVISOR = 4.0   # reference caps at 4 (:491-517)


class CapabilityCache:
    """Directory-level capability cache (TTL + explicit invalidation).

    Caches only directory-scoped facts — fs capability, DMA64 support, NUMA
    node, request cap.  File size is stat'ed fresh on every probe so one
    file's geometry is never attributed to another in the same directory."""

    def __init__(self, ttl_s: float = 60.0):
        self._lock = threading.Lock()
        self._cache: Dict[str, Tuple[FileInfo, float]] = {}
        self._mru: Optional[Tuple[str, FileInfo, float]] = None  # 1-entry MRU (:233)
        self.ttl_s = ttl_s

    def _fresh(self, path: str, cap: FileInfo) -> FileInfo:
        size = os.stat(path).st_size
        kind = cap.fs_kind if size >= 4096 else type(cap.fs_kind)(0)
        # replace(), not a field-by-field copy: a FileInfo field added
        # later must flow through the cache unchanged, not silently
        # reset to its default
        return dataclasses.replace(cap, path=path, file_size=size,
                                   fs_kind=kind)

    def probe(self, path: str) -> FileInfo:
        d = os.path.dirname(os.path.abspath(path)) or "/"
        now = time.monotonic()
        with self._lock:
            if self._mru is not None and self._mru[0] == d                     and now - self._mru[2] < self.ttl_s:
                return self._fresh(path, self._mru[1])
            hit = self._cache.get(d)
            if hit is not None and now - hit[1] < self.ttl_s:
                self._mru = (d, hit[0], hit[1])
                return self._fresh(path, hit[0])
        # honest facts only (strict=False): policy is applied live by
        # should_use_direct_scan, so toggling require_nvme_backing takes
        # effect immediately instead of after cache TTL
        cap = check_file(path, strict=False)
        with self._lock:
            self._cache[d] = (cap, now)
            self._mru = (d, cap, now)
        return self._fresh(path, cap)

    def invalidate(self, directory: Optional[str] = None) -> None:
        """Syscache-callback analog (`pgsql/nvme_strom.c:340-348`)."""
        with self._lock:
            if directory is None:
                self._cache.clear()
            else:
                self._cache.pop(os.path.abspath(directory), None)
            self._mru = None


capability_cache = CapabilityCache()


def _mem_total_bytes() -> int:
    """Physical RAM (the reference's threshold uses total RAM,
    pgsql/nvme_strom.c:1544-1559)."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) << 10
    except OSError:
        pass
    return 8 << 30


def direct_scan_threshold() -> int:
    """Table size above which the direct path is planned
    (reference `(RAM − shared_buffers)·⅔ + shared_buffers`, :1544-1559)."""
    ram = _mem_total_bytes()
    shared = config.get("buffer_size")
    return int((max(ram - shared, 0) * 2) // 3 + shared)


def should_use_direct_scan(path: str, *, table_size: Optional[int] = None) -> bool:
    """The add-path gate (`nvmestrom_add_scan_path`, :555-596)."""
    if not config.get("enabled"):
        return False
    info = capability_cache.probe(path)
    if not info.supported:
        return False
    # DMA64 was the reference's hard requirement for P2P BAR addressing
    # (pgsql/nvme_strom.c:313-318); on the pinned-host path the host
    # kernel owns addressing, so the shared strict predicate only gates
    # when backing verification is authoritative (live policy read —
    # cache holds honest facts)
    if config.get("require_nvme_backing") and not info.strict_eligible:
        return False
    size = table_size if table_size is not None else info.file_size
    if config.get("debug_no_threshold"):
        return True
    return size >= direct_scan_threshold()


@dataclass(frozen=True)
class ScanCost:
    startup: float
    total: float
    pages: int
    workers: int


def _parallel_divisor(workers: int) -> float:
    """PG's parallel divisor incl. leader contribution."""
    d = float(max(workers, 1))
    if workers >= 1:
        d += 0.3 * min(workers, 4) / 4  # leader does some work too
    return d


def cost_direct_scan(n_pages: int, n_tuples: int, *, workers: int = 0) -> ScanCost:
    """`cost_nvmestrom_scan` analog (:451-520): reduced per-page cost, disk
    component divided by at most 4 regardless of worker count."""
    page_cost = config.get("seq_page_cost") * VFS_PAGE_COST
    disk_div = min(_parallel_divisor(workers), _MAX_PARALLEL_DISK_DIVISOR)
    cpu_div = _parallel_divisor(workers)
    disk = n_pages * page_cost / disk_div
    cpu = n_tuples * CPU_TUPLE_COST / cpu_div
    return ScanCost(startup=0.0, total=disk + cpu, pages=n_pages, workers=workers)


def cost_vfs_scan(n_pages: int, n_tuples: int, *, workers: int = 0) -> ScanCost:
    disk = n_pages * VFS_PAGE_COST / min(_parallel_divisor(workers),
                                         _MAX_PARALLEL_DISK_DIVISOR)
    cpu = n_tuples * CPU_TUPLE_COST / _parallel_divisor(workers)
    return ScanCost(startup=0.0, total=disk + cpu, pages=n_pages, workers=workers)


# -- compute pushdown: where does each column expand? (ISSUE 14) -----------
#
# The AXI4MLIR question (PAPERS.md, arXiv:2402.19184): for each column,
# does decompression happen on the host, on the chip, or not at all (ship
# raw)?  The inputs are the OBSERVED codec ratio (exact, recorded by the
# encoder per column) and the live transport picture: when h2d is the
# ceiling (the measured reality here: h2d_peak 1.06 vs raw_seq_read 3.36
# GB/s), packed bytes must stay packed across the link and expand in
# VMEM; when the SSD is the ceiling instead, host expansion already
# captures the win and keeps the decode off the accelerator.

# round-4 measured fallbacks, used when BENCH_MATRIX.json is absent and
# no override/live sample exists
_H2D_GBPS_DEFAULT = 1.06
_SSD_GBPS_DEFAULT = 3.36

_bench_rates_cache: Optional[Tuple[Optional[float], Optional[float]]] = None


def _bench_matrix_rates() -> Tuple[Optional[float], Optional[float]]:
    """(h2d_peak, raw_seq_read) GB/s from the repo's BENCH_MATRIX.json,
    (None, None) when absent/unreadable.  Cached: the file only changes
    when `make bench-matrix` reruns."""
    global _bench_rates_cache
    if _bench_rates_cache is not None:
        return _bench_rates_cache
    import json
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "BENCH_MATRIX.json")
    h2d = ssd = None
    try:
        with open(path) as f:
            d = json.load(f)
        s = d.get("summary", d)
        h2d = float(s.get("h2d_peak")) if s.get("h2d_peak") else None
        ssd = float(s.get("raw_seq_read")) if s.get("raw_seq_read") else None
    except (OSError, ValueError, TypeError):
        pass
    _bench_rates_cache = (h2d, ssd)
    return _bench_rates_cache


def transport_rates() -> Tuple[float, float]:
    """(h2d_gbps, ssd_gbps) the pushdown decision runs on.

    h2d precedence: config override > live H2D rate meter (fed by
    transfer-bound scan fences) > BENCH_MATRIX calibration > measured
    default.  ssd precedence is the same minus the live meter (the scan
    path has no clean SSD-only probe)."""
    h2d = float(config.get("pushdown_h2d_gbps"))
    ssd = float(config.get("pushdown_ssd_gbps"))
    bh2d, bssd = _bench_matrix_rates()
    if not h2d:
        from ..hbm.staging import h2d_meter
        live = h2d_meter.observed_gbps()
        h2d = live if live else (bh2d or _H2D_GBPS_DEFAULT)
    if not ssd:
        ssd = bssd or _SSD_GBPS_DEFAULT
    return h2d, ssd


@dataclass(frozen=True)
class PushdownDecision:
    """Where a packed scan expands, and the wire-byte prediction EXPLAIN
    reports."""

    mode: str                    # "chip" | "host" | "raw"
    wire_bytes: int              # predicted bytes crossing host->device
    logical_bytes: int           # bytes the query logically consumes
    per_column: Tuple[tuple, ...]   # (col, codec, ratio, "chip"|"host"|"raw")
    reason: str

    def explain(self) -> str:
        cols = ", ".join(
            f"col{c}={where}({codec}" +
            (f" {ratio:.1f}x)" if codec != "raw" else ")")
            for c, codec, ratio, where in self.per_column)
        codecs = "+".join(sorted({codec for _c, codec, _r, _w
                                  in self.per_column})) or "none"
        return (f"pushdown {self.mode}: predicted wire bytes: "
                f"{self.wire_bytes} ({self.logical_bytes} logical, "
                f"codec={codecs}); {cols}; {self.reason}")


def decide_pushdown(meta, need_cols=None) -> PushdownDecision:
    """Per-column host/chip/raw expansion decision for a packed sidecar.

    *meta* is a ``scan/colpack.py`` PackedMeta; *need_cols* restricts the
    decision to the columns the query touches (projection pushdown).
    ``pushdown=on`` forces chip; ``auto`` keys on the observed codec
    ratio vs ``pushdown_chip_ratio`` and on which transport is the
    ceiling."""
    mode_cfg = config.get("pushdown")
    h2d, ssd = transport_rates()
    thresh = float(config.get("pushdown_chip_ratio"))
    need = set(range(len(meta.cols))) if need_cols is None \
        else set(need_cols)
    h2d_bound = ssd > h2d
    per_col, wire = [], 0
    for c, cm in enumerate(meta.cols):
        if c not in need:
            continue
        ratio = cm.ratio
        if mode_cfg == "on" or (ratio >= thresh and h2d_bound):
            where = "chip"         # packed across the link, expand in VMEM
        elif ratio >= thresh:
            where = "host"         # SSD-bound: packed off disk only
        else:
            where = "raw"          # codec never paid for itself
        per_col.append((c, cm.codec, round(ratio, 3), where))
        wire += cm.packed_bytes
    logical = 4 * meta.n_rows * len(per_col)
    # the file is ONE representation: per-page headers + unselected-column
    # regions ride along, so the honest wire prediction is whole packed
    # pages, scaled to nothing only when the scan goes raw
    wire_pages = meta.packed_bytes
    scan_ratio = logical / wire_pages if wire_pages else 1.0
    if mode_cfg == "off":
        mode, why = "raw", "pushdown=off"
    elif mode_cfg == "on":
        mode, why = "chip", "pushdown=on (forced)"
    elif not per_col:
        mode, why = "raw", "no packable columns in the projection"
    elif scan_ratio < thresh:
        mode, why = "raw", (f"whole-scan codec ratio {scan_ratio:.2f}x "
                            f"below chip threshold {thresh:.2f}x")
    elif h2d_bound:
        mode, why = "chip", (f"h2d is the ceiling ({h2d:.2f} vs SSD "
                             f"{ssd:.2f} GB/s): packed bytes cross the "
                             f"link, expand in VMEM")
    else:
        mode, why = "host", (f"SSD is the ceiling ({ssd:.2f} vs h2d "
                             f"{h2d:.2f} GB/s): packed off disk, "
                             f"expanded on host")
    return PushdownDecision(
        mode=mode,
        wire_bytes=int(wire_pages if mode != "raw"
                       else 4 * meta.n_rows * len(meta.cols)),
        logical_bytes=int(logical),
        per_column=tuple(per_col), reason=why)
