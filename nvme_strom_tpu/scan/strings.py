"""Dictionary-encoded string columns over the numeric heap.

The heap format is 4-byte numeric by design (the columnar layout that
lets the XLA/Pallas kernels decode pages in registers).  Strings ride
it as **sorted-dictionary codes**: a string column stores uint32 ranks
into a per-column dictionary sidecar (``<table>.dict<col>``), and
because the dictionary is SORTED, code order IS lexicographic string
order — so every numeric machine the scan tier already has works on
strings unchanged:

* equality:  ``WHERE city = 'Berlin'``  -> ``code == rank('Berlin')``
  (absent string -> match-nothing, the where_eq unrepresentable rule)
* ranges:    ``WHERE city BETWEEN 'A' AND 'C'`` -> a code range via
  ``np.searchsorted`` bounds (absent endpoints bind to their rank
  position, preserving lexicographic semantics)
* ORDER BY a string column = ordering by its codes
* GROUP BY / index sidecars / joins on string keys: the codes are the
  keys; results decode back to strings at the edge

The dictionary is STATIC per table build (the reference's scan reads
immutable-during-scan tables the same way); rewriting the table with
new strings rewrites the sidecar.  Stamped against the table
(size + mtime) like index sidecars, so a stale dictionary fails loudly
instead of decoding garbage.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..api import StromError

__all__ = ["StringDict", "encode_strings", "dict_path_for",
           "save_dict", "load_dict"]

_MAGIC = "strom-strdict-v1"


def dict_path_for(table_path: str, col: int) -> str:
    return f"{table_path}.dict{int(col)}"


class StringDict:
    """A sorted string dictionary: ``code = rank`` (lexicographic)."""

    def __init__(self, values: Sequence[str]):
        vals = sorted(set(str(v) for v in values))
        if len(vals) >= (1 << 32):
            raise StromError(12, "string dictionary exceeds uint32 codes")
        self.values: List[str] = vals
        self._rank = {v: i for i, v in enumerate(vals)}

    def __len__(self) -> int:
        return len(self.values)

    def encode(self, strings) -> np.ndarray:
        """uint32 codes; an unknown string raises (build-time API)."""
        try:
            return np.fromiter((self._rank[str(s)] for s in strings),
                               np.uint32, count=len(strings))
        except KeyError as e:
            raise StromError(22, f"string {e.args[0]!r} not in the "
                                 f"dictionary") from None

    def code_of(self, s: str) -> Optional[int]:
        """Rank of *s*, or None when absent (query-time equality: the
        match-nothing rule, like an unrepresentable numeric literal)."""
        return self._rank.get(str(s))

    def range_codes(self, lo: Optional[str],
                    hi: Optional[str]) -> Tuple[Optional[int],
                                                Optional[int]]:
        """Inclusive code bounds equivalent to the STRING range
        ``lo <= s <= hi`` — absent endpoints bind via searchsorted so
        lexicographic semantics hold exactly (e.g. hi='C' excludes
        'Ca' but includes 'C' itself when present)."""
        clo = None
        if lo is not None:
            clo = int(np.searchsorted(np.asarray(self.values), str(lo),
                                      side="left"))
        chi = None
        if hi is not None:
            chi = int(np.searchsorted(np.asarray(self.values), str(hi),
                                      side="right")) - 1
        return clo, chi

    def decode(self, codes) -> np.ndarray:
        codes = np.asarray(codes, np.int64).reshape(-1)
        if len(codes) and (codes.min() < 0
                           or codes.max() >= len(self.values)):
            raise StromError(22, "code outside the dictionary (stale "
                                 "sidecar?)")
        # vectorized take: a SELECT face can decode millions of rows
        return np.array(self.values, dtype=object)[codes]


def encode_strings(strings) -> Tuple[np.ndarray, StringDict]:
    """Build-time helper: ``(uint32 codes, dict)`` for a string column."""
    d = StringDict(strings)
    return d.encode(strings), d


def _table_stamp(table_path: str) -> Tuple[int, int]:
    st = os.stat(table_path)
    return int(st.st_size), int(st.st_mtime_ns)


def save_dict(table_path: str, col: int, d: StringDict) -> str:
    """Write the sidecar, stamped against the CURRENT table file
    (crash-safe tmp+rename, the index-sidecar discipline)."""
    size, mtime = _table_stamp(table_path)
    path = dict_path_for(table_path, col)
    body = json.dumps({"magic": _MAGIC, "col": int(col),
                       "table_size": size, "table_mtime_ns": mtime,
                       "values": d.values})
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(body)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_dict(table_path: str, col: int, *,
              check_stale: bool = True) -> StringDict:
    """Load a column's dictionary; a table rewritten since the sidecar
    was saved fails with EIO (stale codes decode to WRONG strings —
    silent corruption, the one unforgivable failure)."""
    path = dict_path_for(table_path, col)
    try:
        with open(path) as f:
            meta = json.load(f)
    except FileNotFoundError:
        raise
    except (OSError, json.JSONDecodeError) as e:
        raise StromError(5, f"string dictionary {path}: {e}") from e
    if meta.get("magic") != _MAGIC or meta.get("col") != int(col):
        raise StromError(5, f"string dictionary {path}: wrong header")
    if check_stale:
        size, mtime = _table_stamp(table_path)
        if (meta.get("table_size"), meta.get("table_mtime_ns")) \
                != (size, mtime):
            raise StromError(5, f"string dictionary {path} is STALE "
                                f"(table rewritten); rebuild it")
    d = StringDict.__new__(StringDict)
    d.values = [str(v) for v in meta["values"]]
    d._rank = {v: i for i, v in enumerate(d.values)}
    return d
