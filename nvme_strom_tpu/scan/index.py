"""Sorted secondary index: the index-access method over heap tables.

The reference is a sequential-scan engine — its planner only ever chooses
between the direct path and the buffered path for FULL scans
(`pgsql/nvme_strom.c:448-633`).  This module adds the other access method
a database user expects: a sorted ``(key, position)`` sidecar built by one
scan, after which equality and range lookups touch ONLY the pages holding
matching rows (binary search on the sidecar -> :meth:`..scan.query.Query.
fetch`'s merge-planned page reads).

TPU-first shape: the sidecar is two dense arrays (sorted keys + their
global row positions), so every probe is ``searchsorted`` — the same
vectorized-binary-search discipline as the broadcast join (`ops/join.py`)
— rather than a pointer-chasing B-tree, which the VPU cannot batch.

Sidecar layout (``<table>.idx`` by convention)::

    [ magic u64 | json_len u64 | header json, padded to 4096 ]
    [ sorted keys array ][ positions array (int64) ]

Header json: ``{version, col, dtype, count, table_size, table_mtime_ns}``.
``table_size``/``table_mtime_ns`` let :func:`open_index` detect a stale
index after the table changed (the syscache-invalidation analog,
`pgsql/nvme_strom.c:217-348`).

**Composite keys**: ``col`` may be a pair ``(c0, c1)`` of integer (int32 /
uint32) columns.  The sidecar then stores one ``uint64`` key per row —
the two values packed **lexicographically order-preservingly** (each
mapped to uint32 by an order-preserving bias, then ``c0`` in the high
word) — so equality on the pair is a single searchsorted probe, exactly
like the single-column case.  Float columns are refused (IEEE bits do
not pack order-preservingly without sign-flip tricks; build one index
per float column instead).  The header gains ``key_dtypes`` recording
the pair's column dtypes.
"""

from __future__ import annotations

import errno as _errno
import json
import os
import struct
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from ..api import StromError

__all__ = ["build_index", "open_index", "probe_index", "SortedIndex",
           "pack_pair", "index_path_for"]

_MAGIC = 0x53545258_49445831  # "STRX" "IDX1"
_VERSION = 1
_ALIGN = 4096


def _table_stamp(path: str) -> Tuple[int, int]:
    st = os.stat(path)
    return int(st.st_size), int(st.st_mtime_ns)


def exact_int(v, dt: np.dtype):
    """*v* as an exact ``dt`` scalar, or None when no such value exists
    (NaN/inf, fractional, or out of the dtype's range) — THE
    representability check shared by every composite-key probe path, so
    the index/seqscan transparency semantics cannot drift between
    copies."""
    f = float(v)
    info = np.iinfo(dt)
    if not np.isfinite(f) or f != int(f) \
            or not info.min <= int(v) <= info.max:
        return None
    return dt.type(int(v))


def _to_u32_order(a: np.ndarray, dt: np.dtype) -> np.ndarray:
    """Order-preserving map of a 4-byte integer column onto uint64 in
    [0, 2^32): int32 biases by +2^31, uint32 passes through."""
    if dt == np.dtype(np.int32):
        return (a.astype(np.int64) + (1 << 31)).astype(np.uint64)
    return a.astype(np.uint64)


def pack_pair(a0, a1, dt0: np.dtype, dt1: np.dtype) -> np.ndarray:
    """Lexicographic uint64 packing of an integer column pair: compares
    like ``(a0, a1)`` tuple order.  Arrays or scalars."""
    u0 = _to_u32_order(np.asarray(a0), np.dtype(dt0))
    u1 = _to_u32_order(np.asarray(a1), np.dtype(dt1))
    return (u0 << np.uint64(32)) | u1


def unpack_second(keys: np.ndarray, dt1: np.dtype) -> np.ndarray:
    """Second-column values back out of packed composite keys (inverse
    of the low word of :func:`pack_pair`)."""
    low = keys & np.uint64(0xFFFFFFFF)
    if np.dtype(dt1) == np.dtype(np.int32):
        return (low.astype(np.int64) - (1 << 31)).astype(np.int32)
    return low.astype(np.uint32)


def index_path_for(table_path: str, col) -> str:
    """Default sidecar path: ``.idx{c}`` single, ``.idx{c0}_{c1}``
    composite."""
    if isinstance(col, (tuple, list)):
        return f"{table_path}.idx{int(col[0])}_{int(col[1])}"
    return f"{table_path}.idx{int(col)}"


def build_index(table_path: str, schema, col, *,
                index_path: Optional[str] = None,
                session=None, device=None, mesh=None) -> str:
    """One scan of the table -> a sorted (key, position) sidecar.

    Returns the index path (``<table>.idx<col>`` by default).  NaN float
    keys are excluded (they compare unordered; SQL indexes skip NULLs the
    same way).  With *mesh*, the sort runs as the distributed sample
    sort over the device mesh — index builds over large tables scale
    the same way ORDER BY does.

    *col* may be a pair ``(c0, c1)`` of integer columns: the sidecar then
    holds lexicographically packed uint64 keys (module docstring), built
    from one projection scan + a stable sort of the packed keys.  With
    *mesh* the packed uint64 keys ride the distributed sample sort as two
    stable LSD-radix passes (:func:`..parallel.sort.distributed_sort_u64`)
    — bit-identical sidecar to the host build, mesh-scaled like
    single-column builds (VERDICT r3 #4)."""
    from .query import Query

    for c in (col if isinstance(col, (tuple, list)) else [col]):
        if 0 <= int(c) < schema.n_cols:
            if schema.col_nullable(int(c)):
                raise StromError(_errno.EINVAL,
                                 f"build_index: c{c} is nullable — "
                                 f"sidecars hold no NULL entries and "
                                 f"the scan paths could disagree")
            if schema.col_dtype(int(c)).itemsize != 4:
                raise StromError(_errno.EINVAL,
                                 f"build_index: c{c} is 8-byte — "
                                 f"sidecar keys are 4-byte words")
    # stamp BEFORE the scan: a table modified mid-build then mismatches
    # the stamp and open_index fails stale (stamping after would bless an
    # index holding pre-modification data)
    size, mtime = _table_stamp(table_path)
    key_dtypes = None
    if isinstance(col, (tuple, list)):
        if len(col) != 2:
            raise StromError(_errno.EINVAL,
                            "composite index keys are column PAIRS")
        c0, c1 = int(col[0]), int(col[1])
        dt0, dt1 = schema.col_dtype(c0), schema.col_dtype(c1)
        for c, dt in ((c0, dt0), (c1, dt1)):
            if dt.kind not in "iu":
                raise StromError(
                    _errno.EINVAL,
                    f"composite index col{c} is {dt}: only integer "
                    f"columns pack order-preservingly (build a single-"
                    f"column index for float keys)")
        out = Query(table_path, schema).select([c0, c1]).run(
            session=session, device=device)
        packed = pack_pair(out[f"col{c0}"], out[f"col{c1}"], dt0, dt1)
        pos_in = np.asarray(out["positions"], np.int64)
        if mesh is not None:
            # packed keys through the distributed sample sort (two
            # stable uint32 radix passes) — same scaling as the
            # single-column build, bit-identical result
            from ..parallel.sort import distributed_sort_u64
            keys, poss = distributed_sort_u64(mesh, packed, pos_in)
        else:
            # stable: duplicates keep build (physical) order, same
            # contract as the single-column sort path
            order = np.argsort(packed, kind="stable")
            keys = packed[order]
            poss = pos_in[order]
        col_field = [c0, c1]
        key_dtypes = [dt0.str, dt1.str]
    else:
        q = Query(table_path, schema).order_by(col)
        out = q.run(session=session, device=device, mesh=mesh)
        keys = np.asarray(out["values"])
        poss = np.asarray(out["positions"], np.int64)
        if keys.dtype.kind == "f":
            finite = ~np.isnan(keys)
            keys, poss = keys[finite], poss[finite]
        col_field = int(col)
    header = json.dumps({
        "version": _VERSION, "col": col_field, "dtype": keys.dtype.str,
        "count": int(len(keys)),
        "table_size": size,
        "table_mtime_ns": mtime,
        **({"key_dtypes": key_dtypes} if key_dtypes else {}),
    }).encode()
    hlen = (16 + len(header) + _ALIGN - 1) // _ALIGN * _ALIGN
    path = index_path or index_path_for(table_path, col)
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(struct.pack("<QQ", _MAGIC, len(header)))
            f.write(header)
            f.write(b"\0" * (hlen - 16 - len(header)))
            f.write(np.ascontiguousarray(keys).tobytes())
            f.write(np.ascontiguousarray(poss).tobytes())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


@dataclass
class SortedIndex:
    """An opened sidecar: dense sorted keys + row positions."""

    path: str
    col: object             # int, or (c0, c1) tuple for composite keys
    keys: np.ndarray        # sorted, ascending
    positions: np.ndarray   # int64 global row positions, aligned to keys
    key_dtypes: Optional[Tuple[np.dtype, np.dtype]] = None  # composite only

    @property
    def composite(self) -> bool:
        return self.key_dtypes is not None

    def _pack_probes(self, values) -> np.ndarray:
        """(v0, v1) probe pairs -> packed uint64 keys; pairs with a value
        the column dtype cannot represent exactly match nothing."""
        dt0, dt1 = self.key_dtypes
        out = []
        for pair in values:
            n0 = exact_int(pair[0], dt0)
            n1 = exact_int(pair[1], dt1)
            if n0 is not None and n1 is not None:
                out.append(int(pack_pair(n0, n1, dt0, dt1)))
        return np.asarray(out, np.uint64)

    def _prefix_bracket(self, lo0, hi0) -> Tuple[int, int]:
        """[a, b) sidecar bracket of first-key-column range [lo0, hi0]
        (either bound open; a bound c0 cannot represent exactly empties
        the bracket on that side).  THE one implementation behind every
        leftmost-prefix read."""
        dt0, dt1 = self.key_dtypes
        i1 = np.iinfo(dt1)
        a = 0
        b = len(self.keys)
        if lo0 is not None:
            n0 = exact_int(lo0, dt0)
            if n0 is None:
                return 0, 0
            lo = pack_pair(n0, dt1.type(i1.min), dt0, dt1)
            a = int(np.searchsorted(self.keys, lo, side="left"))
        if hi0 is not None:
            n0 = exact_int(hi0, dt0)
            if n0 is None:
                return 0, 0
            hi = pack_pair(n0, dt1.type(i1.max), dt0, dt1)
            b = int(np.searchsorted(self.keys, hi, side="right"))
        return a, max(a, b)

    def prefix_span(self, v0) -> Tuple[int, int]:
        """Composite index only: the [a, b) sidecar span whose first key
        column equals *v0* (empty when unrepresentable) — within it keys
        are sorted by the SECOND column, which is what makes
        ``WHERE c0 = v ORDER BY c1`` a single contiguous read."""
        return self._prefix_bracket(v0, v0)

    def prefix_range(self, lo0=None, hi0=None) -> np.ndarray:
        """Composite index only: positions of ALL rows whose FIRST key
        column lies in ``[lo0, hi0]`` (either bound open) — the SQL
        leftmost-prefix rule: a filter on c0 alone scans the contiguous
        packed range ``[pack(lo0, min1), pack(hi0, max1)]``.  Equality is
        ``prefix_range(v, v)``."""
        a, b = self._prefix_bracket(lo0, hi0)
        return self.positions[a:b]

    def lookup(self, values) -> np.ndarray:
        """Row positions of rows whose key equals any of *values*
        (duplicates in the table all match; order: ascending key, then
        index order within equal keys).  A probe the key dtype cannot
        represent exactly (e.g. 7.5 against int32 keys) matches nothing
        — SQL equality semantics, not silent truncation.

        Composite index: *values* is a sequence of ``(v0, v1)`` pairs."""
        if self.composite:
            vals = self._pack_probes(values)
        else:
            raw = np.asarray(values).reshape(-1)
            vals = raw.astype(self.keys.dtype)
            exact = vals.astype(raw.dtype) == raw \
                if raw.dtype != vals.dtype else np.ones(len(raw), bool)
            vals = vals[exact]
        parts = []
        for v in vals:
            lo = int(np.searchsorted(self.keys, v, side="left"))
            hi = int(np.searchsorted(self.keys, v, side="right"))
            if hi > lo:
                parts.append(self.positions[lo:hi])
        return np.concatenate(parts) if parts else np.zeros(0, np.int64)

    def range(self, lo=None, hi=None, *,
              inclusive: str = "both") -> np.ndarray:
        """Row positions with key in the given range (``inclusive`` one
        of both|left|right|neither), in ascending key order."""
        if inclusive not in ("both", "left", "right", "neither"):
            raise StromError(_errno.EINVAL,
                            f"inclusive={inclusive!r} invalid")
        i0 = 0 if lo is None else int(np.searchsorted(
            self.keys, lo, side="left" if inclusive in ("both", "left")
            else "right"))
        i1 = len(self.keys) if hi is None else int(np.searchsorted(
            self.keys, hi, side="right" if inclusive in ("both", "right")
            else "left"))
        return self.positions[i0:max(i0, i1)]

    def fetch(self, query, values=None, *, lo=None, hi=None,
              cols=None, session=None, device=None,
              inclusive: str = "both") -> dict:
        """Index scan: resolve positions (equality *values* or a
        [lo, hi] range) then read ONLY their pages via ``query.fetch``.
        Adds ``"positions"`` to the fetch result."""
        pos = self.lookup(values) if values is not None \
            else self.range(lo, hi, inclusive=inclusive)
        out = query.fetch(pos, cols=cols, session=session, device=device)
        out["positions"] = pos
        return out


def _read_header(f, path: str) -> Tuple[dict, int]:
    """(header json, aligned header length); raises on any malformation."""
    magic, jlen = struct.unpack("<QQ", f.read(16))
    if magic != _MAGIC:
        raise StromError(_errno.EINVAL, f"{path}: not a strom index")
    meta = json.loads(f.read(jlen))
    if meta.get("version") != _VERSION:
        raise StromError(_errno.EINVAL,
                        f"{path}: index version {meta.get('version')}")
    return meta, (16 + jlen + _ALIGN - 1) // _ALIGN * _ALIGN


def probe_index(index_path: str, table_path: str, *,
                expect_col=None, allow_prefix: bool = True) -> bool:
    """Header-only freshness check for the PLANNER: one 4KB-class read,
    no key/position load.  Returns False for missing, stale, corrupt, or
    unreadable sidecars — the planner never fails a query over an
    optional accelerator.

    *expect_col* additionally validates the header's column field (the
    filename is NOT authoritative): an int accepts a single-column
    sidecar on that column or — with ``allow_prefix`` (filters; NOT
    terminals that read the keys as values) — a composite whose LEADING
    column matches; a tuple demands that exact pair.  So EXPLAIN can
    never claim an index path run() would then refuse."""
    try:
        with open(index_path, "rb") as f:
            meta, _ = _read_header(f, index_path)
        size, mtime = _table_stamp(table_path)
        if size != meta["table_size"] or mtime != meta["table_mtime_ns"]:
            return False
        if expect_col is not None:
            mcol = meta["col"]
            if isinstance(expect_col, (tuple, list)):
                return (isinstance(mcol, list)
                        and tuple(mcol) == tuple(expect_col))
            if isinstance(mcol, list):
                return allow_prefix and mcol[0] == int(expect_col)
            return mcol == int(expect_col)
        return True
    except Exception:
        return False


def open_index(index_path: str, *, table_path: Optional[str] = None,
               check_stale: bool = True) -> SortedIndex:
    """mmap-free open of a sidecar (one buffered read; indexes are small
    next to their tables).  With *table_path* and ``check_stale``, a
    size/mtime mismatch against the stamped table raises ESTALE — rebuild
    with :func:`build_index`."""
    with open(index_path, "rb") as f:
        meta, hlen = _read_header(f, index_path)
        if check_stale and table_path is not None:
            size, mtime = _table_stamp(table_path)
            if (size != meta["table_size"]
                    or mtime != meta["table_mtime_ns"]):
                raise StromError(_errno.ESTALE,
                                f"{index_path} is stale: table changed "
                                f"since the index was built")
        f.seek(hlen)
        n = meta["count"]
        kdt = np.dtype(meta["dtype"])
        keys = np.frombuffer(f.read(n * kdt.itemsize), kdt)
        poss = np.frombuffer(f.read(n * 8), np.int64)
    if len(keys) != n or len(poss) != n:
        raise StromError(_errno.EIO, f"{index_path}: truncated index")
    col = meta["col"]
    kdts = meta.get("key_dtypes")
    return SortedIndex(path=index_path,
                       col=tuple(col) if isinstance(col, list) else col,
                       keys=keys, positions=poss,
                       key_dtypes=(np.dtype(kdts[0]), np.dtype(kdts[1]))
                       if kdts else None)
