"""NUMA-aware DMA staging buffer pool with leak recovery.

Capability analog of the pgsql extension's shared DMA buffer pool
(`pgsql/nvme_strom.c:56-111,1123-1526`): per-NUMA-node chunk freelists with
round-robin fallback, blocking allocation, and **leak recovery** through
resource-owner callbacks — chunks still held when a scan aborts are returned
automatically, and commit-time leaks are warned about
(``NVMEStromCleanupDMABuffer``, `:1302-1351`).

Rebuilt in-process: the pool carves ``buffer_size`` (GUC analog) into
``chunk_size`` chunks of pinned :class:`~nvme_strom_tpu.engine.DmaBuffer`
memory per allowed NUMA node; a :class:`ResourceOwner` context manager
stands in for PostgreSQL's ResourceOwner lifecycle.
"""

from __future__ import annotations

import errno as _errno
import threading
import time
import warnings
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..api import StromError
from ..config import config
from ..engine import DmaBuffer
from ..numa import allowed_nodes

__all__ = ["DmaChunk", "DmaBufferPool", "ResourceOwner"]


@dataclass
class DmaChunk:
    pool: "DmaBufferPool"
    node: int
    index: int
    view: memoryview
    owner: Optional["ResourceOwner"] = None
    allocated: bool = False

    def release(self) -> None:
        self.pool.free(self)


class ResourceOwner:
    """Scoped owner of pool chunks (PG ResourceOwner analog).

    On normal exit, still-held chunks are a *leak*: they are returned with a
    warning (the reference warns at commit, `pgsql/nvme_strom.c:1330-1340`).
    On exception exit they are returned silently (abort recovery path).
    """

    def __init__(self, name: str = "scan"):
        self.name = name
        self._held: Set[int] = set()
        self._chunks: Dict[int, DmaChunk] = {}
        self._lock = threading.Lock()

    def _attach(self, chunk: DmaChunk) -> None:
        with self._lock:
            key = id(chunk)
            self._held.add(key)
            self._chunks[key] = chunk
            chunk.owner = self

    def _detach(self, chunk: DmaChunk) -> None:
        with self._lock:
            self._held.discard(id(chunk))
            self._chunks.pop(id(chunk), None)
            chunk.owner = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        with self._lock:
            leaked = list(self._chunks.values())
            self._held.clear()
            self._chunks.clear()
        if leaked and exc_type is None:
            warnings.warn(f"ResourceOwner {self.name!r}: {len(leaked)} DMA "
                          f"chunk(s) leaked at clean exit; returning to pool",
                          ResourceWarning, stacklevel=2)
        for c in leaked:
            c.owner = None
            c.pool.free(c)


class DmaBufferPool:
    """Per-node freelists of fixed-size pinned chunks."""

    def __init__(self, *, chunk_size: Optional[int] = None,
                 total_size: Optional[int] = None,
                 numa_mask: Optional[int] = None):
        self.chunk_size = chunk_size or config.get("chunk_size")
        total = total_size or config.get("buffer_size")
        if total % self.chunk_size:
            raise StromError(_errno.EINVAL,
                            "pool size must be a multiple of chunk_size")
        mask = numa_mask if numa_mask is not None else config.get("numa_node_mask")
        self.nodes = allowed_nodes(mask)
        per_node = max(total // self.chunk_size // len(self.nodes), 1)
        self._lock = threading.Condition()
        self._free: Dict[int, List[DmaChunk]] = {}
        self._buffers: List[DmaBuffer] = []
        self._outstanding = 0
        self.n_chunks = 0
        for node in self.nodes:
            # one backing DmaBuffer per node (set_mempolicy-bound in the
            # reference, :1454-1526; best-effort here — the buffer records
            # its intended node for observability)
            buf = DmaBuffer(per_node * self.chunk_size, numa_node=node)
            self._buffers.append(buf)
            view = buf.view()
            self._free[node] = [
                DmaChunk(self, node, i,
                         view[i * self.chunk_size:(i + 1) * self.chunk_size])
                for i in range(per_node)]
            self.n_chunks += per_node
        self._closed = False

    def backing_buffer(self, node: int) -> DmaBuffer:
        """The node's backing :class:`DmaBuffer` — scanners pass it as the
        ``backing`` of per-chunk buffer maps so the session can register
        the whole pool region as one io_uring fixed buffer."""
        return self._buffers[self.nodes.index(node)]

    def alloc(self, *, preferred_node: int = -1, blocking: bool = True,
              timeout: Optional[float] = None,
              owner: Optional[ResourceOwner] = None) -> DmaChunk:
        """Allocate one chunk: local node first, then round-robin fallback
        (reference NVMEStromAllocDMABuffer, `pgsql/nvme_strom.c:1186-1260`)."""
        order = list(self.nodes)
        if preferred_node in self._free:
            order.remove(preferred_node)
            order.insert(0, preferred_node)
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                if self._closed:
                    raise StromError(_errno.EBADF, "pool closed")
                for node in order:
                    if self._free[node]:
                        chunk = self._free[node].pop()
                        chunk.allocated = True
                        self._outstanding += 1
                        if owner is not None:
                            owner._attach(chunk)
                        return chunk
                if not blocking:
                    raise StromError(_errno.ENOMEM, "pool exhausted")
                remain = None if deadline is None else deadline - time.monotonic()
                if remain is not None and remain <= 0:
                    raise StromError(_errno.ETIMEDOUT, "pool alloc timeout")
                if not self._lock.wait(remain):
                    raise StromError(_errno.ETIMEDOUT, "pool alloc timeout")

    def free(self, chunk: DmaChunk) -> None:
        """Return a chunk to the freelist.  Idempotent: abort paths can race
        the owner's cleanup with the consumer's (e.g. a ResourceOwner exit
        and a generator finally both releasing the same chunk) — the second
        release is a no-op rather than a freelist double-insert."""
        if chunk.owner is not None:
            chunk.owner._detach(chunk)
        with self._lock:
            if not chunk.allocated:
                return
            chunk.allocated = False
            self._free[chunk.node].append(chunk)
            self._outstanding -= 1
            self._lock.notify()

    @property
    def outstanding(self) -> int:
        with self._lock:
            return self._outstanding

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            if self._outstanding:
                warnings.warn(f"DmaBufferPool closed with {self._outstanding} "
                              f"outstanding chunk(s)", ResourceWarning)
            self._closed = True
            self._lock.notify_all()
        for b in self._buffers:
            b.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
