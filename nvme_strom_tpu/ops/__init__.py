from .filter_xla import decode_pages, scan_filter_step

__all__ = ["decode_pages", "scan_filter_step"]
