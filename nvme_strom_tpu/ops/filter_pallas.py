"""Pallas TPU kernels for the scan-filter hot path.

The XLA versions (:mod:`.filter_xla`) materialize per-column tensors and let
the compiler fuse the reduction.  These Pallas kernels do the whole
page-batch pass explicitly — each grid step streams one block of 8KB pages
HBM→VMEM (the pallas grid pipeline double-buffers the copies), decodes the
columnar page layout in registers, and folds the masked aggregate into SMEM
accumulators — so a batch is consumed in a single pass with no intermediate
HBM traffic.  This is the TPU-native replacement for the reference's
per-tuple CPU walk (`pgsql/nvme_strom.c:941-979`).

All control flow is static: page validity and MVCC visibility are masks,
never branches (the reference arbitrates visibility per tuple at
`pgsql/nvme_strom.c:767-811`; here it is one vectorized compare).

On non-TPU backends the kernels run in interpreter mode so CI exercises the
same code path hardware-free.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..scan.heap import HEADER_WORDS, PAGE_SIZE, HeapSchema
from .filter_xla import DEFAULT_SCHEMA

__all__ = ["scan_filter_step_pallas", "make_filter_fn_pallas"]

_WORDS = PAGE_SIZE // 4
_BLOCK_PAGES = 8          # pages per grid step: (8, 2048) int32 = 64KB VMEM


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _decode_block(w, schema: HeapSchema):
    """(bp, 2048) int32 page words -> ([(bp, T) typed col ...], valid mask).

    Typed columns are a same-width bitcast of their word range — the page
    layout is dtype-independent (scan/heap.py HeapSchema docstring)."""
    bp = w.shape[0]
    t = schema.tuples_per_page
    n_tup = w[:, 2:3]                                   # header word 2
    iota = jax.lax.broadcasted_iota(jnp.int32, (bp, t), 1)
    valid = iota < n_tup
    cols = []
    for c in range(schema.n_cols):
        s, e = schema.col_word_range(c)
        col = w[:, s:e]
        dt = schema.col_dtype(c)
        if dt != jnp.int32:
            col = jax.lax.bitcast_convert_type(col, jnp.dtype(dt))
        cols.append(col)
    if schema.visibility:
        s, e = schema.col_word_range(schema.n_cols)
        valid = valid & (w[:, s:e] != 0)
    return cols, valid


def _sum_slots(schema: HeapSchema):
    """Per-column accumulator routing: integer-kind columns share the int32
    SMEM bank (uint32 wraps bit-identically mod 2^32 — restored by a final
    bitcast), float32 columns the f32 bank.  Returns (kinds, slots) where
    ``kinds[c]`` is 'i' or 'f' and ``slots[c]`` the index in that bank."""
    kinds, slots = [], []
    ni = nf = 0
    for c in range(schema.n_cols):
        if schema.col_dtype(c).kind == "f":
            kinds.append("f")
            slots.append(nf)
            nf += 1
        else:
            kinds.append("i")
            slots.append(ni)
            ni += 1
    return kinds, slots, ni, nf


def _make_kernel(schema: HeapSchema, predicate):
    n_cols = schema.n_cols
    kinds, slots, ni, nf = _sum_slots(schema)

    def kernel(thresh_ref, w_ref, count_ref, isums_ref, fsums_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            count_ref[0, 0] = 0
            for s in range(max(ni, 1)):   # SMEM takes scalar stores only
                isums_ref[0, s] = 0
            for s in range(max(nf, 1)):
                fsums_ref[0, s] = 0.0

        w = w_ref[...]
        cols, valid = _decode_block(w, schema)
        sel = valid & predicate(cols, thresh_ref[0])
        count_ref[0, 0] += jnp.sum(sel.astype(jnp.int32))
        for c in range(n_cols):
            col = cols[c]
            if kinds[c] == "f":
                fsums_ref[0, slots[c]] += jnp.sum(
                    jnp.where(sel, col, jnp.float32(0)))
            else:
                if col.dtype != jnp.int32:  # uint32: accumulate the bits
                    col = jax.lax.bitcast_convert_type(col, jnp.int32)
                isums_ref[0, slots[c]] += jnp.sum(jnp.where(sel, col, 0))

    return kernel


def _pad_pages(pages_u8: jax.Array) -> jax.Array:
    """Pad the batch to a _BLOCK_PAGES multiple; zero pages carry
    n_tuples == 0, so padding contributes nothing to any aggregate."""
    b = pages_u8.shape[0]
    rem = b % _BLOCK_PAGES
    if rem:
        pages_u8 = jnp.pad(pages_u8, ((0, _BLOCK_PAGES - rem), (0, 0)))
    return pages_u8


def _run_filter(pages_u8, threshold, schema: HeapSchema, predicate,
                interpret: Optional[bool]):
    """Returns ``(count, [per-column sum ...])`` with each sum carrying its
    column's dtype (uint32 sums are the int32 accumulator bit-restored —
    identical to uint32 arithmetic mod 2^32)."""
    pages_u8 = _pad_pages(pages_u8)
    b = pages_u8.shape[0]
    words = jax.lax.bitcast_convert_type(
        pages_u8.reshape(b, _WORDS, 4), jnp.int32).reshape(b, _WORDS)
    thresh = jnp.asarray(threshold).reshape(1)
    kinds, slots, ni, nf = _sum_slots(schema)
    count, isums, fsums = pl.pallas_call(
        _make_kernel(schema, predicate),
        grid=(b // _BLOCK_PAGES,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((_BLOCK_PAGES, _WORDS), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, max(ni, 1)), jnp.int32),
            jax.ShapeDtypeStruct((1, max(nf, 1)), jnp.float32),
        ],
        interpret=_should_interpret() if interpret is None else interpret,
    )(thresh, words)
    sums = []
    for c in range(schema.n_cols):
        if kinds[c] == "f":
            sums.append(fsums[0, slots[c]])
        else:
            s = isums[0, slots[c]]
            dt = schema.col_dtype(c)
            if dt != np.dtype(np.int32):
                s = jax.lax.bitcast_convert_type(s, jnp.dtype(dt))
            sums.append(s)
    return count[0, 0], sums


@partial(jax.jit, static_argnames=("interpret",))
def scan_filter_step_pallas(pages_u8: jax.Array, threshold: jax.Array,
                            interpret: Optional[bool] = None):
    """Pallas twin of :func:`..ops.filter_xla.scan_filter_step`: predicate
    ``col0 > threshold`` over a page batch; returns the selected count and
    the sum of col1 over selected rows (identical contract, so the two are
    differentially testable)."""
    count, sums = _run_filter(
        pages_u8, threshold, DEFAULT_SCHEMA,
        lambda cols, th: cols[0] > th, interpret)
    return {"count": count, "sum": sums[1]}


def make_filter_fn_pallas(schema: HeapSchema, predicate, *,
                          interpret: Optional[bool] = None):
    """Pallas twin of :func:`..ops.filter_xla.make_filter_fn`, including
    typed (float32/uint32) schemas — column decode is an in-register
    bitcast, float sums ride a separate f32 accumulator bank.

    ``predicate(cols, threshold) -> bool (B, T)`` must be built from jnp ops
    (it is traced inside the kernel).  Returns a jitted
    ``run(pages_u8, threshold) -> {"count", "sums"}``."""

    @jax.jit
    def run(pages_u8, threshold=jnp.int32(0)):
        count, sums = _run_filter(pages_u8, threshold, schema, predicate,
                                  interpret)
        return {"count": count, "sums": sums}

    return run
