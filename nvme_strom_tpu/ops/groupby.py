"""Grouped aggregation over HBM-resident heap pages.

Extends the scan-compute tier (the pgsql per-tuple walk redesigned as
tensor ops, `pgsql/nvme_strom.c:941-979`) from flat filter/sum to
GROUP BY: per-group count/sum/min/max in one pass over a page batch.

TPU-first shape: the group reduction is a **one-hot contraction** —
``(B·T, G) one-hot  x  (B·T, V) values -> (G, V)`` — which XLA lowers to
an MXU matmul for the sum path (integer-exact via
``preferred_element_type``), instead of the scatter-add a CUDA port
would reach for (scatters serialize on TPU; matmuls do not).  Min/max
ride masked segment reductions on the VPU.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..scan.heap import HeapSchema
from .filter_xla import DEFAULT_SCHEMA, decode_pages

__all__ = ["make_groupby_fn", "scan_groupby_step", "combine_groupby",
           "groupby_kernel_auto"]

_measured_ratio_cache = None


def _measured_groupby_ratio() -> float:
    """Measured on-chip pallas/XLA GROUP BY ratio from BENCH_MATRIX
    (``pallas_vs_xla_groupby``), falling back to the last recorded value
    when the matrix is absent.  Cached per process — the file only
    changes when ``make bench-matrix`` reruns."""
    global _measured_ratio_cache
    if _measured_ratio_cache is None:
        import json
        import os
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
            "BENCH_MATRIX.json")
        ratio = 0.851   # r4/r5 measurement; see groupby_kernel_routing
        try:
            with open(path) as f:
                r = json.load(f).get("pallas_vs_xla_groupby")
            if r:
                ratio = float(r)
        except (OSError, ValueError, TypeError):
            pass
        _measured_ratio_cache = ratio
    return _measured_ratio_cache


def groupby_kernel_auto(agg_kind: str):
    """Measured kernel routing for on-chip GROUP BY: ``(kernel, why)``.

    The crossover is the BENCH_MATRIX same-batch ratio itself: the
    one-hot pallas kernel pays an SMEM accumulator round-trip per group
    that the XLA MXU contraction amortizes, and for FLOAT accumulation
    that overhead is where the measured ratio lands below 1.0
    (``pallas_vs_xla_groupby`` = 0.851 across r4/r5 sessions) — so any
    measured ratio < 1.0 routes float aggregation to XLA.  Integer
    accumulation keeps the pallas win (``pallas_vs_xla`` = 4.263 on the
    same host) and stays on the hand kernel."""
    if agg_kind != "f":
        return "pallas", "int accumulators keep the measured pallas win"
    ratio = _measured_groupby_ratio()
    if ratio < 1.0:
        return "xla", (f"float aggregation routes to XLA (measured "
                       f"pallas_vs_xla_groupby = {ratio:g} < 1.0 — the "
                       f"pallas GROUP BY earns its keep on int "
                       f"accumulators only)")
    return "pallas", (f"measured pallas_vs_xla_groupby = {ratio:g} "
                      f">= 1.0: the hand kernel wins this host")


def combine_groupby(acc: dict, out: dict) -> dict:
    """Batch-fold combiner for grouped results (pass as
    ``TableScanner.scan_filter(..., combine=combine_groupby)`` or to
    ``distributed_scan_filter``): counts/sums/sumsqs add, mins/maxs meet."""
    folded = {"count": acc["count"] + out["count"],
              "sums": acc["sums"] + out["sums"],
              "sumsqs": acc["sumsqs"] + out["sumsqs"],
              "mins": jnp.minimum(acc["mins"], out["mins"]),
              "maxs": jnp.maximum(acc["maxs"], out["maxs"])}
    if "nncounts" in acc and "nncounts" in out:
        # per-column non-NULL counts (the XLA kernel emits them for
        # nullable schemas; the pallas twin never sees one)
        folded["nncounts"] = acc["nncounts"] + out["nncounts"]
    return folded

def acc_dtypes(agg_dt: np.dtype):
    """THE accumulation convention, in one place — returns
    ``(sum accumulator dtype, sumsq dtype, lo, hi)`` where ``lo`` is the
    dtype's worst/lowest value (initializes MAX accumulators) and ``hi``
    its best/highest (initializes MIN accumulators).  Float sums
    stay at the column dtype; int sums widen to 8 bytes only under x64
    (the MXU contraction's preferred_element_type); sumsqs are floating
    (f64 under x64).  Both the page kernels and the index-path host
    emulations (`scan/query._run_*_indexed`) derive from this, so the
    access paths cannot drift."""
    x64 = jax.config.jax_enable_x64
    is_f = agg_dt.kind == "f"
    acc = agg_dt if is_f or not x64 else np.dtype(agg_dt.kind + "8")
    sq = np.dtype(np.float64 if x64 else np.float32)
    if is_f:
        lo, hi = agg_dt.type(-np.inf), agg_dt.type(np.inf)
    else:
        info = np.iinfo(agg_dt)
        lo, hi = agg_dt.type(info.min), agg_dt.type(info.max)
    return acc, sq, lo, hi


def _check_agg_cols(schema: HeapSchema, agg_cols):
    """Validate + resolve aggregation columns: one shared dtype — int32,
    uint32, or float32.  Returns (indices, dtype)."""
    cols_idx = list(agg_cols) if agg_cols is not None else \
        list(range(schema.n_cols))
    if not cols_idx:
        raise ValueError("groupby needs at least one aggregation column")
    for ci in cols_idx:
        if not 0 <= ci < schema.n_cols:
            raise ValueError(f"aggregation column {ci} out of range — "
                             f"this schema has columns 0..{schema.n_cols - 1}")
    dts = {schema.col_dtype(ci) for ci in cols_idx}
    if len(dts) > 1:
        raise ValueError(f"groupby aggregation columns must share one "
                         f"dtype, got {sorted(str(d) for d in dts)}; "
                         f"split into one groupby per dtype")
    dt = dts.pop()
    if dt in (np.dtype(np.int64), np.dtype(np.float64)):
        # 8-byte aggregation rides the XLA path under x64 (round 5)
        if not jax.config.jax_enable_x64:
            raise ValueError(f"aggregating {dt} columns requires "
                             f"jax_enable_x64 (32-bit accumulation "
                             f"would silently truncate)")
    elif dt not in (np.dtype(np.int32), np.dtype(np.uint32),
                    np.dtype(np.float32)):
        raise ValueError(f"groupby aggregates int32, uint32, float32, "
                         f"int64, or float64 columns (got {dt})")
    return cols_idx, dt


def make_groupby_fn(schema: HeapSchema, key_fn: Callable, n_groups: int, *,
                    agg_cols: Optional[Sequence[int]] = None,
                    predicate: Optional[Callable] = None):
    """Build a jitted ``run(pages_u8, *params) -> dict`` grouped aggregate.

    ``key_fn(cols, *params) -> (B, T) int32`` group ids in ``[0, n_groups)``
    (out-of-range ids fall into no group); ``predicate(cols, *params)`` an
    optional row filter.  ``agg_cols`` — column indices to aggregate
    (default: all).  Returns per group: ``count (G,)``, and ``sums / sumsqs
    / mins / maxs`` of shape ``(len(agg_cols), G)``; empty groups report 0
    count, 0 sum, and the dtype's worst-value sentinels for min/max.
    ``sumsqs`` (for VAR/STDDEV) accumulates in floating point on every
    path — int32 squares overflow long before sums do, and variance is a
    statistical quantity, so float semantics are the honest contract.

    Aggregation columns must share one dtype — int32, uint32, or float32
    (uniform ``(V, G)`` result arrays; the reference's per-tuple walk had
    the same one-type-at-a-time shape).  Mixed sets raise.
    """
    cols_idx, agg_dt = _check_agg_cols(schema, agg_cols)
    G = int(n_groups)
    acc_np, sq_np, lo, hi = acc_dtypes(agg_dt)

    @jax.jit
    def run(pages_u8, *params):
        cols, valid = decode_pages(pages_u8, schema)
        keys = key_fn(cols, *params)
        sel = valid & (keys >= 0) & (keys < G)
        if predicate is not None:
            sel = sel & predicate(cols, *params)
        keys = jnp.where(sel, keys, G)  # overflow bucket, sliced off below
        flat_keys = keys.reshape(-1)
        onehot = jax.nn.one_hot(flat_keys, G + 1, dtype=jnp.int32)[:, :G]
        # NULL-aware aggregation (round 5): a nullable column's NULL
        # rows contribute nothing to its sums (stored zeros already do
        # that for + paths) and are excluded from its min/max/sumsq
        # masks; group COUNT stays the row count (SQL COUNT(*))
        nullm = [getattr(cols, "nulls", {}).get(i) for i in cols_idx]
        flat_nn = [sel.reshape(-1) if m is None
                   else (sel & ~m).reshape(-1) for m in nullm]
        vals = jnp.stack([c.reshape(-1) for c in (cols[i] for i in cols_idx)],
                         axis=-1)                       # (N, V)
        count = jnp.sum(onehot, axis=0)                 # (G,)
        flat_sel = sel.reshape(-1)
        if agg_dt.kind == "i" and np.dtype(acc_np).itemsize == 4:
            # the MXU path: (N,G)x(N,V)->(G,V) integer contraction,
            # exact within int32 (sums past 2^31 wrap, as any int32
            # engine would).  Only when the ACCUMULATOR is 32-bit: an
            # s64 dot_general does not lower on TPU (the X64-rewriter
            # has no dot rule — found live on v5e), so int64
            # accumulation (x64 mode, and int64 columns) rides
            # segment_sum below instead
            sums = jax.lax.dot_general(
                onehot, vals, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.dtype(acc_np)).T   # (V, G)
        else:
            # per-group scatter sum, NOT the matmul.  float: 0*NaN = NaN,
            # so one selected NaN row would poison EVERY group's sum
            # through the contraction — segment_sum confines it to its own
            # group, matching the pallas twin's per-group masking.  uint:
            # keeps the modular uint32 (u64 under x64) accumulation exact
            # without relying on unsigned dot support
            zero = agg_dt.type(0)
            sums = jnp.stack([
                jax.ops.segment_sum(
                    jnp.where(flat_sel, v, zero).astype(jnp.dtype(acc_np)),
                    flat_keys, num_segments=G + 1)[:G]
                for v in vals.T])
        # sum of squares for VAR/STDDEV: always floating (int32 squares
        # wrap far earlier than sums; f64 under x64, else f32) and
        # per-group confined like the float sums (NaN stays in its group)
        sq_t = jnp.dtype(sq_np)
        sumsqs = jnp.stack([
            jax.ops.segment_sum(
                jnp.where(m, v.astype(sq_t) * v.astype(sq_t), 0.0),
                flat_keys, num_segments=G + 1)[:G]
            for v, m in zip(vals.T, flat_nn)])
        mins = jnp.stack([
            jax.ops.segment_min(jnp.where(m, v, hi), flat_keys,
                                num_segments=G + 1)[:G]
            for v, m in zip(vals.T, flat_nn)])
        maxs = jnp.stack([
            jax.ops.segment_max(jnp.where(m, v, lo), flat_keys,
                                num_segments=G + 1)[:G]
            for v, m in zip(vals.T, flat_nn)])
        out = {"count": count, "sums": sums, "sumsqs": sumsqs,
               "mins": mins, "maxs": maxs}
        if any(m is not None for m in nullm):
            # per-column non-NULL group counts: AVG/VAR/STD over a
            # nullable column divide by THESE, not the row count
            # (review finding: sums skipped NULLs, denominators did not)
            out["nncounts"] = jnp.stack([
                jax.ops.segment_sum(m.astype(jnp.int32), flat_keys,
                                    num_segments=G + 1)[:G]
                for m in flat_nn])
        return out

    return run


@partial(jax.jit, static_argnums=(2,))
def scan_groupby_step(pages_u8: jax.Array, threshold: jax.Array,
                      n_groups: int = 16):
    """Demo step over the default schema: GROUP BY (col1 mod n_groups)
    WHERE col0 > threshold, aggregating col0."""
    fn = make_groupby_fn(
        DEFAULT_SCHEMA,
        lambda cols, th: jnp.abs(cols[1]) % n_groups,
        n_groups,
        agg_cols=[0],
        predicate=lambda cols, th: cols[0] > th)
    return fn(pages_u8, threshold)
