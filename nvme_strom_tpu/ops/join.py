"""Broadcast join over HBM-resident heap pages.

The last relational op of the scan-compute tier (filter, aggregate,
GROUP BY, top-k — and now join): a small *build side* (dimension table)
is broadcast to the device, and each scanned batch probes it.

TPU-first shape: no hash table — the build keys are **sorted once** and
probes are ``jnp.searchsorted`` (vectorized binary search, log2(M) steps
of pure VPU compare/select), which XLA pipelines across the whole batch.
A CUDA port would build a hash table; on TPU sorted-probe beats scattered
loads.  Payload gather rides the same indices.

The step form aggregates joined rows (count + per-column sums + payload
sum), so it folds across streamed batches like every other scan op;
row-materializing joins compose from the same mask via
:mod:`..parallel.exchange` when the output must move to its key's owner.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..scan.heap import HeapSchema
from .filter_xla import DEFAULT_SCHEMA, decode_pages

__all__ = ["make_join_fn", "make_join_rows_fn", "make_star_fn",
           "make_star_rows_fn", "key_hash32", "hash_split_build",
           "check_join_how", "JOIN_HOWS"]

# Knuth multiplicative constant: scrambles int32 keys so hash % P spreads
# adjacent/striped key spaces evenly across partitions
_KNUTH = np.uint32(2654435761)

# The join faces every strategy serves (strategy choice must never change
# the available semantics): inner = rows with a partner, plus its payload;
# semi = EXISTS (rows with a partner, build payload not exposed); anti =
# NOT EXISTS (rows without a partner); left = every selected probe row,
# payload where partnered and a NULL indicator where not.
JOIN_HOWS = ("inner", "left", "semi", "anti")


def check_join_how(how: str) -> str:
    if how not in JOIN_HOWS:
        raise ValueError(f"join how={how!r} (expected one of {JOIN_HOWS})")
    return how


def _emit_mask(how, sel, hit):
    """The rows a join emits under *how*, from the selected mask and the
    has-a-partner mask — THE single derivation every strategy uses."""
    if how in ("inner", "semi"):
        return hit
    if how == "anti":
        return sel & ~hit
    return sel            # left: every selected probe row


def _owner_mask(probe, owner_part):
    """Grace-pass ownership restriction: non-inner faces scanned in
    sequential build partitions must consider each probe row in exactly
    the pass that OWNS its key (else an anti/left row is emitted once per
    pass).  ``owner_part=(n_parts, p)``; None = no restriction."""
    if owner_part is None:
        return None
    n_parts, p = owner_part
    return (key_hash32(probe) % jnp.uint32(n_parts)).astype(jnp.int32) \
        == jnp.int32(p)


def key_hash32(k):
    """Order-scrambling uint32 hash of int32 keys — same expression for
    host numpy (build split) and traced jnp (fact-side routing), so both
    sides of the partitioned join agree on ownership."""
    if isinstance(k, np.ndarray) or np.isscalar(k):
        return (np.asarray(k).astype(np.uint32, casting="unsafe")
                * _KNUTH)
    return k.astype(jnp.uint32) * _KNUTH


def hash_split_build(build_keys, build_values, n_parts: int):
    """Host-side hash partitioning of the build table: returns a list of
    ``(keys, vals)`` per partition.  Every key lands in exactly one
    partition, so per-partition join results ADD to the broadcast
    answer — the degrade-instead-of-OOM path for build sides above
    ``config join_broadcast_max`` (Grace-style multi-pass locally, one
    partition per device over a mesh)."""
    bk = np.asarray(build_keys, np.int32)
    bv = np.asarray(build_values, _value_dtype(build_values))
    part = (key_hash32(bk) % np.uint32(n_parts)).astype(np.int64)
    return [(bk[part == p], bv[part == p]) for p in range(n_parts)]


def make_join_fn(schema: HeapSchema, probe_col: int,
                 build_keys: np.ndarray, build_values: np.ndarray, *,
                 predicate: Optional[Callable] = None,
                 how: str = "inner", owner_part=None):
    """Build a jitted ``run(pages_u8, *params) -> dict`` join step.

    ``build_keys``/``build_values`` — the dimension table (int32, unique
    keys; sorted internally).  A scanned row has a partner when column
    ``probe_col`` equals some build key (and *predicate* passes); *how*
    picks which rows the join emits (:data:`JOIN_HOWS`).

    Returns per batch: ``matched`` (count of EMITTED rows), ``sums`` —
    a LIST of per-column scalars over emitted rows covering EVERY fact
    column (``run.sum_cols``), each accumulated in its
    :func:`..ops.groupby.acc_dtypes` dtype: the same int32/uint32/
    float32 convention GROUP BY uses, so ``SUM(float_col)`` works in a
    join exactly as in an aggregate.  inner/left add ``payload_sum``
    (sum of matched build values — for left that is SQL's
    ``SUM(payload)`` over the outer result, NULLs ignored); left adds
    ``null_count`` (emitted rows without a partner).
    ``owner_part`` — see :func:`_owner_mask` (Grace passes only).
    """
    from .groupby import acc_dtypes
    check_join_how(how)
    keys, vals = _sorted_build(build_keys, build_values, schema, probe_col)
    sum_cols = list(range(schema.n_cols))
    accs = [acc_dtypes(schema.col_dtype(c))[0] for c in sum_cols]

    @jax.jit
    def run(pages_u8, *params):
        cols, valid = decode_pages(pages_u8, schema)
        sel = valid if predicate is None else valid & predicate(cols, *params)
        probe = cols[probe_col]
        own = _owner_mask(probe, owner_part)
        if own is not None:
            sel = sel & own
        hit, pay = _probe(keys, vals, probe, sel)
        emit = _emit_mask(how, sel, hit)
        out = {"matched": jnp.sum(emit.astype(jnp.int32)),
               "sums": [jnp.sum(jnp.where(emit, cols[c],
                                          schema.col_dtype(c).type(0)),
                                dtype=acc)
                        for c, acc in zip(sum_cols, accs)]}
        if how in ("inner", "left"):
            # payload accumulates in ITS acc_dtypes dtype (float stays
            # float32, ints follow the int convention)
            out["payload_sum"] = jnp.sum(
                jnp.where(hit, pay, vals.dtype.type(0)),
                dtype=acc_dtypes(vals.dtype)[0])
        if how == "left":
            out["null_count"] = jnp.sum((emit & ~hit).astype(jnp.int32))
        return out

    run.sum_cols = sum_cols
    return run


_VALUE_DTS = (np.dtype(np.int32), np.dtype(np.uint32),
              np.dtype(np.float32))


def _value_dtype(build_values) -> np.dtype:
    """Payload dtype normalization: int32/uint32/float32 pass through
    (SUM over a float payload column is ordinary SQL), anything else —
    python int lists, int64 — lands as int32 like before."""
    dt = np.asarray(build_values).dtype
    return dt if dt in _VALUE_DTS else np.dtype(np.int32)


def _sorted_build(build_keys: np.ndarray, build_values: np.ndarray,
                  schema: HeapSchema, probe_col: int):
    """Shared build-side prep: unique-key check + sort.  Returns HOST
    arrays — the jitted kernels capture them as constants (jnp ops accept
    np operands), and the index path's host emulation avoids a pointless
    H2D/D2H round trip.  Keys are int32; VALUES keep their dtype
    (int32/uint32/float32)."""
    if len(np.unique(build_keys)) != len(build_keys):
        raise ValueError("build_keys must be unique (inner join on a "
                         "dimension key)")
    if schema.col_dtype(probe_col) != np.dtype(np.int32):
        raise ValueError("probe column must be int32")
    order = np.argsort(build_keys, kind="stable")
    return (np.asarray(build_keys, np.int32)[order],
            np.asarray(build_values, _value_dtype(build_values))[order])


def _probe(keys, vals, probe, sel):
    """(hit mask, per-row payload) for one batch; an empty build table
    joins nothing instead of tripping a zero-size gather."""
    if keys.shape[0] == 0:
        return jnp.zeros_like(sel), jnp.zeros_like(probe)
    # host build arrays become captured constants here (a np array cannot
    # be indexed by the traced idx below)
    keys, vals = jnp.asarray(keys), jnp.asarray(vals)
    idx = jnp.clip(jnp.searchsorted(keys, probe), 0, keys.shape[0] - 1)
    return sel & (keys[idx] == probe), vals[idx]


def make_join_rows_fn(schema: HeapSchema, probe_col: int,
                      build_keys: np.ndarray, build_values: np.ndarray, *,
                      predicate: Optional[Callable] = None,
                      how: str = "inner", owner_part=None):
    """Row-materializing twin of :func:`make_join_fn`: instead of folding
    aggregates, each batch returns the per-row join outcome — ``hit``
    (the EMIT mask under *how*), ``partner`` (has a build partner — only
    differs from ``hit`` for left), the probed ``key``, the matched
    build ``payload`` (zeros where unpartnered), and the rows' global
    ``positions`` — flattened for host-side compression (the
    SELECT-with-JOIN face: joined tuples back to the executor, like the
    reference scan hands tuples up, pgsql/nvme_strom.c:941-979).
    """
    from .filter_xla import global_row_positions
    check_join_how(how)
    keys, vals = _sorted_build(build_keys, build_values, schema, probe_col)

    @jax.jit
    def run(pages_u8, *params):
        cols, valid = decode_pages(pages_u8, schema)
        sel = valid if predicate is None else valid & predicate(cols, *params)
        probe = cols[probe_col]
        own = _owner_mask(probe, owner_part)
        if own is not None:
            sel = sel & own
        hit, pay = _probe(keys, vals, probe, sel)
        emit = _emit_mask(how, sel, hit)
        return {"hit": emit.reshape(-1),
                "partner": hit.reshape(-1),
                "key": probe.reshape(-1),
                "payload": jnp.where(hit, pay, 0).reshape(-1),
                "positions": global_row_positions(
                    pages_u8, schema).reshape(-1)}

    return run


# ---------------------------------------------------------------------------
# Star joins (several broadcast dimensions probed in one pass)
# ---------------------------------------------------------------------------
#
# The reference never joins itself — its scan hands tuples to the
# PostgreSQL executor, which composes any number of joins ABOVE it
# (`pgsql/nvme_strom.c:941-979`).  This tier gives the TPU framework the
# star-schema core of that composition: each scanned batch probes N
# sorted dimension tables in the SAME fused kernel (N vectorized binary
# searches back-to-back — the probes pipeline on the VPU, and the batch
# is decoded once instead of once per join).

def _star_probe_all(joins, cols, valid, predicate, params):
    """Shared star-probe core: returns (emit mask, [(hit_i, pay_i)]).

    inner/semi dims restrict the emitted rows to partnered ones, anti
    dims to unpartnered ones; left dims never restrict (their NULL
    indicator is the per-dim hit mask)."""
    sel = valid if predicate is None else valid & predicate(cols, *params)
    probes = []
    emit = sel
    for (pc, keys, vals, how) in joins:
        # payload-less dims (semi/anti faces) probe with the keys as a
        # stand-in payload (never read)
        hit, pay = _probe(keys, keys if vals is None else vals,
                          cols[pc], sel)
        # per-dim restriction composes THE single emit derivation
        # (_emit_mask) — left contributes sel, i.e. no restriction
        emit = emit & _emit_mask(how, sel, hit)
        probes.append((hit, pay))
    return emit, probes


def make_star_fn(schema: HeapSchema, joins, *,
                 predicate: Optional[Callable] = None,
                 expr_fns=(), expr_zeros=(), expr_accs=()):
    """Build a jitted star-join aggregate step over *joins* — a list of
    ``(probe_col, build_keys, build_values|None, how)`` dimensions
    (build arrays pre-sorted via :func:`_sorted_build`).

    Returns per batch: ``count`` (emitted rows), ``sums`` — per-column
    masked sums over every fact column (acc_dtypes convention),
    ``nncounts`` — per-column emitted non-NULL counts (the AVG
    denominators; equal to ``count`` for non-nullable columns),
    ``pay_sums`` — one entry per dimension: the payload sum over
    emitted rows that HIT that dimension (None-valued dims — semi/anti —
    contribute 0), ``null_counts`` — per dimension, emitted rows without
    a partner there (the LEFT NULL face), and ``esums`` — masked sums of
    the optional expression values (``expr_fns[i](cols) -> (B, T)``,
    accumulated as ``expr_accs[i]`` with ``expr_zeros[i]`` off-rows).
    Everything is additive, so batches fold by plain tree-sum."""
    from .groupby import acc_dtypes
    sum_cols = list(range(schema.n_cols))
    accs = [acc_dtypes(schema.col_dtype(c))[0] for c in sum_cols]

    @jax.jit
    def run(pages_u8, *params):
        cols, valid = decode_pages(pages_u8, schema)
        emit, probes = _star_probe_all(joins, cols, valid, predicate,
                                       params)
        out = {"count": jnp.sum(emit.astype(jnp.int32)),
               "sums": [jnp.sum(jnp.where(emit, cols[c],
                                          schema.col_dtype(c).type(0)),
                                dtype=acc)
                        for c, acc in zip(sum_cols, accs)]}
        # AVG(fact col) denominators: NULL cells decode as 0 so the
        # masked sums already skip them — the non-NULL counts must too
        nulls = getattr(cols, "nulls", {})
        out["nncounts"] = [
            jnp.sum((emit & ~nulls[c]).astype(jnp.int32))
            if c in nulls else out["count"] for c in sum_cols]
        pay_sums, null_counts = [], []
        for (pc, keys, vals, how), (hit, pay) in zip(joins, probes):
            if vals is None:
                pay_sums.append(jnp.int32(0))
            else:
                pay_sums.append(jnp.sum(
                    jnp.where(emit & hit, pay, vals.dtype.type(0)),
                    dtype=acc_dtypes(np.asarray(vals).dtype)[0]))
            null_counts.append(jnp.sum((emit & ~hit).astype(jnp.int32)))
        out["pay_sums"] = pay_sums
        out["null_counts"] = null_counts
        if expr_fns:
            out["esums"] = [
                jnp.sum(jnp.where(emit, f(cols), z), dtype=a)
                for f, z, a in zip(expr_fns, expr_zeros, expr_accs)]
        return out

    run.sum_cols = sum_cols
    return run


def make_star_rows_fn(schema: HeapSchema, joins, *,
                      predicate: Optional[Callable] = None,
                      fact_cols=()):
    """Row-materializing twin of :func:`make_star_fn`: per batch returns
    ``hit`` (the emit mask), the requested fact columns (``c<i>``), each
    dimension's matched payload (``pay<i>``, zeros where unpartnered)
    and partner mask (``m<i>``), and global ``positions`` — flattened
    for host-side compression (the SELECT face of a star query)."""
    from .filter_xla import global_row_positions
    fact_cols = list(fact_cols)

    @jax.jit
    def run(pages_u8, *params):
        cols, valid = decode_pages(pages_u8, schema)
        emit, probes = _star_probe_all(joins, cols, valid, predicate,
                                       params)
        out = {"hit": emit.reshape(-1),
               "positions": global_row_positions(
                   pages_u8, schema).reshape(-1)}
        for c in fact_cols:
            out[f"c{c}"] = cols[c].reshape(-1)
        for i, ((pc, keys, vals, how), (hit, pay)) in \
                enumerate(zip(joins, probes)):
            if vals is not None:
                out[f"pay{i}"] = jnp.where(hit, pay, 0).reshape(-1)
            out[f"m{i}"] = hit.reshape(-1)
        return out

    return run
