"""Pallas TPU kernels: decompress + filter + project in one fused pass.

The Pallas twin of :mod:`.decode_xla`, built on the ``filter_pallas.py``
grid-pipeline pattern: each grid step streams one block of packed 8KB
pages HBM->VMEM (the pallas grid pipeline double-buffers the copies),
expands the colpack regions in registers — planar bit-unpack, D-way dict
select, R-step RLE interval masks, all static control flow — and folds
the masked aggregate into SMEM accumulators.  The wire and HBM carry only
packed bytes; logical rows exist nowhere but VMEM/registers, which is
what lets effective logical GB/s clear the ``h2d_peak`` transport ceiling.

Decoded columns are (block_pages, rows_per_block) tensors, so the VMEM
block is sized down as rows_per_block grows (a 32768-row block decodes
128KB per column per page).

On non-TPU backends the kernels run in interpreter mode so CI exercises
the same code path hardware-free (filter_pallas.py convention).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..scan.colpack import PackedMeta
from ..scan.heap import PAGE_SIZE, HeapSchema
from .decode_xla import decode_block_words
from .filter_pallas import _should_interpret, _sum_slots

__all__ = ["make_decode_filter_fn_pallas"]

_WORDS = PAGE_SIZE // 4


def _block_pages(meta: PackedMeta) -> int:
    """Pages per grid step: cap the decoded-column VMEM footprint at
    ~1MB per column (8 pages at rpb<=4096, scaling down to 1)."""
    per_page = meta.rows_per_block * 4
    return max(1, min(8, (1 << 20) // max(per_page, 1)))


def _make_kernel(meta: PackedMeta, schema: HeapSchema, predicate, need):
    kinds, slots, ni, nf = _sum_slots(schema)

    def kernel(w_ref, count_ref, isums_ref, fsums_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            count_ref[0, 0] = 0
            for s in range(max(ni, 1)):   # SMEM takes scalar stores only
                isums_ref[0, s] = 0
            for s in range(max(nf, 1)):
                fsums_ref[0, s] = 0.0

        w = w_ref[...]
        cols, valid = decode_block_words(w, meta, need)
        sel = valid if predicate is None else valid & predicate(cols)
        count_ref[0, 0] += jnp.sum(sel.astype(jnp.int32))
        for c in range(schema.n_cols):
            col = cols[c]
            if kinds[c] == "f":
                fsums_ref[0, slots[c]] += jnp.sum(
                    jnp.where(sel, col, jnp.float32(0)))
            else:
                if col.dtype != jnp.int32:  # uint32: accumulate the bits
                    col = jax.lax.bitcast_convert_type(col, jnp.int32)
                isums_ref[0, slots[c]] += jnp.sum(jnp.where(sel, col, 0))

    return kernel


def make_decode_filter_fn_pallas(meta: PackedMeta, schema: HeapSchema,
                                 predicate=None, *,
                                 need_cols: Optional[Sequence[int]] = None,
                                 interpret: Optional[bool] = None):
    """Fused decode->filter->project over packed pages (Pallas).

    Contract-identical to :func:`.decode_xla.make_decode_filter_fn_xla`
    (and to ``make_filter_fn_pallas``'s aggregate face): a jitted
    ``run(pages_u8) -> {"count", "sums"}``.  Integer sums ride the int32
    SMEM bank (uint32 bit-restored), floats the f32 bank — the same
    accumulator routing as the unpacked kernel, so packed and unpacked
    integer aggregates are byte-identical."""
    need = tuple(need_cols) if need_cols is not None else None
    bp = _block_pages(meta)
    kinds, slots, ni, nf = _sum_slots(schema)
    kernel = _make_kernel(meta, schema, predicate, need)

    def _run(pages_u8):
        b = pages_u8.shape[0]
        rem = b % bp
        if rem:   # zero padding fails the block magic -> contributes 0
            pages_u8 = jnp.pad(pages_u8, ((0, bp - rem), (0, 0)))
            b = pages_u8.shape[0]
        words = jax.lax.bitcast_convert_type(
            pages_u8.reshape(b, _WORDS, 4), jnp.int32).reshape(b, _WORDS)
        count, isums, fsums = pl.pallas_call(
            kernel,
            grid=(b // bp,),
            in_specs=[pl.BlockSpec((bp, _WORDS), lambda i: (i, 0))],
            out_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((1, 1), jnp.int32),
                jax.ShapeDtypeStruct((1, max(ni, 1)), jnp.int32),
                jax.ShapeDtypeStruct((1, max(nf, 1)), jnp.float32),
            ],
            interpret=_should_interpret() if interpret is None
            else interpret,
        )(words)
        sums = []
        for c in range(schema.n_cols):
            if kinds[c] == "f":
                sums.append(fsums[0, slots[c]])
            else:
                s = isums[0, slots[c]]
                dt = schema.col_dtype(c)
                if dt != np.dtype(np.int32):
                    s = jax.lax.bitcast_convert_type(s, jnp.dtype(dt))
                sums.append(s)
        return {"count": count[0, 0], "sums": sums}

    return jax.jit(_run)
