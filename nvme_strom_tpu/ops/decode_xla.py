"""Fused decode+filter+project over packed columnar extents (XLA path).

The wire carries ``scan/colpack.py`` packed blocks (8KB pages holding
``rows_per_block`` rows each); this module expands them ON THE DEVICE and
folds the filter + masked aggregate in the same fused dispatch, so the
host->HBM link — the measured ceiling, BENCH_MATRIX ``h2d_peak`` — moves
packed bytes while the query still sees logical rows.

``decode_block_words`` is deliberately built from nothing but slices,
shifts, masks, compares and minor-axis concatenation — every codec decode
is static control flow over fixed region geometry, so the SAME function
traces inside the Pallas kernels (:mod:`.decode_pallas`) and here under
plain jit.  The independent numpy decoder in ``scan/colpack.py`` is the
correctness oracle for both.

Projection is part of the fusion: columns outside ``need_cols`` are never
expanded — their sums are constant zeros the compiler folds away.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..scan.colpack import CPK_MAGIC, ColCodec, PackedMeta

__all__ = ["decode_block_words", "make_decode_filter_fn_xla"]


def _unpack_bits_jnp(packed_u32, bits: int, rpb: int):
    """Planar bit-unpack: (bp, nw) uint32 words -> (bp, rpb) uint32.

    Value ``j`` lives in word ``j % nw`` at shift ``(j // nw) * bits``
    (colpack's planar layout), so plane k is one shift+mask of the whole
    region and planes concatenate along the minor axis — no gather, no
    reshape."""
    nw = packed_u32.shape[1]
    vpw = 32 // bits
    mask = jnp.uint32((1 << bits) - 1) if bits < 32 \
        else jnp.uint32(0xFFFFFFFF)
    planes = [(packed_u32 >> jnp.uint32(k * bits)) & mask
              for k in range(vpw)]
    return jnp.concatenate(planes, axis=1)[:, :rpb]


def _decode_col(wu, cm: ColCodec, rpb: int, iota):
    """One column's region -> (bp, rpb) uint32 bit patterns."""
    r = wu[:, cm.off:cm.off + cm.nwords]
    if cm.codec == "raw":
        return r[:, :rpb]
    if cm.codec == "bitpack":
        base = r[:, 0:1]
        return _unpack_bits_jnp(r[:, 1:], cm.bits, rpb) + base
    if cm.codec == "dict":
        dvals = r[:, :cm.dsize]
        idx = _unpack_bits_jnp(r[:, cm.dsize:], cm.bits, rpb)
        # static D-way select-sum: exactly one slot matches, the rest
        # contribute 0 — a gather TPUs can actually vectorize
        acc = jnp.zeros_like(idx)
        for d in range(cm.dsize):
            acc = acc + jnp.where(idx == jnp.uint32(d),
                                  dvals[:, d:d + 1], jnp.uint32(0))
        return acc
    # rle: run values + cumulative ends; padded runs are empty [n, n)
    # intervals, so walking every rmax slot is mask-correct
    vals = r[:, 1:1 + cm.rmax]
    ends = jax.lax.bitcast_convert_type(
        r[:, 1 + cm.rmax:1 + 2 * cm.rmax], jnp.int32)
    acc = jnp.zeros(iota.shape, jnp.uint32)
    prev = jnp.zeros((iota.shape[0], 1), jnp.int32)
    for k in range(cm.rmax):
        end = ends[:, k:k + 1]
        m = (iota >= prev) & (iota < end)
        acc = acc + jnp.where(m, vals[:, k:k + 1], jnp.uint32(0))
        prev = end
    return acc


def decode_block_words(w, meta: PackedMeta,
                       need: Optional[Sequence[int]] = None):
    """(bp, 2048) int32 packed-page words -> ([typed (bp, rpb) col ...],
    valid mask).

    Pages without the data-block magic (the file header page, zero
    padding) decode to an all-False mask, so a packed file scans through
    the unmodified chunk pipeline.  Columns outside *need* come back as
    constant zeros (projection fused into the decode)."""
    rpb = meta.rows_per_block
    bp = w.shape[0]
    wu = jax.lax.bitcast_convert_type(w, jnp.uint32)
    iota = jax.lax.broadcasted_iota(jnp.int32, (bp, rpb), 1)
    n_rows = w[:, 2:3]
    valid = (w[:, 0:1] == CPK_MAGIC) & (iota < n_rows)
    cols = []
    for c, cm in enumerate(meta.cols):
        dt = jnp.dtype(np.dtype(meta.dtypes[c]))
        if need is not None and c not in need:
            cols.append(jnp.zeros((bp, rpb), dt))
            continue
        u = _decode_col(wu, cm, rpb, iota)
        cols.append(u if dt == jnp.uint32
                    else jax.lax.bitcast_convert_type(u, dt))
    return cols, valid


def make_decode_filter_fn_xla(meta: PackedMeta, predicate=None, *,
                              need_cols: Optional[Sequence[int]] = None):
    """Fused decode->filter->project for packed page batches (XLA).

    Same contract as :func:`.filter_xla.make_filter_fn`: a jitted
    ``run(pages_u8) -> {"count", "sums"}`` with per-column masked sums in
    the column dtypes — accumulation is dtype-identical to the unpacked
    scan, so integer aggregates are byte-identical between the two
    representations.  ``predicate(cols)`` sees the full positional column
    list (un-needed columns as zeros), exactly like the heap kernels."""
    need = tuple(need_cols) if need_cols is not None else None
    words_per_page = 8192 // 4

    @jax.jit
    def run(pages_u8):
        b = pages_u8.shape[0]
        w = jax.lax.bitcast_convert_type(
            pages_u8.reshape(b, words_per_page, 4),
            jnp.int32).reshape(b, words_per_page)
        cols, valid = decode_block_words(w, meta, need)
        sel = valid if predicate is None else valid & predicate(cols)
        return {
            "count": jnp.sum(sel.astype(jnp.int32)),
            "sums": [jnp.sum(jnp.where(sel, v, v.dtype.type(0)))
                     for v in cols],
        }

    return run
