"""XLA scan/filter kernels over HBM-resident heap pages.

The compute half of the pgsql analog: where the reference's CustomScan walks
tuples one at a time on the CPU (`pgsql/nvme_strom.c:941-979`), here a batch
of direct-loaded pages is decoded and filtered as dense tensor ops — the
whole page batch is one bitcast + masked reduction, which XLA fuses and the
VPU eats.  No data-dependent control flow: invalid/invisible tuples are
masked, not branched on (jit-safe, SURVEY.md's XLA-semantics constraint).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..scan.heap import HEADER_WORDS, PAGE_SIZE, HeapSchema

__all__ = ["decode_pages", "scan_filter_step", "make_filter_fn", "global_row_positions"]

_WORDS = PAGE_SIZE // 4

# default demo schema: two int32 data columns + visibility
DEFAULT_SCHEMA = HeapSchema(n_cols=2, visibility=True)


def decode_pages(pages_u8: jax.Array, schema: HeapSchema = DEFAULT_SCHEMA):
    """(B, 8192) uint8 pages -> dict of (B, T) int32 columns + valid mask.

    Pure bitcast/slice — zero data movement beyond what XLA fuses."""
    b = pages_u8.shape[0]
    words = jax.lax.bitcast_convert_type(
        pages_u8.reshape(b, _WORDS, 4), jnp.int32).reshape(b, _WORDS)
    n_tuples = words[:, 2]
    t = schema.tuples_per_page
    idx = jnp.arange(t, dtype=jnp.int32)[None, :]
    valid = idx < n_tuples[:, None]
    cols = []
    for c in range(schema.n_cols):
        s, e = schema.col_word_range(c)
        col = words[:, s:e]
        dt = schema.col_dtype(c)
        if dt != np.dtype(np.int32):
            # typed columns are a bitcast — layout is dtype-independent
            col = jax.lax.bitcast_convert_type(col, jnp.dtype(dt))
        cols.append(col)
    if schema.visibility:
        s, e = schema.col_word_range(schema.n_cols)
        visible = words[:, s:e] != 0
        valid = valid & visible
    return cols, valid


def global_row_positions(pages_u8: jax.Array, schema: HeapSchema):
    """(B, T) global row numbers from the page headers (word 1 is the
    page id), batch-position-independent so streamed folds stay exact.
    int32 positions wrap past 2^31 rows; under x64 widen to int64 —
    shared convention of ops/topk.py and the ORDER BY gather."""
    b = pages_u8.shape[0]
    words = jax.lax.bitcast_convert_type(
        pages_u8.reshape(b, _WORDS, 4), jnp.int32).reshape(b, _WORDS)
    page_ids = words[:, 1]
    t = schema.tuples_per_page
    pos_t = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return (page_ids[:, None].astype(pos_t) * t
            + jnp.arange(t, dtype=pos_t)[None, :])


@jax.jit
def scan_filter_step(pages_u8: jax.Array, threshold: jax.Array):
    """Flagship single-chip step: predicate col0 > threshold over a page
    batch; returns selected count and the sum of col1 over selected rows."""
    cols, valid = decode_pages(pages_u8)
    sel = valid & (cols[0] > threshold)
    count = jnp.sum(sel.astype(jnp.int32))
    total = jnp.sum(jnp.where(sel, cols[1], 0).astype(jnp.int64)
                    if jax.config.jax_enable_x64 else
                    jnp.where(sel, cols[1], 0))
    return {"count": count, "sum": total}


def make_filter_fn(schema: HeapSchema, predicate):
    """Build a jitted page-batch filter: ``predicate(cols) -> bool (B, T)``.
    Returns selected count, per-column masked sums."""

    @jax.jit
    def run(pages_u8):
        cols, valid = decode_pages(pages_u8, schema)
        sel = valid & predicate(cols)
        return {
            "count": jnp.sum(sel.astype(jnp.int32)),
            "sums": [jnp.sum(jnp.where(sel, c, 0)) for c in cols],
        }

    return run
