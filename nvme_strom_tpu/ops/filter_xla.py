"""XLA scan/filter kernels over HBM-resident heap pages.

The compute half of the pgsql analog: where the reference's CustomScan walks
tuples one at a time on the CPU (`pgsql/nvme_strom.c:941-979`), here a batch
of direct-loaded pages is decoded and filtered as dense tensor ops — the
whole page batch is one bitcast + masked reduction, which XLA fuses and the
VPU eats.  No data-dependent control flow: invalid/invisible tuples are
masked, not branched on (jit-safe, SURVEY.md's XLA-semantics constraint).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..scan.heap import HEADER_WORDS, PAGE_SIZE, HeapSchema

__all__ = ["decode_pages", "scan_filter_step", "make_filter_fn", "global_row_positions"]

_WORDS = PAGE_SIZE // 4

# default demo schema: two int32 data columns + visibility
DEFAULT_SCHEMA = HeapSchema(n_cols=2, visibility=True)


class Cols(list):
    """Decoded column list with the per-column NULL masks riding along:
    ``cols[c]`` is the (B, T) value array (zeros under NULL — the
    builder's convention), ``cols.nulls`` maps nullable column index ->
    (B, T) bool (True = NULL).  A plain list subclass so every existing
    ``cols[c]`` consumer is untouched."""

    def __init__(self, items, nulls=None):
        super().__init__(items)
        self.nulls = dict(nulls or {})


def decode_pages(pages_u8: jax.Array, schema: HeapSchema = DEFAULT_SCHEMA):
    """(B, 8192) uint8 pages -> (columns, valid mask).

    Pure bitcast/slice — zero data movement beyond what XLA fuses.
    8-byte columns (int64/float64) bitcast from word PAIRS and require
    ``jax_enable_x64`` (without it jnp would silently truncate — an
    exactness violation, so it refuses instead).  Nullable columns'
    validity bitmaps decode into ``cols.nulls`` (True = NULL)."""
    from ..api import StromError
    if schema.has_wide and not jax.config.jax_enable_x64:
        raise StromError(22, "schema has int64/float64 columns: enable "
                             "jax_enable_x64 (8-byte decode would "
                             "silently truncate at 32 bits)")
    b = pages_u8.shape[0]
    words = jax.lax.bitcast_convert_type(
        pages_u8.reshape(b, _WORDS, 4), jnp.int32).reshape(b, _WORDS)
    n_tuples = words[:, 2]
    t = schema.tuples_per_page
    idx = jnp.arange(t, dtype=jnp.int32)[None, :]
    valid = idx < n_tuples[:, None]
    cols = []
    for c in range(schema.n_cols):
        s, e = schema.col_word_range(c)
        col = words[:, s:e]
        dt = schema.col_dtype(c)
        if dt.itemsize == 8:
            # (B, 2T) words -> (B, T, 2) -> one 8-byte lane per tuple
            col = jax.lax.bitcast_convert_type(
                col.reshape(b, t, 2), jnp.dtype(dt))
        elif dt != np.dtype(np.int32):
            # typed columns are a bitcast — layout is dtype-independent
            col = jax.lax.bitcast_convert_type(col, jnp.dtype(dt))
        cols.append(col)
    nulls = {}
    for c in range(schema.n_cols):
        if not schema.col_nullable(c):
            continue
        s, e = schema.validity_word_range(c)
        vw = words[:, s:e]                      # (B, ceil(T/32))
        wi, bi = idx // 32, idx % 32            # (1, T)
        bits = (vw[:, wi.reshape(-1)].reshape(b, t)
                >> bi.astype(jnp.int32)) & 1
        nulls[c] = bits == 0
    if schema.visibility:
        s, e = schema.col_word_range(schema.n_cols)
        visible = words[:, s:e] != 0
        valid = valid & visible
    return Cols(cols, nulls), valid


def global_row_positions(pages_u8: jax.Array, schema: HeapSchema):
    """(B, T) global row numbers from the page headers (word 1 is the
    page id), batch-position-independent so streamed folds stay exact.
    int32 positions wrap past 2^31 rows; under x64 widen to int64 —
    shared convention of ops/topk.py and the ORDER BY gather."""
    b = pages_u8.shape[0]
    words = jax.lax.bitcast_convert_type(
        pages_u8.reshape(b, _WORDS, 4), jnp.int32).reshape(b, _WORDS)
    page_ids = words[:, 1]
    t = schema.tuples_per_page
    pos_t = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return (page_ids[:, None].astype(pos_t) * t
            + jnp.arange(t, dtype=pos_t)[None, :])


@jax.jit
def scan_filter_step(pages_u8: jax.Array, threshold: jax.Array):
    """Flagship single-chip step: predicate col0 > threshold over a page
    batch; returns selected count and the sum of col1 over selected rows."""
    cols, valid = decode_pages(pages_u8)
    sel = valid & (cols[0] > threshold)
    count = jnp.sum(sel.astype(jnp.int32))
    total = jnp.sum(jnp.where(sel, cols[1], 0).astype(jnp.int64)
                    if jax.config.jax_enable_x64 else
                    jnp.where(sel, cols[1], 0))
    return {"count": count, "sum": total}


def make_filter_fn(schema: HeapSchema, predicate):
    """Build a jitted page-batch filter: ``predicate(cols) -> bool (B, T)``.
    Returns selected count, per-column masked sums — NULL-aware: a
    nullable column's sum skips its NULL rows (SQL SUM semantics), and
    ``nncounts`` (per-column non-NULL selected-row counts, the
    COUNT(col)/AVG(col) denominators) appears whenever the schema has
    nullable columns."""
    any_null = any(schema.col_nullable(c) for c in range(schema.n_cols))

    @jax.jit
    def run(pages_u8):
        cols, valid = decode_pages(pages_u8, schema)
        sel = valid & predicate(cols)

        def colmask(c):
            n = cols.nulls.get(c)
            return sel if n is None else sel & ~n

        out = {
            "count": jnp.sum(sel.astype(jnp.int32)),
            "sums": [jnp.sum(jnp.where(colmask(c), v, 0))
                     for c, v in enumerate(cols)],
        }
        if any_null:
            out["nncounts"] = [
                jnp.sum(colmask(c).astype(jnp.int32))
                for c in range(schema.n_cols)]
        return out

    return run
