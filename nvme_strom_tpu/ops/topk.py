"""Top-k over HBM-resident heap pages (ORDER BY col LIMIT k).

Completes the scan-compute tier's SQL-analog set (filter, aggregate,
GROUP BY): per-batch ``jax.lax.top_k`` on the VPU plus a fold that merges
batch winners, so the scan streams arbitrarily large tables while device
memory holds only ``k`` candidates — the reference's per-tuple CPU walk
could only ever do this by sorting on the host.

Row identity travels with the values: ``positions`` are global row
numbers (``page_id * tuples_per_page + slot``), taken from the page
header's page_id so chunk reordering cannot misattribute rows.
Positions are int64 under ``jax_enable_x64``; without it they are int32
and tables past 2^31 rows would wrap (as any int32 engine would).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..scan.heap import HeapSchema, PAGE_SIZE
from .filter_xla import DEFAULT_SCHEMA, decode_pages, \
    global_row_positions

__all__ = ["make_topk_fn", "combine_topk", "scan_topk_step",
           "worst_sentinel", "topk_key", "rank_topk"]

_WORDS = PAGE_SIZE // 4


def worst_sentinel(dt: np.dtype, largest: bool) -> np.ndarray:
    """The pad value that can never beat a real candidate."""
    if dt.kind == "f":
        return np.array(-np.inf if largest else np.inf, dt)
    info = np.iinfo(dt)
    return np.array(info.min if largest else info.max, dt)


def topk_key(v, dt: np.dtype, largest: bool):
    """Order-reversing key for smallest-k that cannot overflow: unary
    minus wraps for uint32 and INT32_MIN, bitwise NOT (~v = -v-1 /
    MAX-v) reverses order safely for both int kinds."""
    if largest:
        return v
    return -v if dt.kind == "f" else ~v


def rank_topk(flat_v, flat_p, k: int, dt: np.dtype, largest: bool):
    """The kernel's exact select/pad/squash on flat candidate arrays —
    ONE implementation shared by the page kernel and the index access
    path, so the two cannot drift on tie-breaking (lax.top_k keeps the
    first occurrence), NaN ranking (maximal), or the sentinel squash."""
    worst = worst_sentinel(dt, largest)
    kk = min(k, int(flat_v.size))
    if kk:
        _, idx = jax.lax.top_k(topk_key(flat_v, dt, largest), kk)
        vals = flat_v[idx]
        positions = flat_p[idx]
    else:
        vals = jnp.zeros((0,), dt)
        positions = jnp.zeros((0,), flat_p.dtype)
    if kk < k:  # fewer candidates than k: pad to the contract
        vals = jnp.concatenate([vals, jnp.full((k - kk,), worst, dt)])
        positions = jnp.concatenate(
            [positions, jnp.full((k - kk,), -1, positions.dtype)])
    # pad slots and filtered-out rows already carry position -1 (the
    # callers set it); a REAL row whose value happens to equal the worst
    # sentinel keeps its position — value-based squashing would silently
    # lose rows, and value 0 / UINT32_MAX are common in unsigned data
    return vals, positions


def make_topk_fn(schema: HeapSchema, col: int, k: int, *,
                 largest: bool = True,
                 predicate: Optional[Callable] = None):
    """Build a jitted ``run(pages_u8, *params) -> {"values", "positions"}``.

    Returns the *k* largest (or smallest) values of column ``col`` among
    valid (and predicate-passing) rows of the batch, with their global row
    numbers.  Fewer than ``k`` qualifying rows pad with the dtype's worst
    sentinel and position -1.
    """
    dt = schema.col_dtype(col)
    worst = worst_sentinel(dt, largest)

    def key_of(v):
        return topk_key(v, dt, largest)

    @jax.jit
    def run(pages_u8, *params):
        cols, valid = decode_pages(pages_u8, schema)
        sel = valid if predicate is None else \
            valid & predicate(cols, *params)
        v = cols[col]
        # global row ids from the page header, not the batch position
        pos = global_row_positions(pages_u8, schema)
        flat_v = jnp.where(sel, v, worst).reshape(-1)
        flat_p = jnp.where(sel, pos, -1).reshape(-1)
        vals, positions = rank_topk(flat_v, flat_p, k, dt, largest)
        return {"values": vals, "positions": positions}

    run.k = k
    run.largest = largest
    run.worst = worst
    # the matching fold, with the ordering baked in — pass this as
    # scan_filter(..., combine=run.combine) so largest/smallest agree
    run.combine = lambda a, b: combine_topk(a, b, largest=largest,
                                            key_of=key_of)
    return run


def combine_topk(acc: dict, out: dict, *, largest: bool = True,
                 key_of=None) -> dict:
    """Batch-fold combiner: merge two top-k candidate sets into one.

    Prefer the fn-bound form ``combine=fn.combine`` (it carries the
    ordering); calling this directly requires passing the same *largest*
    the step was built with."""
    vals = jnp.concatenate([acc["values"], out["values"]])
    poss = jnp.concatenate([acc["positions"], out["positions"]])
    k = acc["values"].shape[0]
    if key_of is not None:
        key = key_of(vals)
    elif largest:
        key = vals
    else:
        key = -vals if vals.dtype.kind == "f" else ~vals
    _, idx = jax.lax.top_k(key, k)
    return {"values": vals[idx], "positions": poss[idx]}


_DEMO_CACHE = {}


def scan_topk_step(pages_u8, threshold, k: int = 8):
    """Demo step: top-k of col0 among rows with col0 > threshold.
    The jitted kernel is cached per k (one compile per shape, not per
    batch — scan_filter calls the step once per streamed batch)."""
    fn = _DEMO_CACHE.get(k)
    if fn is None:
        fn = _DEMO_CACHE[k] = make_topk_fn(
            DEFAULT_SCHEMA, 0, k,
            predicate=lambda cols, th: cols[0] > th)
    return fn(pages_u8, threshold)
