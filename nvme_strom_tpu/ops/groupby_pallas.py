"""Pallas TPU kernel for grouped aggregation (GROUP BY) over heap pages.

Single-pass twin of :mod:`.groupby` (the XLA one-hot-contraction path):
each grid step streams one block of 8KB pages HBM→VMEM, decodes the
columnar layout in registers, and folds per-group count/sum/min/max into
SMEM accumulators that persist across the (sequential) TPU grid — the
whole batch is consumed with zero intermediate HBM traffic, the same
shape as :mod:`.filter_pallas` but with ``(G,)``/``(V, G)`` accumulators
instead of scalars.  Replaces the reference's per-tuple CPU aggregation
walk (`pgsql/nvme_strom.c:941-979`).

Group reduction inside the kernel: **float32 aggregation rides the MXU**
via a batched ``(bp, G, T)`` one-hot contraction (finite-masked values
plus NaN/±inf indicator rows in one stacked matmul, IEEE semantics
reconstructed per group — something even the XLA twin avoids, scatter-
summing floats instead), while integer aggregation keeps the
**statically unrolled per-group masked reduction**: Mosaic's int32
matmul support is narrower than XLA's and float accumulation would
break the int-exactness contract.  Mosaic layout constraints shape the
float path: the one-hot is built ``(bp, G, T)`` with T minor (a G-minor
layout needs a reshape Mosaic won't lower on decode-derived operands)
and minor-dim insertion happens only on 32-bit operands, never bool.

**Large-``G`` strategy (why the planner caps pallas at G <= 64,
``scan/query._PALLAS_MAX_GROUPS``):** the unroll emits ``O(G·V)`` scalar
SMEM updates per block, so both compile time and SMEM footprint scale
linearly with ``G·V``.  Tiling the unroll (grid over 64-group blocks)
would fix SMEM but re-stream every page ``G/64`` times from HBM — strictly
worse than the XLA one-hot contraction, whose MXU matmul amortizes all
``G`` groups in one pass over the data.  Above the cap the XLA path is
therefore the *designed* answer, not a fallback; EXPLAIN reports the
routing and reason.

Contract-identical to :func:`.groupby.make_groupby_fn` (int32 / uint32 /
float32 agg columns, accumulator dtypes and min/max sentinels all derived
from :func:`.groupby.acc_dtypes` — THE shared accumulation convention),
so the two are differentially testable.  On non-TPU backends the kernel
runs in interpreter mode.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..scan.heap import PAGE_SIZE, HeapSchema
from .filter_pallas import _BLOCK_PAGES, _decode_block, _pad_pages, \
    _should_interpret

__all__ = ["make_groupby_fn_pallas"]

_WORDS = PAGE_SIZE // 4


def make_groupby_fn_pallas(schema: HeapSchema, key_fn: Callable,
                           n_groups: int, *,
                           agg_cols: Optional[Sequence[int]] = None,
                           predicate: Optional[Callable] = None,
                           interpret: Optional[bool] = None):
    """Build a jitted ``run(pages_u8, *params) -> dict`` grouped aggregate
    (Pallas twin of :func:`.groupby.make_groupby_fn`, same contract).

    ``key_fn(cols, *params) -> (B, T) int32`` group ids in ``[0, n_groups)``
    (out-of-range ids fall into no group); scalar ``*params`` are staged
    through SMEM as int32.  Returns per group: ``count (G,)`` and
    ``sums / mins / maxs`` of shape ``(len(agg_cols), G)``.  Aggregation
    columns share one dtype — int32, uint32, or float32 (same contract as
    the XLA twin; accumulator/sentinel dtypes from ``acc_dtypes``)."""
    from .groupby import _check_agg_cols, acc_dtypes
    cols_idx, agg_dt = _check_agg_cols(schema, agg_cols)
    G = int(n_groups)
    V = len(cols_idx)
    # THE accumulation convention (groupby.acc_dtypes): sum accumulator,
    # sumsq dtype, and min/max sentinels — derived, not hard-coded, so the
    # pallas and XLA paths cannot drift (x64 included).
    acc_np, sq_np, lo, hi = acc_dtypes(agg_dt)
    acc_t = jnp.dtype(acc_np)
    sq_t = jnp.dtype(sq_np)
    col_t = jnp.dtype(agg_dt)
    # np scalars, not jnp: traced values would be captured constants
    # inside the pallas kernel closure
    zero = acc_np.type(0)
    sq_zero = sq_np.type(0)

    float_mxu = agg_dt.kind == "f" and not jax.config.jax_enable_x64
    # Mosaic cannot reduce UNSIGNED integers ("Reductions over unsigned
    # integers not implemented") — the uint32 path therefore computes in
    # order/wrap-preserving int32 BIT-SPACE on device: sums accumulate
    # int32 bits (two's-complement wraparound == uint32 wraparound, so
    # the acc_dtypes mod-2^32 contract holds exactly) and min/max work
    # on sign-bit-XORed values (u32 order == i32 order after the flip);
    # run() bitcasts the outputs back.  x64 widens to 64-bit
    # accumulators where the same trick would need int64 SMEM — the
    # interpret path serves that (no-x64 is the TPU configuration).
    uint_bits = agg_dt.kind == "u" and not jax.config.jax_enable_x64
    if uint_bits:
        # stored representations on device: int32 bits for sums (wrap-
        # exact), sign-flipped int32 for min/max (order-preserving);
        # sentinels are the flipped images of hi=uint32max / lo=0
        store_acc, store_col = jnp.int32, jnp.int32
        ref_hi, ref_lo = np.int32((1 << 31) - 1), np.int32(-(1 << 31))
    else:
        store_acc, store_col = acc_t, col_t
        ref_hi, ref_lo = hi, lo

    def make_kernel(n_params: int):
      def kernel(params_ref, w_ref, count_ref, sums_ref, sumsqs_ref,
                 mins_ref, maxs_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            if float_mxu:   # VMEM accumulators take vector stores
                count_ref[...] = jnp.zeros_like(count_ref)
                sums_ref[...] = jnp.zeros_like(sums_ref)
                sumsqs_ref[...] = jnp.zeros_like(sumsqs_ref)
                mins_ref[...] = jnp.full_like(mins_ref, hi)
                maxs_ref[...] = jnp.full_like(maxs_ref, lo)
            else:
                for g in range(G):  # SMEM takes scalar stores only
                    count_ref[0, g] = 0
                    for vi in range(V):
                        sums_ref[vi, g] = zero
                        sumsqs_ref[vi, g] = sq_zero
                        mins_ref[vi, g] = ref_hi
                        maxs_ref[vi, g] = ref_lo

        params = [params_ref[k] for k in range(n_params)]
        cols, valid = _decode_block(w_ref[...], schema)
        keys = key_fn(cols, *params)
        sel = valid & (keys >= 0) & (keys < G)
        if predicate is not None:
            sel = sel & predicate(cols, *params)
        if float_mxu:
            # FLOAT path rides the MXU inside the kernel: a masked
            # (bp, T, G) one-hot contracts with the value rows via a
            # batched dot_general — one matmul per aggregation column
            # replaces the G-wide unrolled masked-sum sweep (the reason
            # the float kernel trailed the XLA path, which itself
            # avoids the matmul for floats and scatter-sums instead).
            # NaN/±inf rows would poison EVERY group through the
            # contraction (0*NaN=NaN), so non-finite values contract as
            # INDICATOR rows alongside the finite-masked values and the
            # IEEE result is reconstructed per group — exact, not
            # approximate.  min/max have no MXU form but vectorize
            # across groups off the same one-hot (one 3-D reduction
            # each, not a G-unrolled sweep).
            bp, t = keys.shape
            # (bp, G, T) orientation — T stays the MINOR dim: Mosaic
            # refuses the reshape a G-minor (bp, T, G) layout needs on
            # decode-derived operands, and minor-dim insertion is
            # 32-bit-only (expand int32 keys / a float mask, never bool)
            onehot = (keys[:, None, :] == jax.lax.broadcasted_iota(
                jnp.int32, (bp, G, t), 1)).astype(jnp.float32) \
                * sel.astype(jnp.float32)[:, None, :]   # (bp, G, T)
            # per-block counts (<= bp*T) are exact in f32; the CAST per
            # block keeps the cross-block accumulator int32-exact
            count_ref[...] += jnp.sum(onehot,
                                      axis=(0, 2)).astype(jnp.int32)
            for vi, ci in enumerate(cols_idx):
                vf = cols[ci].astype(jnp.float32)
                isn = jnp.isnan(vf)
                pin = vf == jnp.inf
                nin = vf == -jnp.inf
                fin = jnp.where(isn | pin | nin, 0.0, vf)
                stk = jnp.stack(
                    [fin, fin * fin, isn.astype(jnp.float32),
                     pin.astype(jnp.float32), nin.astype(jnp.float32)],
                    axis=1)                             # (bp, 5, T)
                mm = jax.lax.dot_general(
                    stk, onehot,
                    dimension_numbers=(((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)  # (bp, 5, G)
                tot = jnp.sum(mm, axis=0)                # (5, G)
                s, s2 = tot[0], tot[1]
                n_nan, n_pinf, n_ninf = tot[2], tot[3], tot[4]
                nan = jnp.float32(jnp.nan)
                inf = jnp.float32(jnp.inf)
                # IEEE sum semantics per group: NaN dominates; mixed
                # infinities are NaN; one-signed infinity wins; else
                # the finite contraction.  Cross-block accumulation
                # preserves these cases (inf+-inf=NaN, NaN+x=NaN)
                sum_g = jnp.where(
                    (n_nan > 0) | ((n_pinf > 0) & (n_ninf > 0)), nan,
                    jnp.where(n_pinf > 0, inf,
                              jnp.where(n_ninf > 0, -inf, s)))
                sq_g = jnp.where(
                    n_nan > 0, nan,
                    jnp.where((n_pinf > 0) | (n_ninf > 0), inf, s2))
                sums_ref[vi, :] += sum_g
                sumsqs_ref[vi, :] += sq_g
                # min/max vectorize across groups off the same one-hot:
                # ONE 3-D masked reduction each instead of a G-unrolled
                # sweep (VMEM vector accumulators on this path)
                vb = cols[ci][:, None, :]               # (bp, 1, T)
                mins_ref[vi, :] = jnp.minimum(
                    mins_ref[vi, :],
                    jnp.min(jnp.where(onehot > 0, vb, hi), axis=(0, 2)))
                maxs_ref[vi, :] = jnp.maximum(
                    maxs_ref[vi, :],
                    jnp.max(jnp.where(onehot > 0, vb, lo), axis=(0, 2)))
        else:
            # integer paths keep the static unroll: Mosaic's int32
            # matmul support is narrower than XLA's, and float
            # accumulation of int32 sums would break the exactness
            # contract (acc_dtypes)
            for g in range(G):
                m = sel & (keys == g)                   # (bp, T)
                count_ref[0, g] += jnp.sum(m.astype(jnp.int32))
                for vi, ci in enumerate(cols_idx):
                    v = cols[ci]
                    if uint_bits:
                        # Mosaic lacks the uint32->float cast too:
                        # decompose through int32 halves (hi bit + low
                        # 31 bits), both of which cast fine
                        lo31 = jax.lax.bitcast_convert_type(
                            v & jnp.uint32(0x7FFFFFFF),
                            jnp.int32).astype(sq_t)
                        hib = jax.lax.bitcast_convert_type(
                            v >> 31, jnp.int32).astype(sq_t)
                        vf = hib * sq_t.type(2.0 ** 31) + lo31
                    else:
                        vf = v.astype(sq_t)
                    if uint_bits:   # int32 bit-space sum (wrap-exact)
                        v32 = jax.lax.bitcast_convert_type(v, jnp.int32)
                        sums_ref[vi, g] += jnp.sum(
                            jnp.where(m, v32, jnp.int32(0)))
                    else:
                        sums_ref[vi, g] += jnp.sum(
                            jnp.where(m, v,
                                      agg_dt.type(0)).astype(acc_t))
                    # floating accumulator (shared sumsqs contract:
                    # int32 squares would wrap far earlier than sums)
                    sumsqs_ref[vi, g] += jnp.sum(
                        jnp.where(m, vf * vf, sq_zero))
        if not float_mxu:
            # integer min/max: per-group masked reductions (the float
            # path vectorized them off the one-hot above); unsigned
            # values compare in sign-flipped int32 space
            for g in range(G):
                m = sel & (keys == g)
                for vi, ci in enumerate(cols_idx):
                    v = cols[ci]
                    if uint_bits:   # sign-flip: u32 order in i32 space
                        v = jax.lax.bitcast_convert_type(
                            v ^ jnp.uint32(1 << 31), jnp.int32)
                    mins_ref[vi, g] = jnp.minimum(
                        mins_ref[vi, g],
                        jnp.min(jnp.where(m, v, ref_hi)))
                    maxs_ref[vi, g] = jnp.maximum(
                        maxs_ref[vi, g],
                        jnp.max(jnp.where(m, v, ref_lo)))
      return kernel

    @jax.jit
    def run(pages_u8, *params):
        padded = _pad_pages(pages_u8)
        b = padded.shape[0]
        words = jax.lax.bitcast_convert_type(
            padded.reshape(b, _WORDS, 4), jnp.int32).reshape(b, _WORDS)
        pvec = jnp.stack([jnp.asarray(p, jnp.int32) for p in params]) \
            if params else jnp.zeros((1,), jnp.int32)
        vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
        smem = pl.BlockSpec(memory_space=pltpu.SMEM)
        count, sums, sumsqs, mins, maxs = pl.pallas_call(
            make_kernel(len(params)),
            grid=(b // _BLOCK_PAGES,),
            in_specs=[
                smem,
                pl.BlockSpec((_BLOCK_PAGES, _WORDS), lambda i: (i, 0)),
            ],
            # float path: MXU-contracted count/sums/sumsqs accumulate as
            # VECTORS in VMEM; min/max (and every integer path) stay in
            # SMEM scalar accumulators
            out_specs=[
                vmem if float_mxu else smem,
                vmem if float_mxu else smem,
                vmem if float_mxu else smem,
                vmem if float_mxu else smem,
                vmem if float_mxu else smem,
            ],
            out_shape=[
                jax.ShapeDtypeStruct((G,) if float_mxu else (1, G),
                                     jnp.int32),
                jax.ShapeDtypeStruct((V, G), store_acc),
                jax.ShapeDtypeStruct((V, G), sq_t),
                jax.ShapeDtypeStruct((V, G), store_col),
                jax.ShapeDtypeStruct((V, G), store_col),
            ],
            interpret=_should_interpret() if interpret is None else interpret,
        )(pvec, words)
        if uint_bits:
            sums = jax.lax.bitcast_convert_type(sums, jnp.uint32)
            mins = jax.lax.bitcast_convert_type(
                mins, jnp.uint32) ^ jnp.uint32(1 << 31)
            maxs = jax.lax.bitcast_convert_type(
                maxs, jnp.uint32) ^ jnp.uint32(1 << 31)
        return {"count": count if float_mxu else count[0],
                "sums": sums, "sumsqs": sumsqs,
                "mins": mins, "maxs": maxs}

    return run
