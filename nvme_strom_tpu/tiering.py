"""Unified extent address space (ISSUE 20).

ONE placement/migration engine over the capacity hierarchy
HBM → pinned RAM → SSD.  The per-tier stores stay where they grew up —
:mod:`.cache` is the RAM tier (byte-weighted ARC policy plugin),
:mod:`.serving.hbm_tier` is the HBM tier (byte-weighted LRU) — but every
*transition* between tiers is decided here, in :class:`ExtentSpace`:

* **demand faults** — a miss filled at wait time, after the fault ladder
  (retry/hedge/mirror/checksum) healed the bytes, lands in the RAM tier
  through :meth:`ExtentSpace.fault_fill`;
* **promotion** — the RAM tier's second-touch (ARC t1→t2) transition
  hands the extent UP; under ``tier_unified`` (the default) the move is
  *exclusive*: the RAM copy is surrendered (:meth:`yield_up` on the
  tier) so HBM + RAM behave as one capacity pool instead of
  double-caching the hot set;
* **demotion** — HBM eviction victims move DOWN into the RAM tier; RAM
  eviction victims drop to the SSD-backed tier (the file itself — a
  future read is a demand fault, not data loss);
* **invalidation** — the write ladder's existing invalidation sites call
  ONE contract (:meth:`invalidate_extents` / :meth:`invalidate_paths`)
  that fans out over every registered tier;
* **pins** — the KV pool's block pins ride :meth:`pin`/:meth:`unpin`
  instead of reaching into the HBM tier directly.

Every lease any tier hands out is a :class:`TierLease`: one refcounted
type with one holder contract (``copy_into`` fail-open on stale or
corrupt, ``device_array`` when the bytes live on device, freed at the
last release).  The stromlint rule family ``tiers`` ratchets the rest of
the tree onto this surface: tier internals (``lookup``/``fill``/
``admit``/``drop``/``promote_hook``/``invalidate_*``) outside this
module and the two policy plugins are findings.

Setting ``tier_unified = false`` reverts to three isolated tiers (no
promotion, HBM evictions drop instead of demoting) — the A/B baseline
``bench.py --tiering`` measures the unified engine against.

The module-global ``extent_space`` follows the one-branch-when-off
contract of the tiers it drives: ``configure()`` re-reads the capacity
Vars once, hot paths check the plain per-tier ``active`` attributes.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence, Tuple

from .config import config
from .stats import stats
from .trace import recorder as _trace
from .integrity import domain as _integrity

__all__ = ["TierLease", "ExtentSpace", "extent_space", "source_key"]


def source_key(source) -> tuple:
    """Stable identity for a source in the unified space: the tuple of
    its members' real paths (works for plain, segmented and striped
    sources, and the loopback fakes, which subclass them)."""
    # representation tags (e.g. a packed .cpk sidecar's
    # "#repr=cpk"/"#gen=..." pair) extend the identity so a re-encoded
    # file can never alias a stale cached extent; tags start with '#'
    # and thus never collide with real paths
    extra = tuple(getattr(source, "cache_key_extra", ()) or ())
    members = getattr(source, "members", None)
    if members:
        try:
            return tuple(os.path.realpath(m.path)
                         for m in members) + extra
        except AttributeError:
            pass
    path = getattr(source, "path", None)
    if isinstance(path, str):
        return (os.path.realpath(path),) + extra
    return ("<anon:%d>" % id(source),) + extra


class TierLease:
    """Refcounted pin on a resident extent, in ANY tier.

    Taken under the owning tier's lock by its ``lookup``; the holder
    copies out with :meth:`copy_into` and must :meth:`release` (eviction
    skips the entry, invalidation only marks it stale while the lease is
    live, stale entries are never served and free at the last release).

    The owning tier supplies three hooks: ``_lease_view(entry)`` — a
    host memoryview of the bytes (None when the backing is gone),
    ``_drop_corrupt(entry)`` — drop a rotted entry under its lease
    rules, and ``_release(entry)`` — refcount bookkeeping.
    """

    __slots__ = ("_owner", "_entry", "_released")

    def __init__(self, owner, entry) -> None:
        self._owner = owner
        self._entry = entry
        self._released = False

    @property
    def length(self) -> int:
        return self._entry.length

    @property
    def stale(self) -> bool:
        return self._entry.stale

    def device_array(self):
        """The extent as its device-resident uint8 array (no copy) when
        the owning tier keeps one, else None; None too when the entry
        was invalidated after the lookup."""
        e = self._entry
        return None if e.stale else getattr(e, "array", None)

    def copy_into(self, dest) -> bool:
        """Copy the extent into *dest* (a writable buffer no longer than
        the extent).  Returns False — and copies nothing — when the
        entry was invalidated after the lookup, or (integrity=always)
        when the resident bytes rotted; the caller re-reads through the
        fault ladder.  Fail-open: never EBADMSG from a cached copy."""
        e = self._entry
        if e.stale:
            return False
        view = self._owner._lease_view(e)
        if view is None:
            return False
        if _integrity.verify_reads and \
                not _integrity.verify(view[:e.length], e.crc):
            self._owner._drop_corrupt(e)
            return False
        n = len(dest)
        dest[:] = view[:n]
        return not e.stale

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._owner._release(self._entry)


class ExtentSpace:
    """The one placement/migration engine over the registered tiers.

    Tiers self-register at import (module bottom of :mod:`.cache` and
    :mod:`.serving.hbm_tier`), keeping this module import-light — it
    never imports a tier at top level, the tiers import it for
    :class:`TierLease`.
    """

    #: lookup order, top of the hierarchy first
    _ORDER = ("hbm", "ram")

    def __init__(self) -> None:
        self.unified = True
        self._tiers: Dict[str, object] = {}

    # -- registry ------------------------------------------------------

    def register_tier(self, name: str, tier) -> None:
        self._tiers[name] = tier

    def tier(self, name: str):
        return self._tiers.get(name)

    def tier_active(self, name: str) -> bool:
        t = self._tiers.get(name)
        return bool(t is not None and t.active)

    def tier_capacity(self, name: str) -> int:
        t = self._tiers.get(name)
        return int(getattr(t, "_cap", 0)) if t is not None else 0

    # -- configuration -------------------------------------------------

    def configure(self) -> None:
        """(Re)configure every tier from the unified capacity Vars and
        rewire the inter-tier transitions.  The canonical knobs are
        ``tier_ram_bytes`` / ``tier_hbm_bytes`` / ``tier_kv_block_bytes``
        (the pre-unification names alias them, see MIGRATION.md)."""
        # deferred imports: the tier modules import this module for the
        # shared lease type, so the space pulls its plugins in lazily
        from .cache import residency_cache            # registers "ram"
        from .serving.hbm_tier import hbm_tier        # registers "hbm"
        residency_cache.configure()
        hbm_tier.configure()          # calls rewire() itself

    def clear_tiers(self) -> None:
        """Drop every resident extent in every tier (test/gate reset)."""
        for t in self._tiers.values():
            t.clear()

    def rewire(self) -> None:
        """Re-arm the inter-tier transitions after any tier's
        ``configure()``: the RAM tier's second-touch hook points at
        :meth:`_promote_from_ram` only while the HBM tier is on AND the
        space is unified — one branch when off, and ``tier_unified =
        false`` reverts to three isolated tiers (the A/B baseline)."""
        self.unified = bool(config.get("tier_unified"))
        ram = self._tiers.get("ram")
        hbm = self._tiers.get("hbm")
        if ram is None:
            return
        on = hbm is not None and hbm.active and self.unified
        ram.promote_hook = self._promote_from_ram if on else None

    # -- identity ------------------------------------------------------

    source_key = staticmethod(source_key)

    # -- read side -----------------------------------------------------

    @property
    def lookup_active(self) -> bool:
        """Any tier can serve a hit (the engine's plan-time branch)."""
        return any(t.active for t in self._tiers.values())

    @property
    def fill_active(self) -> bool:
        """The RAM tier accepts demand-fault fills (the engine's
        wait-time branch)."""
        return self.tier_active("ram")

    def lookup(self, skey: tuple, base: int,
               length: int) -> Optional[Tuple[TierLease, str]]:
        """Top-down exact-extent lookup: returns ``(lease, tier_name)``
        from the highest tier holding the extent, or None on a full
        miss.  An HBM hit outranks a RAM hit — it costs one device→dest
        copy and no host-slab touch at all."""
        for name in self._ORDER:
            t = self._tiers.get(name)
            if t is None or not t.active:
                continue
            lease = t.lookup(skey, base, length)
            if lease is not None:
                return lease, name
        return None

    # -- placement / migration -----------------------------------------

    def fault_fill(self, skey: tuple, base: int, length: int, data, *,
                   logical_length: int = 0, source_ref=None,
                   speculative: bool = False) -> bool:
        """Demand-fault fill: healed bytes from the fault ladder enter
        the hierarchy at the RAM tier.  Speculative (readahead) fills
        ride the same path but are provenance-tagged by the tier and
        never counted as faults — and, since a still-speculative extent
        takes the first-touch path on its first demand hit, they can
        never promote either."""
        ram = self._tiers.get("ram")
        if ram is None:
            return False
        ok = ram.fill(skey, base, length, data,
                      logical_length=logical_length, source_ref=source_ref,
                      speculative=speculative)
        if ok and not speculative:
            stats.add("nr_tier_ram_fault")
            if _trace.active:
                _trace.instant("tier_fault", offset=base, length=length,
                               args={"tier": "ram"})
        return ok

    def _promote_from_ram(self, skey: tuple, base: int, length: int,
                          data, *, crc=None, source_ref=None) -> bool:
        """Second-touch promotion (the RAM tier's ARC t1→t2 transition,
        invoked outside its lock): admit the bytes into HBM, then —
        exclusive migration — surrender the RAM copy so the two tiers
        pool capacity instead of double-caching.  The surrendered key is
        ghosted, so a later demotion re-enters RAM as frequency."""
        hbm = self._tiers.get("hbm")
        if hbm is None or not hbm.admit(skey, base, length, data,
                                        crc=crc, source_ref=source_ref):
            return False
        stats.add("nr_tier_hbm_promote")
        if _trace.active:
            _trace.instant("tier_promote", offset=base, length=length,
                           args={"tier": "hbm"})
        ram = self._tiers.get("ram")
        if ram is not None:
            ram.yield_up(skey, base, length)
        return True

    def demote_from_hbm(self, demoted) -> None:
        """HBM eviction victims move DOWN: each ``(key, data,
        source_ref)`` re-enters the RAM tier (a failed fill just means a
        future SSD re-read — the fault ladder is the floor of the
        hierarchy).  Split mode drops instead: isolated tiers do not
        migrate, which is exactly the baseline the tier gate beats."""
        if not self.unified:
            return
        ram = self._tiers.get("ram")
        if ram is None:
            return
        for key, data, source_ref in demoted:
            if data is None:
                continue
            skey, base, length = key
            if ram.fill(skey, base, length, data, source_ref=source_ref):
                stats.add("nr_tier_hbm_demote")
                if _trace.active:
                    _trace.instant("tier_demote", offset=base,
                                   length=length, args={"tier": "hbm"})

    # -- pinned placement (the KV pool's block pins) -------------------

    def pin(self, skey: tuple, base: int, length: int, data, *,
            crc=None, source_ref=None) -> Optional[TierLease]:
        """Place an extent in HBM and pin it there: admit + lookup as
        one transition.  Returns the holding lease, or None when the
        tier is off, capacity is pinned solid, or a racing drop won.
        The pin IS a promotion — it counts in the tier scoreboard."""
        hbm = self._tiers.get("hbm")
        if hbm is None or not hbm.active:
            return None
        if not hbm.admit(skey, base, length, data,
                         crc=crc, source_ref=source_ref):
            return None
        lease = hbm.lookup(skey, base, length)
        if lease is None:  # racing invalidation/drop won
            hbm.drop(skey, base, length)
            return None
        stats.add("nr_tier_hbm_promote")
        if _trace.active:
            _trace.instant("tier_promote", offset=base, length=length,
                           args={"tier": "hbm"})
        return lease

    def unpin(self, lease: Optional[TierLease], skey: tuple, base: int,
              length: int) -> None:
        """Release a pin taken with :meth:`pin` and drop the extent
        WITHOUT demotion — the caller owns the bytes' next home (the KV
        pool's explicit HBM→RAM block demotion)."""
        if lease is not None:
            lease.release()
        hbm = self._tiers.get("hbm")
        if hbm is not None:
            hbm.drop(skey, base, length)

    # -- coherency (ONE invalidation contract) -------------------------

    def invalidate_extents(self, skey: tuple,
                           extents: Sequence[Tuple[int, int]]) -> int:
        """The write ladder's invalidation contract: drop every resident
        copy the write touches, in EVERY tier.  Same-key entries match
        by byte overlap; entries under a different key that shares a
        file drop wholesale (offsets do not map across framings).
        Returns the number dropped across the hierarchy."""
        n = 0
        for name in self._ORDER:
            t = self._tiers.get(name)
            if t is not None:
                n += t.invalidate_extents(skey, extents)
        return n

    def invalidate_paths(self, paths: Sequence[str]) -> int:
        """Drop every resident extent over any of *paths*, in every tier
        (the checkpoint savers' contract after an atomic rename)."""
        n = 0
        for name in self._ORDER:
            t = self._tiers.get(name)
            if t is not None:
                n += t.invalidate_paths(paths)
        return n

    # -- integrity scrub -----------------------------------------------

    def scrub_tiers(self):
        """``(name, tier)`` pairs the background scrubber walks, bottom
        up (RAM rot is likelier than HBM rot, so RAM goes first in the
        round-robin)."""
        out = []
        for name in reversed(self._ORDER):
            t = self._tiers.get(name)
            if t is not None and t.active:
                out.append((name, t))
        return out

    # -- the ONE residency surface -------------------------------------

    def residency(self) -> Dict[str, int]:
        """Resident bytes per tier (the scoreboard's gauges)."""
        return {name: t.resident_bytes()
                for name, t in self._tiers.items()}

    def resident_fraction(self, paths: Sequence[str],
                          total_bytes: int) -> Dict[str, float]:
        """Fraction of a table's bytes resident, per tier — the surface
        the planner and EXPLAIN consume (expected hit ratio per tier
        for a scan over *paths*)."""
        return {name: t.resident_fraction(paths, total_bytes)
                for name, t in self._tiers.items()}


#: process-wide space; tiers self-register at import, the engine calls
#: ``configure()`` at Session construction, tests rewire via the tier
#: ``configure()`` methods (each ends in ``extent_space.rewire()``)
extent_space = ExtentSpace()
