"""stromlint — project-invariant static analysis for nvme_strom_tpu.

An AST-based checker (stdlib only) enforcing the invariants nine PRs of
growth made load-bearing: lock discipline over the engine-swap/lane/member
locks, mmap buffer lifetimes flowing into owned slabs, the ctypes layer
tracking ``csrc/strom_tpu.h`` field-for-field, the counter surface staying
renderable end to end, and config/fault-taxonomy hygiene.

Run it as ``strom_lint`` (console script), ``python -m
nvme_strom_tpu.analysis``, or ``make lint-strom``; it is gated in
``make check``.
"""

from __future__ import annotations

from . import abi, buffers, confcheck, locks, surface, tiers
from .core import (Baseline, BaselineError, Finding, Project,
                   apply_baseline, format_finding, load_baseline)

#: rule family -> module with a ``run(project) -> List[Finding]``
RULE_MODULES = {
    "locks": locks,
    "buffers": buffers,
    "abi": abi,
    "surface": surface,
    "config": confcheck,
    "tiers": tiers,
}

__all__ = [
    "RULE_MODULES", "Baseline", "BaselineError", "Finding", "Project",
    "apply_baseline", "format_finding", "load_baseline",
]
