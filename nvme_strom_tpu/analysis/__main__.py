"""``python -m nvme_strom_tpu.analysis`` == ``strom_lint``."""

import sys

from .cli import main

sys.exit(main())
