"""Rule family ``buffers``: mmap/aligned-alloc lifetime and ownership.

``buffers.release`` — every ``mmap.mmap(...)`` site must have a reachable
release path: stored on ``self`` it needs a ``self.<attr>.close()`` (or
``munmap``-equivalent) somewhere in the class; kept local it needs a
``.close()`` in the same function, a ``with`` scope, or a hand-off into an
owning slab type (``_Entry`` in cache.py, ``DmaBuffer``/``LandingBuffer``
via their constructors) whose release path is audited separately.

``buffers.escape`` — a raw mmap returned from a function transfers
ownership invisibly; inside the residency cache no raw slab (``.mm``) may
escape a ``CacheLease`` scope at all.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, Project, SourceFile

__all__ = ["run"]

#: constructors that take ownership of a raw buffer passed to them
_OWNER_SINKS = {"_Entry", "DmaBuffer", "LandingBuffer", "PinnedExtent"}


def _is_mmap_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "mmap"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "mmap")


def _enclosing(parents: Dict[ast.AST, ast.AST], node: ast.AST, kinds):
    cur = parents.get(node)
    while cur is not None and not isinstance(cur, kinds):
        cur = parents.get(cur)
    return cur


def _parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    out: Dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            out[child] = parent
    return out


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _class_releases_attr(cls: ast.ClassDef, attr: str) -> bool:
    """True when some method calls ``self.<attr>.close()`` / ``.release()``
    or hands ``self.<attr>`` to an owner sink."""
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if (isinstance(fn, ast.Attribute)
                and fn.attr in ("close", "release", "munmap")
                and _self_attr(fn.value) == attr):
            return True
        for arg in node.args:
            if _self_attr(arg) == attr and _sink_name(fn) in _OWNER_SINKS:
                return True
    return False


def _sink_name(fn: ast.AST) -> Optional[str]:
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _local_released(func: ast.AST, name: str) -> bool:
    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr in ("close", "release")
                and isinstance(fn.value, ast.Name) and fn.value.id == name):
            return True
        if _sink_name(fn) in _OWNER_SINKS:
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id == name:
                    return True
    return False


def _stored_to_self(func: ast.AST, name: str) -> Optional[str]:
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Name) \
                and node.value.id == name:
            for t in node.targets:
                attr = _self_attr(t)
                if attr:
                    return attr
    return None


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src, tree in project.iter_trees():
        parents = _parent_map(tree)
        for node in ast.walk(tree):
            if not _is_mmap_call(node):
                continue
            parent = parents.get(node)
            # ``with mmap.mmap(...)`` scopes the release
            if isinstance(parent, ast.withitem):
                continue
            func = _enclosing(parents, node,
                              (ast.FunctionDef, ast.AsyncFunctionDef))
            cls = _enclosing(parents, node, ast.ClassDef)
            line = node.lineno
            # direct ``self.X = mmap.mmap(...)``
            attr = None
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    attr = _self_attr(t) or attr
                local = (parent.targets[0].id
                         if isinstance(parent.targets[0], ast.Name) else None)
            else:
                local = None
            if attr is None and local is not None and func is not None:
                attr = _stored_to_self(func, local)
            if attr is not None:
                owner_cls = cls
                if owner_cls is None or not _class_releases_attr(owner_cls, attr):
                    findings.append(Finding(
                        src.relpath, line, "buffers.release",
                        f"mmap stored to self.{attr} but no method of "
                        f"{owner_cls.name if owner_cls else '<module>'} "
                        f"closes it (unreachable release path)"))
                continue
            if local is not None and func is not None:
                if not _local_released(func, local):
                    findings.append(Finding(
                        src.relpath, line, "buffers.release",
                        f"mmap bound to local '{local}' is neither closed "
                        f"in this function nor handed to an owning slab "
                        f"({'/'.join(sorted(_OWNER_SINKS))})"))
                continue
            # returned raw, passed anonymously, or at module level
            if isinstance(parent, ast.Return):
                findings.append(Finding(
                    src.relpath, line, "buffers.escape",
                    "raw mmap returned from function: ownership escapes "
                    "without a release path"))
            elif isinstance(parent, ast.Call) and \
                    _sink_name(parent.func) in _OWNER_SINKS:
                pass
            else:
                findings.append(Finding(
                    src.relpath, line, "buffers.release",
                    "anonymous mmap allocation: no binding to close"))

        # CacheLease scope: raw slab (.mm) must not escape the cache module
        if src.relpath.endswith("cache.py"):
            for node in ast.walk(tree):
                if not isinstance(node, ast.Return) or node.value is None:
                    continue
                for sub in ast.walk(node.value):
                    if (isinstance(sub, ast.Attribute) and sub.attr == "mm"
                            and not (isinstance(sub.value, ast.Name)
                                     and sub.value.id == "self")):
                        findings.append(Finding(
                            src.relpath, node.lineno, "buffers.escape",
                            "raw slab buffer (.mm) escapes the cache via a "
                            "return; only CacheLease may carry slab access"))
    return findings
