"""Rule family ``abi``: native ABI drift between ``csrc/strom_tpu.h`` and
the ctypes bindings.

The header is the source of truth (the reference's kernel UAPI analog).
A tolerant C parser extracts ``#define`` constants, the counter enum
(order is ABI), struct layouts and every ``nstpu_*`` prototype; the
bindings file is AST-parsed for module constants, ``ctypes.Structure``
subclasses and every ``lib.<fn>.argtypes``/``restype`` assignment.  Any
mismatch — missing binding for a pointer/64-bit signature, wrong arg
count, wrong field type, reordered counter, drifted ``#define`` — is a
finding at the binding's line.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding, Project, SourceFile

__all__ = ["run", "parse_header", "check_bindings_source", "HeaderABI"]


# -- C header parsing ------------------------------------------------------

_COMMENT_RE = re.compile(r"/\*.*?\*/|//[^\n]*", re.S)
_DEFINE_RE = re.compile(r"^\s*#define\s+(NSTPU_\w+)\s+(\(?-?\w+\)?)",
                        re.M)
_ENUM_RE = re.compile(r"enum\s*\w*\s*\{(.*?)\}\s*;", re.S)
_STRUCT_RE = re.compile(
    r"(?:typedef\s+)?struct\s+(\w+)\s*\{(.*?)\}\s*(\w*)\s*;", re.S)
_FIELD_RE = re.compile(r"([\w\s]+?)\s*(\**)\s*(\w+)\s*(\[\s*\w+\s*\])?\s*;")
_PROTO_RE = re.compile(
    r"([A-Za-z_][\w\s]*?[\w\*])\s*\**\s*(nstpu_\w+)\s*\(([^)]*)\)\s*;")


@dataclass
class HeaderABI:
    defines: Dict[str, int] = field(default_factory=dict)
    counters: List[str] = field(default_factory=list)     # enum order
    structs: Dict[str, List[Tuple[str, str]]] = field(default_factory=dict)
    protos: Dict[str, Tuple[str, List[str]]] = field(default_factory=dict)


def _canon_ctype(c_type: str, ptr: bool,
                 struct_names: Sequence[str]) -> Optional[str]:
    """Canonical token for a C type (None = unknown, skip checking)."""
    t = " ".join(w for w in c_type.split() if w not in ("const", "struct"))
    if ptr:
        if t == "void":
            return "c_void_p"
        if t == "char":
            return "c_char_p"
        if t in struct_names:
            return f"POINTER({t})"
        inner = _canon_ctype(t, False, struct_names)
        return f"POINTER({inner})" if inner else None
    return {
        "int": "i32", "int32_t": "i32",
        "unsigned": "u32", "uint32_t": "u32", "unsigned int": "u32",
        "int64_t": "i64", "long long": "i64",
        "uint64_t": "u64", "unsigned long long": "u64",
        "size_t": "u64", "void": "void",
    }.get(t)


def parse_header(text: str) -> HeaderABI:
    abi = HeaderABI()
    clean = _COMMENT_RE.sub("", text)
    for name, val in _DEFINE_RE.findall(clean):
        try:
            abi.defines[name] = int(val.strip("()"), 0)
        except ValueError:
            continue
    for body in _ENUM_RE.findall(clean):
        names = []
        for entry in body.split(","):
            entry = entry.split("=")[0].strip()
            if entry:
                names.append(entry)
        if names and names[0].startswith("NSTPU_CTR_"):
            abi.counters = [n[len("NSTPU_CTR_"):].lower() for n in names
                            if not n[len("NSTPU_CTR_"):].startswith("_")]
    struct_names = [m.group(1) for m in _STRUCT_RE.finditer(clean)]
    for m in _STRUCT_RE.finditer(clean):
        fields: List[Tuple[str, str]] = []
        for fm in _FIELD_RE.finditer(m.group(2)):
            ctype, stars, fname, arr = fm.groups()
            canon = _canon_ctype(ctype.strip(), bool(stars), struct_names)
            fields.append((fname, canon or ctype.strip()))
        abi.structs[m.group(1)] = fields
    for m in _PROTO_RE.finditer(clean):
        ret, fn, args = m.groups()
        ret_ptr = "*" in m.group(0).split(fn)[0][len(ret):] or ret.endswith("*")
        ret = ret.rstrip("*").strip()
        arg_types: List[str] = []
        args = args.strip()
        if args and args != "void":
            for a in args.split(","):
                a = a.strip()
                ptr = "*" in a
                toks = a.replace("*", " ").split()
                base = " ".join(toks[:-1]) if len(toks) > 1 else toks[0]
                canon = _canon_ctype(base, ptr, struct_names)
                arg_types.append(canon or base)
        ret_canon = _canon_ctype(ret, ret_ptr, struct_names) or ret
        abi.protos[fn] = (ret_canon, arg_types)
    return abi


# -- bindings parsing ------------------------------------------------------

_CANON_PY = {
    "c_int": "i32", "c_int32": "i32", "c_uint": "u32", "c_uint32": "u32",
    "c_int64": "i64", "c_longlong": "i64",
    "c_uint64": "u64", "c_ulonglong": "u64", "c_size_t": "u64",
    "c_void_p": "c_void_p", "c_char_p": "c_char_p",
    "None": "void",
}


def _canon_py_type(expr: ast.AST) -> Optional[str]:
    src = ast.unparse(expr).replace("ctypes.", "")
    m = re.fullmatch(r"POINTER\((\w+)\)", src)
    if m:
        inner = _CANON_PY.get(m.group(1), m.group(1))
        return f"POINTER({inner})"
    return _CANON_PY.get(src, src)


@dataclass
class _Bindings:
    constants: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    counters: Tuple[List[str], int] = ((), 0)
    structures: Dict[str, Tuple[List[Tuple[str, str]], int]] = \
        field(default_factory=dict)
    argtypes: Dict[str, Tuple[List[str], int]] = field(default_factory=dict)
    restype: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    struct_to_header: Dict[str, str] = field(default_factory=dict)


def _parse_bindings(src: SourceFile) -> Optional[_Bindings]:
    b = _Bindings()
    tree = src.tree
    relevant = False
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and any(
                (isinstance(base, ast.Attribute) and base.attr == "Structure")
                or (isinstance(base, ast.Name) and base.id == "Structure")
                for base in node.bases):
            for stmt in node.body:
                if (isinstance(stmt, ast.Assign)
                        and isinstance(stmt.targets[0], ast.Name)
                        and stmt.targets[0].id == "_fields_"):
                    fields = []
                    for el in stmt.value.elts:
                        fname = el.elts[0].value
                        fields.append((fname, _canon_py_type(el.elts[1])))
                    b.structures[node.name] = (fields, stmt.lineno)
        if not isinstance(node, ast.Assign):
            continue
        tgt = node.targets[0]
        # module constants, incl. tuple unpack (BACKEND_* = 0, 1, 2)
        if isinstance(tgt, ast.Name) and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, int):
            b.constants[tgt.id] = (node.value.value, node.lineno)
        elif isinstance(tgt, ast.Tuple) and isinstance(node.value, ast.Tuple):
            for n, v in zip(tgt.elts, node.value.elts):
                if isinstance(n, ast.Name) and isinstance(v, ast.Constant) \
                        and isinstance(v.value, int):
                    b.constants[n.id] = (v.value, node.lineno)
        if isinstance(tgt, ast.Name) and tgt.id == "NATIVE_COUNTERS" \
                and isinstance(node.value, ast.Tuple):
            names = [e.value for e in node.value.elts
                     if isinstance(e, ast.Constant)]
            b.counters = (names, node.lineno)
            relevant = True
        # lib.<fn>.argtypes / .restype
        if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Attribute):
            fn = tgt.value.attr
            if not fn.startswith("nstpu_"):
                continue
            relevant = True
            if tgt.attr == "argtypes" and isinstance(node.value, ast.List):
                b.argtypes[fn] = ([_canon_py_type(e)
                                   for e in node.value.elts], node.lineno)
            elif tgt.attr == "restype":
                b.restype[fn] = (_canon_py_type(node.value), node.lineno)
    return b if relevant else None


def _needs_explicit(types: Sequence[str], ret: str) -> bool:
    """ctypes defaults (int args / int return) are only safe for pure
    32-bit-int signatures."""
    wide = {"i64", "u64", "c_void_p", "c_char_p"}
    if ret in wide or ret.startswith("POINTER"):
        return True
    return any(t in wide or t.startswith("POINTER") for t in types)


def check_bindings_source(src: SourceFile, abi: HeaderABI) -> List[Finding]:
    """Cross-check one bindings file against a parsed header."""
    b = _parse_bindings(src)
    if b is None:
        return []
    out: List[Finding] = []

    def finding(line: int, msg: str) -> None:
        out.append(Finding(src.relpath, line, "abi.drift", msg))

    # structs: match each ctypes Structure to the header struct with the
    # same field names, then compare types; remember the name map for
    # prototype pointer checks
    for pyname, (fields, line) in b.structures.items():
        names = [f[0] for f in fields]
        match = next((hn for hn, hf in abi.structs.items()
                      if [f[0] for f in hf] == names), None)
        if match is None:
            finding(line, f"ctypes Structure {pyname} matches no header "
                          f"struct (fields {names})")
            continue
        b.struct_to_header[pyname] = match
        for (fname, ptype), (_, htype) in zip(fields, abi.structs[match]):
            if ptype != htype:
                finding(line, f"{pyname}.{fname} is {ptype} but header "
                              f"struct {match} declares {htype}")
    for hname, hfields in abi.structs.items():
        if hname not in b.struct_to_header.values():
            finding(1, f"header struct {hname} has no ctypes Structure "
                       f"binding")

    def map_struct_ptrs(t: str) -> str:
        m = re.fullmatch(r"POINTER\((\w+)\)", t)
        if m and m.group(1) in b.struct_to_header:
            return f"POINTER({b.struct_to_header[m.group(1)]})"
        return t

    # counter enum order
    counters, cline = b.counters
    if abi.counters and counters and list(counters) != abi.counters:
        finding(cline, f"NATIVE_COUNTERS does not match the NSTPU_CTR_ "
                       f"enum order: {list(counters)} != {abi.counters}")

    # module constants against their NSTPU_<name> defines
    for name, (val, line) in b.constants.items():
        want = abi.defines.get(f"NSTPU_{name}")
        if want is not None and want != val:
            finding(line, f"{name} = {val} but header defines "
                          f"NSTPU_{name} = {want}")
    if "NSTPU_API_VERSION" in abi.defines and "API_VERSION" not in b.constants:
        finding(1, "bindings declare no API_VERSION constant to pin "
                   "NSTPU_API_VERSION")

    # prototypes
    for fn, (types, line) in b.argtypes.items():
        proto = abi.protos.get(fn)
        if proto is None:
            finding(line, f"binding for {fn} but the header declares no "
                          f"such function")
            continue
        _, want_args = proto
        if len(types) != len(want_args):
            finding(line, f"{fn} takes {len(want_args)} args in the header "
                          f"but the binding declares {len(types)}")
            continue
        for i, (got, want) in enumerate(zip(types, want_args)):
            if map_struct_ptrs(got) != want:
                finding(line, f"{fn} arg {i} is {got} but the header "
                              f"declares {want}")
    for fn, (got, line) in b.restype.items():
        proto = abi.protos.get(fn)
        if proto is None:
            if fn not in b.argtypes:
                finding(line, f"binding for {fn} but the header declares "
                              f"no such function")
            continue
        want_ret, _ = proto
        if want_ret not in ("i32", "void") and map_struct_ptrs(got) != want_ret:
            finding(line, f"{fn} returns {want_ret} in the header but the "
                          f"binding declares restype {got}")
    # header functions with unsafe-by-default signatures need bindings
    for fn, (ret, args) in abi.protos.items():
        if fn in b.argtypes:
            if ret not in ("i32", "void") and fn not in b.restype:
                finding(b.argtypes[fn][1],
                        f"{fn} returns {ret} but the binding declares no "
                        f"restype (ctypes will truncate to int)")
            continue
        if fn in b.restype and not args:
            continue       # e.g. nstpu_signature(void) with restype only
        if _needs_explicit(args, ret):
            finding(1, f"header function {fn}({', '.join(args)}) -> {ret} "
                       f"has no argtypes binding; ctypes int defaults "
                       f"would corrupt 64-bit/pointer args")
    return out


def run(project: Project) -> List[Finding]:
    if not project.header_text:
        return []
    abi = parse_header(project.header_text)
    findings: List[Finding] = []
    for src, _tree in project.iter_trees():
        findings.extend(check_bindings_source(src, abi))
    return findings
