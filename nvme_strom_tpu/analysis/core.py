"""stromlint core: project model, findings, suppressions, baseline ratchet.

The analyzer is deliberately self-contained (stdlib ``ast`` only) and
discovers its anchor points by CONTENT, not by path: the file that assigns
``STAT_FIELDS`` is the stats surface, any file assigning ``lib.<fn>.argtypes``
is the ctypes binding layer, and so on.  That keeps the rule modules honest
(they cannot special-case a filename) and makes the test fixtures trivial —
a three-line temp package exercises the same code path as the real tree.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding", "SourceFile", "Project", "Baseline", "BaselineError",
    "load_baseline", "apply_baseline", "format_finding",
]

#: inline suppression: ``# stromlint: ignore[rule.id]`` (comma list) or the
#: bare ``# stromlint: ignore`` to silence every rule on that line.  The
#: comment suppresses findings on its own line and, when it is the only
#: thing on the line, on the line below (so multi-line statements can carry
#: a suppression above them).
_SUPPRESS_RE = re.compile(
    r"#\s*stromlint:\s*ignore(?:\[(?P<rules>[\w.,\s-]+)\])?")


@dataclass(frozen=True)
class Finding:
    """One rule violation, formatted as ``file:line rule message``."""
    path: str          # project-relative path
    line: int
    rule: str          # dotted id, e.g. ``locks.lockset``
    message: str

    def sort_key(self) -> Tuple[str, int, str]:
        return (self.path, self.line, self.rule)


def format_finding(f: Finding) -> str:
    return f"{f.path}:{f.line} {f.rule} {f.message}"


class SourceFile:
    """One parsed python file plus its suppression map."""

    def __init__(self, relpath: str, text: str):
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self._tree: Optional[ast.Module] = None
        self._suppress: Optional[Dict[int, Optional[Set[str]]]] = None

    @property
    def tree(self) -> ast.Module:
        if self._tree is None:
            self._tree = ast.parse(self.text, filename=self.relpath)
        return self._tree

    def _suppress_map(self) -> Dict[int, Optional[Set[str]]]:
        """line -> set of suppressed rule ids (None = all rules)."""
        if self._suppress is not None:
            return self._suppress
        out: Dict[int, Optional[Set[str]]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = m.group("rules")
            ids: Optional[Set[str]] = None
            if rules:
                ids = {r.strip() for r in rules.split(",") if r.strip()}
            targets = [i]
            # a standalone suppression comment covers the next line too
            if line.lstrip().startswith("#"):
                targets.append(i + 1)
            for t in targets:
                if t in out and out[t] is not None and ids is not None:
                    out[t] = set(out[t]) | ids
                elif t not in out or ids is None:
                    out[t] = ids if ids is None else set(ids)
        self._suppress = out
        return out

    def is_suppressed(self, line: int, rule: str) -> bool:
        got = self._suppress_map().get(line, False)
        if got is False:
            return False
        if got is None:          # bare ignore
            return True
        family = rule.split(".", 1)[0]
        return rule in got or family in got


class Project:
    """The unit a lint run sees: python sources + the native header +
    prose docs (README/deploy) for the documentation checks."""

    def __init__(self, root: str, py_files: Sequence[SourceFile],
                 header_text: Optional[str] = None,
                 header_path: str = "csrc/strom_tpu.h",
                 doc_texts: Optional[Dict[str, str]] = None):
        self.root = root
        self.py_files = list(py_files)
        self.header_text = header_text
        self.header_path = header_path
        self.doc_texts = dict(doc_texts or {})

    # -- discovery ---------------------------------------------------------
    @classmethod
    def from_root(cls, root: str,
                  package: str = "nvme_strom_tpu") -> "Project":
        pkg_dir = os.path.join(root, package)
        files: List[SourceFile] = []
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root)
                with open(full, "r", encoding="utf-8") as fh:
                    files.append(SourceFile(rel, fh.read()))
        header_text = None
        header_path = os.path.join("csrc", "strom_tpu.h")
        full_header = os.path.join(root, header_path)
        if os.path.exists(full_header):
            with open(full_header, "r", encoding="utf-8") as fh:
                header_text = fh.read()
        docs: Dict[str, str] = {}
        for rel in ("README.md", os.path.join("deploy", "README.md")):
            p = os.path.join(root, rel)
            if os.path.exists(p):
                with open(p, "r", encoding="utf-8") as fh:
                    docs[rel] = fh.read()
        return cls(root, files, header_text=header_text,
                   header_path=header_path, doc_texts=docs)

    def file(self, suffix: str) -> Optional[SourceFile]:
        for f in self.py_files:
            if f.relpath.endswith(suffix):
                return f
        return None

    def iter_trees(self) -> Iterable[Tuple[SourceFile, ast.Module]]:
        for f in self.py_files:
            try:
                yield f, f.tree
            except SyntaxError:
                # surfaced by whoever runs python; not a lint concern
                continue


# -- baseline ratchet ------------------------------------------------------
#
# The baseline is the list of DELIBERATE exemptions, each with a reason.
# The ratchet has two jaws: a finding not covered by the baseline fails the
# run (no silent growth), and a baseline entry matching nothing also fails
# the run (no dead weight hiding future regressions behind a stale entry).

class BaselineError(Exception):
    pass


@dataclass
class Baseline:
    entries: List[dict] = field(default_factory=list)
    path: Optional[str] = None


def load_baseline(path: str) -> Baseline:
    if not os.path.exists(path):
        return Baseline(entries=[], path=path)
    with open(path, "r", encoding="utf-8") as fh:
        raw = json.load(fh)
    entries = raw.get("entries", raw if isinstance(raw, list) else [])
    for e in entries:
        for key in ("rule", "file", "match", "reason"):
            if not e.get(key):
                raise BaselineError(
                    f"baseline entry {e!r} missing required key '{key}' "
                    f"(every exemption needs a reason string)")
    return Baseline(entries=entries, path=path)


def apply_baseline(findings: Sequence[Finding], baseline: Baseline
                   ) -> Tuple[List[Finding], List[dict]]:
    """Returns ``(unsuppressed findings, stale entries)``.  A finding is
    baselined when an entry's rule and file match exactly and its ``match``
    string occurs in the message."""
    used = [False] * len(baseline.entries)
    out: List[Finding] = []
    for f in findings:
        hit = False
        for i, e in enumerate(baseline.entries):
            if (e["rule"] == f.rule and e["file"] == f.path
                    and e["match"] in f.message):
                used[i] = True
                hit = True
        if not hit:
            out.append(f)
    stale = [e for i, e in enumerate(baseline.entries) if not used[i]]
    return out, stale
