"""strom_lint — run every stromlint rule over the package.

Usage: strom_lint [--root DIR] [--baseline FILE] [--rule FAMILY] [--list]

Findings print as ``file:line rule message`` (clickable in editors/CI).
Exit status: 0 clean, 1 findings or stale baseline entries, 2 bad
invocation / unreadable baseline.

Suppression, in precedence order:

* inline ``# stromlint: ignore[rule.id]`` on (or immediately above) the
  offending line — for one-off, self-documenting exemptions;
* the baseline file (default ``stromlint.baseline`` at the root) — the
  checked-in ratchet of deliberate exemptions, each with a reason.  A
  finding NOT in the baseline fails the run; a baseline entry matching
  NO finding also fails the run, so the ratchet can only tighten.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from . import RULE_MODULES
from .core import (BaselineError, Finding, Project, apply_baseline,
                   format_finding, load_baseline)

__all__ = ["main", "run_rules"]


def run_rules(project: Project, families=None) -> List[Finding]:
    """All findings from the selected rule families, inline suppressions
    already applied, sorted for stable output."""
    findings: List[Finding] = []
    by_path = {f.relpath: f for f in project.py_files}
    for family, mod in RULE_MODULES.items():
        if families and family not in families:
            continue
        for f in mod.run(project):
            src = by_path.get(f.path)
            if src is not None and src.is_suppressed(f.line, f.rule):
                continue
            findings.append(f)
    return sorted(dict.fromkeys(findings), key=Finding.sort_key)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="strom_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--root", default=None,
                    help="project root (default: auto-detect from the "
                         "installed package location or cwd)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: ROOT/stromlint.baseline)")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="FAMILY",
                    help="run only this rule family (repeatable): "
                         + ", ".join(sorted(RULE_MODULES)))
    ap.add_argument("--list", action="store_true",
                    help="list rule families and exit")
    args = ap.parse_args(argv)

    if args.list:
        for family, mod in sorted(RULE_MODULES.items()):
            doc = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"{family:<10} {doc}")
        return 0

    root = args.root
    if root is None:
        # package checkout layout: <root>/nvme_strom_tpu/analysis/cli.py
        guess = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        root = guess if os.path.isdir(
            os.path.join(guess, "nvme_strom_tpu")) else os.getcwd()
    if args.rule:
        unknown = set(args.rule) - set(RULE_MODULES)
        if unknown:
            print(f"strom_lint: unknown rule families: "
                  f"{', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    project = Project.from_root(root)
    if not project.py_files:
        print(f"strom_lint: no package sources under {root}",
              file=sys.stderr)
        return 2
    findings = run_rules(project, families=args.rule)

    baseline_path = args.baseline or os.path.join(root, "stromlint.baseline")
    try:
        baseline = load_baseline(baseline_path)
    except (BaselineError, ValueError) as e:
        print(f"strom_lint: bad baseline {baseline_path}: {e}",
              file=sys.stderr)
        return 2
    remaining, stale = apply_baseline(findings, baseline)

    for f in remaining:
        print(format_finding(f))
    for e in stale:
        print(f"{baseline_path}: stale baseline entry "
              f"(rule={e['rule']} file={e['file']} match={e['match']!r}) "
              f"matches no finding — remove it", file=sys.stderr)
    n_base = len(findings) - len(remaining)
    status = "clean" if not remaining and not stale else "FAILED"
    print(f"strom_lint: {len(remaining)} finding(s), {n_base} baselined, "
          f"{len(stale)} stale baseline entr(ies) — {status}",
          file=sys.stderr)
    return 1 if (remaining or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
