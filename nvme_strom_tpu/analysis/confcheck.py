"""Rule family ``config``: configuration and fault-taxonomy hygiene.

``config.unread`` — every ``Var("name", ...)`` registration must have at
least one literal read site (``config.get("name")`` / ``.set("name", ..)``)
outside its own registration; a knob nothing reads is dead weight that
will silently diverge from the code.

``config.undocumented`` — every registered var must be mentioned in
README.md or deploy/README.md so operators can discover it.

``config.errno-taxonomy`` — every errno named in a ``*_ERRNOS`` frozenset
must exist in the :mod:`errno` module, and the set's class token must be
a member of the ``ErrorClass`` enum (so classification and taxonomy can
never drift apart).

``config.bounds`` (the config-bounds rule, ISSUE 18) — every numeric
(int/size/float) Var read by the online autotuner (a literal
``config.get`` site in ``autotune.py``) must declare BOTH ``minval``
and ``maxval``: the controller takes its hard clamp range from the
Var's declared bounds, so an unbounded controlled knob is a knob the
hill-climb may walk to absurdity.  bool/str vars are exempt (they gate
behavior; the climber never steps them).
"""

from __future__ import annotations

import ast
import errno as _errno
from typing import List, Optional, Set, Tuple

from .core import Finding, Project, SourceFile

__all__ = ["run"]


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _registrations(project: Project) -> List[Tuple[SourceFile, int, str]]:
    out = []
    for src, tree in project.iter_trees():
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                    and node.func.id == "Var" and node.args):
                name = _str_const(node.args[0])
                if name is not None:
                    out.append((src, node.lineno, name))
    return out


def _literal_accesses(project: Project) -> Set[str]:
    """Names passed as the literal first argument of any ``.get``/``.set``
    call — the read/write sites the unread check accepts."""
    got: Set[str] = set()
    for _src, tree in project.iter_trees():
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("get", "set") and node.args):
                name = _str_const(node.args[0])
                if name is not None:
                    got.add(name)
    return got


def _check_vars(project: Project, findings: List[Finding]) -> None:
    regs = _registrations(project)
    if not regs:
        return
    accessed = _literal_accesses(project)
    docs = " ".join(project.doc_texts.values())
    for src, line, name in regs:
        if name not in accessed:
            findings.append(Finding(
                src.relpath, line, "config.unread",
                f"config var '{name}' is registered but never read "
                f"(no literal config.get/set site in the package)"))
        if docs and name not in docs:
            findings.append(Finding(
                src.relpath, line, "config.undocumented",
                f"config var '{name}' is not documented in "
                f"{'/'.join(sorted(project.doc_texts))}"))


def _autotune_reads(project: Project) -> Set[str]:
    """Var names read via a literal ``config.get("...")`` inside the
    controller module — the knobs whose declared bounds are load-bearing."""
    got: Set[str] = set()
    for src, tree in project.iter_trees():
        if not src.relpath.endswith("autotune.py"):
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get" and node.args):
                name = _str_const(node.args[0])
                if name is not None:
                    got.add(name)
    return got


def _check_bounds(project: Project, findings: List[Finding]) -> None:
    controlled = _autotune_reads(project)
    if not controlled:
        return
    for src, tree in project.iter_trees():
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "Var" and node.args):
                continue
            name = _str_const(node.args[0])
            if name is None or name not in controlled:
                continue
            kind = _str_const(node.args[2]) if len(node.args) > 2 else None
            if kind not in ("int", "size", "float"):
                continue
            declared = {kw.arg for kw in node.keywords
                        if not (isinstance(kw.value, ast.Constant)
                                and kw.value.value is None)}
            missing = [b for b in ("minval", "maxval")
                       if b not in declared]
            if missing:
                findings.append(Finding(
                    src.relpath, node.lineno, "config.bounds",
                    f"config var '{name}' is read by the autotune "
                    f"controller but declares no {'/'.join(missing)} — "
                    f"the climber clamps to declared bounds, so this "
                    f"knob is unbounded"))


def _error_class_members(project: Project) -> Set[str]:
    for _src, tree in project.iter_trees():
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name == "ErrorClass":
                members: Set[str] = set()
                for stmt in node.body:
                    if isinstance(stmt, ast.Assign):
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                members.add(t.id)
                return members
    return set()


def _check_errnos(project: Project, findings: List[Finding]) -> None:
    classes = _error_class_members(project)
    for src, tree in project.iter_trees():
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign) and node.targets
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id.endswith("_ERRNOS")):
                continue
            set_name = node.targets[0].id
            token = set_name[:-len("_ERRNOS")].lstrip("_")
            if classes and token not in classes:
                findings.append(Finding(
                    src.relpath, node.lineno, "config.errno-taxonomy",
                    f"errno set '{set_name}' names class '{token}' which "
                    f"is not an ErrorClass member"))
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Attribute) \
                        and sub.attr.startswith("E"):
                    if not hasattr(_errno, sub.attr):
                        findings.append(Finding(
                            src.relpath, sub.lineno, "config.errno-taxonomy",
                            f"'{sub.attr}' in {set_name} is not a known "
                            f"errno name"))


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    _check_vars(project, findings)
    _check_bounds(project, findings)
    _check_errnos(project, findings)
    return findings
