"""Rule family ``surface``: the stats/trace export surface is complete.

Every counter the runtime bumps must be declared in ``STAT_FIELDS``
(``surface.undeclared``); every declared ``nr_*``/``bytes_*`` counter must
be renderable by ``tpu_stat`` and the Prometheus surface
(``surface.stat-render``, ``surface.prom-render``); every trace event kind
emitted anywhere must appear in the recorder schema with the right kind,
schema entries must not go stale, and ``*_begin``/``*_end`` span kinds
must pair (``surface.trace-*``); every ``NSTPU_BACKEND_*`` rung in the
native header must appear in both backend legends (``surface.backend``).

Anchors are discovered by content: the file assigning ``STAT_FIELDS`` is
the stats contract, the file defining ``render_prometheus`` is the prom
surface, the file assigning ``EVENT_SCHEMA`` is the recorder schema, and
the file named ``tpu_stat.py`` is the human renderer.  A generic
``for k in sorted(...)`` dump covers every counter; only counters a
renderer special-cases (skips in its generic loop) need explicit
literal coverage.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Project, SourceFile

__all__ = ["run"]

#: stats-object methods whose first (literal) argument is a counter name
_STATS_MUTATORS = {"add", "gauge_set", "gauge_add", "gauge_max"}


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _collect_stat_fields(project: Project
                         ) -> Tuple[Optional[SourceFile], int, Set[str]]:
    for src, tree in project.iter_trees():
        for node in ast.walk(tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "STAT_FIELDS":
                    names = set()
                    if isinstance(value, (ast.Tuple, ast.List)):
                        for el in value.elts:
                            s = _str_const(el)
                            if s:
                                names.add(s)
                    return src, node.lineno, names
    return None, 0, set()


def _string_constants(node: ast.AST) -> Set[str]:
    """Every string literal under ``node``, including f-string fragments."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        s = _str_const(sub)
        if s is not None:
            out.add(s)
    return out


def _covered(field: str, literals: Set[str]) -> bool:
    """A counter is covered by a renderer when its full name appears, or
    when it composes as an f-string prefix (ending ``_``) plus a literal
    suffix, the labeled-series idiom ``f"nr_landing_{path}"``."""
    if field in literals:
        return True
    for p in literals:
        if p.endswith("_") and field.startswith(p) and field[len(p):] in literals:
            return True
    return False


def _has_generic_dump(func_or_tree: ast.AST) -> bool:
    """A ``for k in sorted(...)`` loop renders every counter it is handed."""
    for node in ast.walk(func_or_tree):
        if (isinstance(node, ast.For) and isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "sorted"):
            return True
    return False


def _generic_skip_literals(func: ast.AST) -> Set[str]:
    """String literals tested inside the generic loop's ``continue``
    guards — counters matching one are NOT generically rendered."""
    skips: Set[str] = set()
    for node in ast.walk(func):
        if not (isinstance(node, ast.For) and isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "sorted"):
            continue
        for stmt in ast.walk(node):
            if isinstance(stmt, ast.If) and any(
                    isinstance(s, ast.Continue) for s in stmt.body):
                skips |= _string_constants(stmt.test)
    return skips


def _stats_receiver(fn: ast.AST) -> bool:
    if not isinstance(fn, ast.Attribute):
        return False
    recv = fn.value
    return ((isinstance(recv, ast.Name) and recv.id == "stats")
            or (isinstance(recv, ast.Attribute) and recv.attr == "stats"))


def _check_mutators(project: Project, fields: Set[str],
                    findings: List[Finding]) -> None:
    for src, tree in project.iter_trees():
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and _stats_receiver(node.func) and node.args):
                continue
            name = _str_const(node.args[0])
            if name is None:
                continue
            wanted = []
            if node.func.attr in _STATS_MUTATORS:
                wanted = [name]
            elif node.func.attr == "count_clock":
                wanted = ["nr_" + name, "clk_" + name]
            for w in wanted:
                if w not in fields:
                    findings.append(Finding(
                        src.relpath, node.lineno, "surface.undeclared",
                        f"counter '{w}' bumped via stats.{node.func.attr} "
                        f"but not declared in STAT_FIELDS"))


def _check_renderers(project: Project, fields: Set[str],
                     findings: List[Finding]) -> None:
    scoped = sorted(f for f in fields
                    if (f.startswith("nr_") or f.startswith("bytes_"))
                    and "debug" not in f)
    # tpu_stat: the human surface
    stat_src = project.file("tpu_stat.py")
    if stat_src is not None:
        tree = stat_src.tree
        if not _has_generic_dump(tree):
            lits = _string_constants(tree)
            for f in scoped:
                if not _covered(f, lits):
                    findings.append(Finding(
                        stat_src.relpath, 1, "surface.stat-render",
                        f"counter '{f}' is never rendered by tpu_stat "
                        f"(no generic dump and no literal reference)"))
    # prometheus: the machine surface
    for src, tree in project.iter_trees():
        prom = None
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == "render_prometheus":
                prom = node
                break
        if prom is None:
            continue
        lits = _string_constants(prom)
        generic = _has_generic_dump(prom)
        skips = _generic_skip_literals(prom) if generic else set()
        for f in scoped:
            if generic and not any(s in f for s in skips):
                continue          # the sorted() loop emits it verbatim
            if not _covered(f, lits):
                findings.append(Finding(
                    src.relpath, prom.lineno, "surface.prom-render",
                    f"counter '{f}' is skipped by render_prometheus's "
                    f"generic loop but no labeled series covers it"))
        break


# -- engine backend legend -------------------------------------------------

def _assigned_literals(tree: ast.AST, name: str) -> Optional[Set[str]]:
    """String literals under the value assigned to ``name`` (module
    scope), or None when no such assignment exists."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            tgts, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            tgts, value = [node.target], node.value
        else:
            continue
        for t in tgts:
            if isinstance(t, ast.Name) and t.id == name:
                return _string_constants(value)
    return None


def _check_backends(project: Project, findings: List[Finding]) -> None:
    """Rule ``surface.backend``: every ``NSTPU_BACKEND_*`` rung declared
    in the native header must be rendered by the observability surface —
    its lowercased name in ``_BACKEND_NAMES`` (the ctypes legend feeding
    ``backend_name`` and hence the stats export) AND in tpu_stat's
    ``_BACKENDS`` legend.  A new failover rung cannot ship invisible."""
    if not project.header_text:
        return
    rungs = {m.group(1).lower() for m in re.finditer(
        r"#define\s+NSTPU_BACKEND_(\w+)\b", project.header_text)}
    if not rungs:
        return
    for suffix, legend in (("_native/__init__.py", "_BACKEND_NAMES"),
                           ("tools/tpu_stat.py", "_BACKENDS")):
        src = project.file(suffix)
        if src is None:
            continue
        lits = _assigned_literals(src.tree, legend)
        if lits is None:
            findings.append(Finding(
                src.relpath, 1, "surface.backend",
                f"no {legend} legend found for the NSTPU_BACKEND_* enum "
                f"({project.header_path})"))
            continue
        for rung in sorted(rungs - lits):
            findings.append(Finding(
                src.relpath, 1, "surface.backend",
                f"backend rung '{rung}' (NSTPU_BACKEND_{rung.upper()}, "
                f"{project.header_path}) missing from {legend}"))


# -- trace schema ----------------------------------------------------------

def _collect_schema(project: Project
                    ) -> Tuple[Optional[SourceFile], int, Dict[str, str]]:
    for src, tree in project.iter_trees():
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and node.targets:
                tgt, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt, value = node.target, node.value
            else:
                continue
            if not (isinstance(tgt, ast.Name) and tgt.id == "EVENT_SCHEMA"
                    and isinstance(value, ast.Dict)):
                continue
            schema: Dict[str, str] = {}
            for k, v in zip(value.keys, value.values):
                ks, vs = _str_const(k), _str_const(v)
                if ks is not None and vs is not None:
                    schema[ks] = vs
            return src, node.lineno, schema
    return None, 0, {}


def _collect_emissions(project: Project
                       ) -> List[Tuple[SourceFile, int, str, str]]:
    out = []
    for src, tree in project.iter_trees():
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("span", "instant") and node.args):
                continue
            name = _str_const(node.args[0])
            if name is not None:
                out.append((src, node.lineno, name, node.func.attr))
    return out


def _check_trace(project: Project, findings: List[Finding]) -> None:
    emissions = _collect_emissions(project)
    if not emissions:
        return
    schema_src, schema_line, schema = _collect_schema(project)
    if schema_src is None:
        src, line, name, _ = emissions[0]
        findings.append(Finding(
            src.relpath, line, "surface.trace-schema",
            f"trace event '{name}' emitted but no EVENT_SCHEMA dict "
            f"declares the recorder's event kinds"))
        return
    emitted: Set[str] = set()
    for src, line, name, kind in emissions:
        emitted.add(name)
        want = schema.get(name)
        if want is None:
            findings.append(Finding(
                src.relpath, line, "surface.trace-schema",
                f"trace event '{name}' ({kind}) not in EVENT_SCHEMA"))
        elif want != "any" and want != kind:
            findings.append(Finding(
                src.relpath, line, "surface.trace-kind",
                f"trace event '{name}' emitted as {kind} but EVENT_SCHEMA "
                f"declares it '{want}'"))
    for name in sorted(set(schema) - emitted):
        findings.append(Finding(
            schema_src.relpath, schema_line, "surface.trace-stale",
            f"EVENT_SCHEMA entry '{name}' is never emitted"))
    for name in schema:
        if name.endswith("_begin") and name[:-6] + "_end" not in schema:
            findings.append(Finding(
                schema_src.relpath, schema_line, "surface.trace-pair",
                f"span kind '{name}' has no matching "
                f"'{name[:-6]}_end' in EVENT_SCHEMA"))
        if name.endswith("_end") and name[:-4] + "_begin" not in schema:
            findings.append(Finding(
                schema_src.relpath, schema_line, "surface.trace-pair",
                f"span kind '{name}' has no matching "
                f"'{name[:-4]}_begin' in EVENT_SCHEMA"))


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    src, _line, fields = _collect_stat_fields(project)
    if src is not None:
        _check_mutators(project, fields, findings)
        _check_renderers(project, fields, findings)
    _check_backends(project, findings)
    _check_trace(project, findings)
    return findings
