"""Rule family ``tiers``: the unified extent space owns tier movement.

``tiers.lease`` — ISSUE 20 collapsed ``CacheLease``/``HbmLease``/KV block
pins into one refcounted :class:`~nvme_strom_tpu.tiering.TierLease` and
moved all placement/migration/invalidation behind
``tiering.extent_space``.  Code outside the engine (``tiering.py``) and
its two policy plugins (``cache.py``, ``serving/hbm_tier.py``) must not:

* name the legacy lease classes (``CacheLease``, ``HbmLease``) — new
  consumers take a ``TierLease`` from ``extent_space`` and must not
  depend on which tier produced it;
* drive a tier's movement/invalidation internals directly
  (``lookup``/``fill``/``admit``/``drop``/``yield_up``/
  ``invalidate_extents``/``invalidate_paths``/``promote_hook``/
  ``device_tier`` on ``residency_cache``/``hbm_tier``) — that bypasses
  the one migration engine and its counters/instants.

Read-only surfaces (``active``, ``peek``, ``resident_*``, ``scrub_*``,
``clear``, ``configure``, ``source_key``, accounting getters) stay open:
gates, the autotuner and the scrubber observe tiers without moving data.
Existing violations ride the ``stromlint.baseline`` ratchet.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, Project

__all__ = ["run"]

#: modules allowed to touch tier internals: the engine and its plugins
_ALLOWED = {
    "nvme_strom_tpu/tiering.py",
    "nvme_strom_tpu/cache.py",
    "nvme_strom_tpu/serving/hbm_tier.py",
}

#: legacy per-tier lease types (now thin aliases of TierLease)
_LEGACY_LEASES = {"CacheLease", "HbmLease"}

#: receivers that are tier singletons (canonical + conventional aliases)
_TIER_RECEIVERS = {"residency_cache", "hbm_tier", "_rcache", "_hbm_tier",
                   "rc", "ht"}

#: attributes that move bytes or invalidate — extent_space's job
_MOVEMENT_ATTRS = {"lookup", "fill", "admit", "drop", "yield_up",
                   "invalidate_extents", "invalidate_paths",
                   "promote_hook", "device_tier"}


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src, tree in project.iter_trees():
        if src.relpath in _ALLOWED:
            continue
        for node in ast.walk(tree):
            hit = None
            if isinstance(node, ast.Name) and node.id in _LEGACY_LEASES:
                hit = (f"legacy lease type '{node.id}' referenced; take "
                       f"a TierLease from tiering.extent_space instead")
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    if alias.name in _LEGACY_LEASES:
                        hit = (f"legacy lease type '{alias.name}' "
                               f"imported; take a TierLease from "
                               f"tiering.extent_space instead")
                        break
            elif (isinstance(node, ast.Attribute)
                    and node.attr in _MOVEMENT_ATTRS
                    and isinstance(node.value, ast.Name)
                    and node.value.id in _TIER_RECEIVERS):
                hit = (f"direct tier internal "
                       f"'{node.value.id}.{node.attr}' outside the "
                       f"unified engine; route through "
                       f"tiering.extent_space")
            if hit is None:
                continue
            line = getattr(node, "lineno", 1)
            if src.is_suppressed(line, "tiers.lease"):
                continue
            findings.append(Finding(src.relpath, line, "tiers.lease", hit))
    findings.sort(key=Finding.sort_key)
    return findings
