"""Rule family ``locks``: lockset discipline + lock-ordering.

``locks.lockset`` — per class, the owning lock of each attribute is derived
from the existing ``with self._lock:`` bodies (the map the ISSUE calls the
per-class ``_lock``→fields map): a lock OWNS an attribute when some method
mutates the attribute while holding it.  Any other mutation of that
attribute outside the lock (excluding ``__init__``, where the object is
thread-private) is a finding — exactly the shape of the PR 7 snapshot race.

``locks.check-then-act`` — for attributes of a lock-owning class that are
never mutated under any lock at all, flag the classic race seed: a method
that tests ``self.attr`` and then assigns it (two threads both pass the
test).  Single-writer designs baseline this with a reason.

``locks.order`` — nested ``with`` acquisitions build a directed
acquired-while-holding graph per class (with one level of private-method
call propagation, so a helper that runs only under a caller's lock inherits
that lockset); a cycle is a deadlock seed.

``locks.swap-order`` — the engine swap lock (``_lane_lock``) must be the
OUTERMOST lock: acquiring it while holding any other instance lock inverts
the swap/member ordering that lane scale-out depends on.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Project, SourceFile

__all__ = ["run"]

_LOCK_CTORS = {"Lock", "RLock", "Condition"}

#: locks that must always be acquired first (no other instance lock held)
_OUTERMOST = {"_lane_lock"}

#: container mutators counted as attribute mutations
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "clear", "update",
    "setdefault", "popitem", "add", "discard", "appendleft", "popleft",
}


def _is_self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _lock_ctor_name(call: ast.AST) -> Optional[str]:
    """'Lock' for threading.Lock() / Lock() / threading.Condition(...)."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    name = None
    if isinstance(fn, ast.Attribute):
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    return name if name in _LOCK_CTORS else None


@dataclass
class _Mutation:
    attr: str
    line: int
    held: frozenset            # canonical lock attr names held
    method: str


@dataclass
class _ClassInfo:
    name: str
    file: SourceFile
    locks: Set[str] = field(default_factory=set)
    alias: Dict[str, str] = field(default_factory=dict)   # cond -> inner lock
    mutations: List[_Mutation] = field(default_factory=list)
    # method -> list of lock-attrs it acquires (top-level, for propagation)
    acquires: Dict[str, Set[str]] = field(default_factory=dict)
    # (holder_lock, acquired_lock, line) edges
    order_edges: List[Tuple[str, str, int]] = field(default_factory=list)
    # method -> list of (callee, heldset, line)
    calls: Dict[str, List[Tuple[str, frozenset, int]]] = field(default_factory=dict)
    # method -> {attr: first line an If-test reads self.attr}
    tested: Dict[str, Dict[str, int]] = field(default_factory=dict)


def _collect_class(cls: ast.ClassDef, src: SourceFile) -> _ClassInfo:
    info = _ClassInfo(name=cls.name, file=src)
    init = next((n for n in cls.body if isinstance(n, ast.FunctionDef)
                 and n.name == "__init__"), None)
    if init is not None:
        for node in ast.walk(init):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                attr = _is_self_attr(node.targets[0])
                ctor = _lock_ctor_name(node.value)
                if attr and ctor:
                    info.locks.add(attr)
                    if ctor == "Condition" and node.value.args:
                        inner = _is_self_attr(node.value.args[0])
                        if inner:
                            info.alias[attr] = inner
    if not info.locks:
        return info

    def canon(lock: str) -> str:
        return info.alias.get(lock, lock)

    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        _walk_method(info, meth.name, meth.body, frozenset(), canon)
        if meth.name != "__init__":
            tested: Dict[str, int] = {}
            for node in ast.walk(meth):
                if isinstance(node, ast.If):
                    for sub in ast.walk(node.test):
                        attr = _is_self_attr(sub)
                        if attr:
                            tested.setdefault(attr, node.lineno)
            if tested:
                info.tested[meth.name] = tested
    return info


def _walk_method(info: _ClassInfo, method: str, stmts, held: frozenset,
                 canon) -> None:
    for stmt in stmts:
        _walk_stmt(info, method, stmt, held, canon)


def _walk_stmt(info: _ClassInfo, method: str, stmt: ast.AST,
               held: frozenset, canon) -> None:
    if isinstance(stmt, ast.With):
        new_held = held
        for item in stmt.items:
            lock = _is_self_attr(item.context_expr)
            if lock and lock in info.locks:
                lock = canon(lock)
                for h in new_held:
                    info.order_edges.append((h, lock, stmt.lineno))
                info.acquires.setdefault(method, set()).add(lock)
                new_held = new_held | {lock}
            else:
                # record expressions inside the context manager too
                _scan_expr(info, method, item.context_expr, held, canon)
        _walk_method(info, method, stmt.body, new_held, canon)
        return
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        # a nested function runs later, on whatever thread calls it: it
        # holds nothing of the enclosing lockset
        _walk_method(info, stmt.name, stmt.body, frozenset(), canon)
        return
    if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        for t in targets:
            base = t
            while isinstance(base, (ast.Subscript, ast.Starred)):
                base = base.value
            if isinstance(base, ast.Tuple):
                for el in base.elts:
                    e = el
                    while isinstance(e, (ast.Subscript, ast.Starred)):
                        e = e.value
                    attr = _is_self_attr(e)
                    if attr:
                        info.mutations.append(
                            _Mutation(attr, t.lineno, held, method))
                continue
            attr = _is_self_attr(base)
            if attr:
                info.mutations.append(_Mutation(attr, t.lineno, held, method))
        val = getattr(stmt, "value", None)
        if val is not None:
            _scan_expr(info, method, val, held, canon)
        return
    if isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            attr = _is_self_attr(base)
            if attr:
                info.mutations.append(_Mutation(attr, t.lineno, held, method))
        return
    # generic statement: scan expressions, then recurse into child bodies
    for fname in ("test", "value", "exc", "iter", "msg"):
        v = getattr(stmt, fname, None)
        if isinstance(v, ast.AST):
            _scan_expr(info, method, v, held, canon)
    for fname in ("body", "orelse", "finalbody", "handlers"):
        body = getattr(stmt, fname, None)
        if body:
            for child in body:
                if isinstance(child, ast.ExceptHandler):
                    _walk_method(info, method, child.body, held, canon)
                else:
                    _walk_stmt(info, method, child, held, canon)


def _scan_expr(info: _ClassInfo, method: str, expr: ast.AST,
               held: frozenset, canon) -> None:
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute):
            # self.attr.mutator(...) counts as a mutation of attr
            recv_attr = _is_self_attr(fn.value)
            if recv_attr and fn.attr in _MUTATORS:
                info.mutations.append(
                    _Mutation(recv_attr, node.lineno, held, method))
            # self.method(...) call for lockset propagation
            if (isinstance(fn.value, ast.Name) and fn.value.id == "self"):
                info.calls.setdefault(method, []).append(
                    (fn.attr, held, node.lineno))


def _propagate(info: _ClassInfo) -> None:
    """Interprocedural lockset propagation to a fixpoint: a private method
    whose EVERY same-class call site holds lock L effectively runs under L,
    including call sites that themselves only hold L by propagation (so a
    helper of a helper still inherits the caller's lockset)."""
    effective: Dict[str, frozenset] = {}
    for _ in range(16):          # fixpoint in <= call-graph depth rounds
        nxt: Dict[str, frozenset] = {}
        sites: Dict[str, List[frozenset]] = {}
        for caller, calls in info.calls.items():
            inherited = effective.get(caller, frozenset())
            for callee, held, _line in calls:
                sites.setdefault(callee, []).append(held | inherited)
        for meth, locksets in sites.items():
            if not meth.startswith("_") or meth.startswith("__"):
                continue
            common = frozenset.intersection(*locksets)
            if common:
                nxt[meth] = common
        if nxt == effective:
            break
        effective = nxt
    if not effective:
        return
    for m in info.mutations:
        extra = effective.get(m.method)
        if extra:
            m.held = m.held | extra
    # call-graph order edges: caller holds L (incl. propagated), callee
    # acquires K  =>  L -> K
    for meth, calls in info.calls.items():
        base = effective.get(meth, frozenset())
        for callee, held, line in calls:
            for h in held | base:
                for k in info.acquires.get(callee, ()):
                    if k != h:
                        info.order_edges.append((h, k, line))
    # propagated methods acquiring further locks also order under the
    # caller's lock
    for meth, extra in effective.items():
        for k in info.acquires.get(meth, ()):
            for h in extra:
                if k != h:
                    info.order_edges.append((h, k, 0))


def _cycles(edges: List[Tuple[str, str, int]]) -> List[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for a, b, _ in edges:
        graph.setdefault(a, set()).add(b)
    seen: Set[str] = set()
    out: List[List[str]] = []
    def dfs(node: str, path: List[str]) -> None:
        if node in path:
            cyc = path[path.index(node):] + [node]
            if sorted(cyc) not in [sorted(c) for c in out]:
                out.append(cyc)
            return
        if node in seen:
            return
        seen.add(node)
        for nxt in graph.get(node, ()):
            dfs(nxt, path + [node])
    for start in list(graph):
        dfs(start, [])
    return out


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src, tree in project.iter_trees():
        for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
            info = _collect_class(cls, src)
            if not info.locks:
                continue
            _propagate(info)
            canon_locks = {info.alias.get(l, l) for l in info.locks}

            # owning-lock map: lock -> attrs mutated under it
            owners: Dict[str, Set[str]] = {}
            for m in info.mutations:
                for lock in m.held:
                    if lock in canon_locks and m.attr not in canon_locks:
                        owners.setdefault(m.attr, set()).add(lock)
            for m in info.mutations:
                if m.method == "__init__" or m.attr not in owners:
                    continue
                own = owners[m.attr]
                if not (m.held & own):
                    lock_names = "/".join(sorted(own))
                    findings.append(Finding(
                        src.relpath, m.line, "locks.lockset",
                        f"{info.name}.{m.attr} is guarded by "
                        f"{lock_names} elsewhere but mutated here "
                        f"(in {m.method}) without it"))

            # check-then-act on never-locked attrs of a locking class:
            # an If-test reads self.X and a later lockless mutation in
            # the same method writes it (two threads both pass the test)
            seen_cta = set()
            for m in info.mutations:
                tested = info.tested.get(m.method, {})
                if (m.held or m.method == "__init__"
                        or m.attr in owners or m.attr in canon_locks
                        or m.attr not in tested
                        or m.line <= tested[m.attr]
                        or (m.attr, m.line) in seen_cta):
                    continue
                seen_cta.add((m.attr, m.line))
                findings.append(Finding(
                    src.relpath, m.line, "locks.check-then-act",
                    f"{info.name}.{m.attr} is tested then assigned in "
                    f"{m.method} without any of the class locks "
                    f"({'/'.join(sorted(canon_locks))}) held"))

            # ordering: cycles
            for cyc in _cycles(info.order_edges):
                findings.append(Finding(
                    src.relpath, cls.lineno, "locks.order",
                    f"{info.name} acquires its locks in a cycle: "
                    f"{' -> '.join(cyc)}"))
            # ordering: swap lock must be outermost
            for holder, acquired, line in info.order_edges:
                if acquired in _OUTERMOST and line:
                    findings.append(Finding(
                        src.relpath, line, "locks.swap-order",
                        f"{info.name} acquires swap lock {acquired} while "
                        f"holding {holder}; the engine swap lock must be "
                        f"outermost"))
    return findings
