"""ctypes bindings for the native async I/O engine (csrc/strom_engine.cc).

Loads ``libstrom_tpu.so`` (building it via ``make -C csrc`` on first use when
a toolchain is present).  The native engine is the performance path: io_uring
submission/completion entirely outside the GIL, with the same task-table
semantics as the Python fallback in :mod:`nvme_strom_tpu.engine`.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import StromError

__all__ = ["NativeEngine", "native_available"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libstrom_tpu.so")
_CSRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "csrc")

BACKEND_AUTO, BACKEND_IO_URING, BACKEND_THREADPOOL = 0, 1, 2
BACKEND_NVME_PASSTHRU = 3
_BACKEND_NAMES = {BACKEND_AUTO: "auto",
                  BACKEND_IO_URING: "io_uring",
                  BACKEND_THREADPOOL: "threadpool",
                  BACKEND_NVME_PASSTHRU: "nvme_passthru"}

#: nstpu_passthru_probe() / nstpu_engine_passthru_reason() refusal
#: reasons (negative), keyed by the counter suffix Session uses to count
#: why the ladder fell (NSTPU_PASSTHRU_* in csrc/strom_tpu.h)
PASSTHRU_REASONS = {-1: "disabled", -2: "nodev", -3: "nouring",
                    -4: "nouringcmd", -5: "lbafmt"}

#: NSTPU_API_VERSION — the header contract these bindings mirror.  A
#: loaded .so reporting a different nstpu_engine_version() is a stale
#: build (strom_check diagnoses this at startup; stromlint's abi.drift
#: rule keeps the constant itself honest against csrc/strom_tpu.h).
API_VERSION = 4

# counter order must match enum NSTPU_CTR_* in csrc/strom_tpu.h
NATIVE_COUNTERS = (
    "nr_submit_dma", "clk_submit_dma",
    "nr_ssd2dev", "clk_ssd2dev",
    "nr_wait_dtask", "clk_wait_dtask",
    "nr_wrong_wakeup",
    "total_dma_length",
    "cur_dma_count",
    "max_dma_count",
    "nr_resubmit",
    "nr_sq_full",
    "nr_write_dma",
    "total_write_length",
    "nr_fixed_dma",
    "nr_enter_dma",
    # appended in API v1 (PR 4): queue-occupancy integral.  Older .so
    # builds return fewer entries from nstpu_engine_stats; stats() simply
    # omits the missing tail, so the binding stays compatible both ways.
    "occ_integral_ns",
    "occ_busy_ns",
    # appended in API v4 (PR 19): requests submitted as raw NVMe READ
    # commands over the io_uring passthrough rung
    "nr_passthru_dma",
)

#: log2-ns latency histogram depth — must match kNstpuLatBuckets in
#: csrc/strom_engine.cc and stats.LAT_HIST_BUCKETS
LAT_HIST_BUCKETS = 64

REQ_WRITE = 0x1        # NSTPU_REQ_WRITE
REQ_PASSTHRU = 0x2     # NSTPU_REQ_PASSTHRU: file_off is a DEVICE byte offset
REQ_MEMBER_SHIFT = 8   # NSTPU_REQ_MEMBER_SHIFT
MAX_MEMBERS = 64       # NSTPU_MAX_MEMBERS


class _Req(ctypes.Structure):
    _fields_ = [("fd", ctypes.c_int32), ("flags", ctypes.c_int32),
                ("file_off", ctypes.c_uint64), ("len", ctypes.c_uint64),
                ("dest_off", ctypes.c_uint64)]


class _TraceEvent(ctypes.Structure):
    # must match nstpu_trace_event in csrc/strom_tpu.h (API v3)
    _fields_ = [("submit_ns", ctypes.c_uint64),
                ("complete_ns", ctypes.c_uint64),
                ("file_off", ctypes.c_uint64), ("len", ctypes.c_uint64),
                ("member", ctypes.c_uint32), ("lane", ctypes.c_uint32),
                ("result", ctypes.c_int32), ("seq", ctypes.c_uint32)]


#: drain batch size — matches NSTPU_TRACE_RING_EVENTS so one call can
#: empty a full lane ring
TRACE_RING_EVENTS = 4096


_lib = None
_lib_lock = threading.Lock()
_load_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_SO):
            try:
                subprocess.run(["make", "-C", _CSRC], check=True,
                               capture_output=True, timeout=120)
            except Exception:
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _load_failed = True
            return None
        lib.nstpu_engine_create.restype = ctypes.c_uint64
        lib.nstpu_engine_create.argtypes = [ctypes.c_int, ctypes.c_int]
        try:
            lib.nstpu_engine_create2.restype = ctypes.c_uint64
            lib.nstpu_engine_create2.argtypes = [ctypes.c_int, ctypes.c_int,
                                                 ctypes.c_int]
        except AttributeError:  # pragma: no cover - older .so
            pass
        lib.nstpu_engine_destroy.argtypes = [ctypes.c_uint64]
        lib.nstpu_engine_backend.argtypes = [ctypes.c_uint64]
        lib.nstpu_submit.restype = ctypes.c_int64
        lib.nstpu_submit.argtypes = [ctypes.c_uint64, ctypes.c_void_p,
                                     ctypes.POINTER(_Req), ctypes.c_int32]
        lib.nstpu_wait.argtypes = [ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64]
        lib.nstpu_pending.argtypes = [ctypes.c_uint64,
                                      ctypes.POINTER(ctypes.c_int64), ctypes.c_int32]
        lib.nstpu_engine_reap.argtypes = [ctypes.c_uint64,
                                          ctypes.POINTER(ctypes.c_int64),
                                          ctypes.c_int32, ctypes.c_int64]
        lib.nstpu_engine_stats.argtypes = [ctypes.c_uint64,
                                           ctypes.POINTER(ctypes.c_uint64),
                                           ctypes.c_int32]
        try:
            lib.nstpu_engine_member_stats.argtypes = [
                ctypes.c_uint64, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_uint64)]
        except AttributeError:  # pragma: no cover - older .so
            pass
        try:
            lib.nstpu_signature.restype = ctypes.c_char_p
        except AttributeError:  # pragma: no cover - older .so
            pass
        try:
            lib.nstpu_buf_register.argtypes = [ctypes.c_uint64,
                                               ctypes.c_void_p,
                                               ctypes.c_uint64]
            lib.nstpu_buf_unregister.argtypes = [ctypes.c_uint64,
                                                 ctypes.c_int32]
        except AttributeError:  # pragma: no cover - older .so
            pass
        try:
            lib.nstpu_engine_lat_hist.argtypes = [
                ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_int32]
        except AttributeError:  # pragma: no cover - older .so
            pass
        try:  # API v2: lane topology + per-member hist/occupancy
            lib.nstpu_engine_nlanes.argtypes = [ctypes.c_uint64]
            lib.nstpu_engine_lane_pin.argtypes = [
                ctypes.c_uint64, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
            lib.nstpu_engine_member_lat_hist.argtypes = [
                ctypes.c_uint64, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_int32]
            lib.nstpu_engine_member_occ.argtypes = [
                ctypes.c_uint64, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_uint64)]
        except AttributeError:  # pragma: no cover - older .so
            pass
        try:  # API v3: flight-recorder event ring
            lib.nstpu_engine_trace.argtypes = [ctypes.c_uint64, ctypes.c_int]
            lib.nstpu_engine_trace_drain.argtypes = [
                ctypes.c_uint64, ctypes.POINTER(_TraceEvent), ctypes.c_int32]
        except AttributeError:  # pragma: no cover - older .so
            pass
        try:  # API v4: NVMe passthrough rung
            lib.nstpu_engine_create3.restype = ctypes.c_uint64
            lib.nstpu_engine_create3.argtypes = [ctypes.c_int, ctypes.c_int,
                                                 ctypes.c_int,
                                                 ctypes.c_char_p]
            lib.nstpu_passthru_probe.argtypes = [ctypes.c_char_p]
            lib.nstpu_engine_passthru_reason.argtypes = [ctypes.c_uint64]
        except AttributeError:  # pragma: no cover - older .so
            pass
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def native_api_version() -> Optional[int]:
    """ABI version the loaded .so reports, or None when unavailable.
    Compared against :data:`API_VERSION` by strom_check's abi probe."""
    lib = _load()
    if lib is None:
        return None
    try:
        return int(lib.nstpu_engine_version())
    except Exception:
        return None


def passthru_probe(dev_path: Optional[str]) -> Optional[int]:
    """Capability-probe one NVMe char device for the passthrough rung.

    Returns the device's LBA shift (>= 9) when every rung of the probe
    passes, a negative ``NSTPU_PASSTHRU_*`` refusal reason when it does
    not (see :data:`PASSTHRU_REASONS`), or None when the .so is missing
    or predates API v4."""
    lib = _load()
    if lib is None or not hasattr(lib, "nstpu_passthru_probe"):
        return None
    dev = dev_path.encode() if dev_path else None
    return int(lib.nstpu_passthru_probe(dev))


def native_signature() -> Optional[str]:
    """Build signature of the loaded .so (the /proc/nvme-strom
    version-read analog), or None when the native engine is unavailable."""
    lib = _load()
    if lib is None:
        return None
    try:
        return lib.nstpu_signature().decode()
    except AttributeError:
        return f"strom_tpu native engine api v{lib.nstpu_engine_version()}"


class NativeEngine:
    """One native engine instance (the 'loaded kernel module' analog)."""

    def __init__(self, backend: str = "auto", queue_depth: int = 32,
                 rings: int = 0, passthru_dev: Optional[str] = None):
        lib = _load()
        if lib is None:
            raise StromError(38, "native engine unavailable (libstrom_tpu.so)")  # ENOSYS
        want = {"auto": BACKEND_AUTO, "io_uring": BACKEND_IO_URING,
                "threadpool": BACKEND_THREADPOOL,
                "nvme_passthru": BACKEND_NVME_PASSTHRU}[backend]
        self._lib = lib
        if hasattr(lib, "nstpu_engine_create3") and (
                passthru_dev or want in (BACKEND_AUTO,
                                         BACKEND_NVME_PASSTHRU)):
            self._h = lib.nstpu_engine_create3(
                want, queue_depth, rings,
                passthru_dev.encode() if passthru_dev else None)
        elif rings > 0 and hasattr(lib, "nstpu_engine_create2"):
            self._h = lib.nstpu_engine_create2(want, queue_depth, rings)
        else:
            self._h = lib.nstpu_engine_create(want, queue_depth)
        if not self._h:
            raise StromError(5, f"native engine init failed (backend={backend})")
        self.backend_name = _BACKEND_NAMES.get(
            lib.nstpu_engine_backend(self._h), "unknown")
        self._prev_stats: Dict[str, int] = {}
        self._prev_members: Dict[int, Tuple[int, int, int]] = {}
        self._prev_hist: List[int] = [0] * LAT_HIST_BUCKETS
        self._prev_member_hist: Dict[int, List[int]] = {}
        self._prev_member_occ: Dict[int, Tuple[int, int]] = {}
        self._stats_lock = threading.Lock()

    def submit(self, dest_addr: int,
               reqs: Sequence[Tuple[int, int, int, int]], *,
               write: bool = False,
               members: Optional[Sequence[int]] = None,
               passthru: Optional[Sequence[bool]] = None) -> int:
        """Submit one task of (fd, file_off, len, dest_off) requests.

        ``write=True`` reverses direction for the whole task: the buffer
        span at dest_off is WRITTEN to the fd (the GIL-free RAM2SSD leg
        the read-only reference lacked).  ``members[i]`` attributes request
        *i* to a stripe member for per-member accounting.  ``passthru[i]``
        marks request *i* as a raw NVMe READ: its file_off is a DEVICE
        byte offset (blockmap-resolved) and its fd is ignored — only valid
        on the nvme_passthru backend, refused whole-submit otherwise."""
        arr = (_Req * len(reqs))()
        base_flags = REQ_WRITE if write else 0
        for i, (fd, off, ln, doff) in enumerate(reqs):
            arr[i].fd = fd
            m = members[i] if members is not None else 0
            pt = REQ_PASSTHRU if (passthru is not None and passthru[i]) else 0
            arr[i].flags = base_flags | pt | (min(max(m, 0), MAX_MEMBERS - 1)
                                              << REQ_MEMBER_SHIFT)
            arr[i].file_off = off
            arr[i].len = ln
            arr[i].dest_off = doff
        tid = self._lib.nstpu_submit(self._h, ctypes.c_void_p(dest_addr),
                                     arr, len(reqs))
        if tid < 0:
            raise StromError(-tid, f"native submit failed ({-tid})")
        return tid

    def buf_register(self, addr: int, length: int) -> Optional[int]:
        """Register a pinned region as an io_uring fixed buffer (the
        PRP-list-pool analog, kmod/nvme_strom.c:912-936).  Returns the
        slot, or None when unsupported/full — callers just lose the fast
        path, never correctness.  The region must stay mapped until
        :meth:`buf_unregister` (or engine close)."""
        if not hasattr(self._lib, "nstpu_buf_register"):
            return None
        slot = self._lib.nstpu_buf_register(self._h, ctypes.c_void_p(addr),
                                            ctypes.c_uint64(length))
        return slot if slot >= 0 else None

    def buf_unregister(self, slot: int) -> None:
        if hasattr(self._lib, "nstpu_buf_unregister") and self._h:
            self._lib.nstpu_buf_unregister(self._h, slot)

    def passthru_reason(self) -> Optional[int]:
        """Why the passthrough rung is (in)active: 0 when nvme_passthru IS
        the backend, a negative ``NSTPU_PASSTHRU_*`` refusal reason when
        the ladder fell past it, or None on a pre-v4 .so."""
        if not hasattr(self._lib, "nstpu_engine_passthru_reason"):
            return None
        return int(self._lib.nstpu_engine_passthru_reason(self._h))

    def nlanes(self) -> int:
        """Lane (queue-pair) count of this engine, 1 on an older .so."""
        if not hasattr(self._lib, "nstpu_engine_nlanes"):
            return 1
        n = self._lib.nstpu_engine_nlanes(self._h)
        return n if n > 0 else 1

    def lane_pin(self, lane: int, cpus: Sequence[int]) -> bool:
        """Pin one lane's reaper/worker threads to the given CPUs (the
        NUMA-locality lever).  Returns True on success; False covers an
        older .so, a bad lane, or a kernel that refuses the affinity —
        callers lose only locality, never correctness."""
        if not hasattr(self._lib, "nstpu_engine_lane_pin") or not cpus:
            return False
        arr = (ctypes.c_int32 * len(cpus))(*cpus)
        return self._lib.nstpu_engine_lane_pin(self._h, lane, arr,
                                               len(cpus)) == 0

    def member_stats(self, member: int) -> Tuple[int, int, int]:
        """(completed requests, bytes, busy ns) for one stripe member."""
        out = (ctypes.c_uint64 * 3)()
        rc = self._lib.nstpu_engine_member_stats(self._h, member, out)
        if rc < 0:
            raise StromError(-rc, f"member_stats({member}) failed")
        return out[0], out[1], out[2]

    def wait(self, task_id: int, timeout_ms: int = -1) -> None:
        rc = self._lib.nstpu_wait(self._h, task_id, timeout_ms)
        if rc < 0:
            raise StromError(-rc, f"native task {task_id} failed ({-rc})")

    def pending(self, cap: int = 4096) -> List[int]:
        out = (ctypes.c_int64 * cap)()
        n = self._lib.nstpu_pending(self._h, out, cap)
        if n < 0:
            raise StromError(-n, "native pending failed")
        return list(out[:min(n, cap)])

    def reap(self, timeout_ms: int = 30000, cap: int = 4096) -> List[int]:
        out = (ctypes.c_int64 * cap)()
        n = self._lib.nstpu_engine_reap(self._h, out, cap, timeout_ms)
        if n < 0:
            raise StromError(-n, "native reap failed")
        return list(out[:min(n, cap)])

    def stats(self) -> Dict[str, int]:
        out = (ctypes.c_uint64 * len(NATIVE_COUNTERS))()
        n = self._lib.nstpu_engine_stats(self._h, out, len(NATIVE_COUNTERS))
        return {NATIVE_COUNTERS[i]: out[i] for i in range(max(n, 0))}

    def stats_delta(self) -> Dict[str, int]:
        """Counters since the previous call (gauges passed through).
        Serialized: concurrent callers must not double-count a delta."""
        with self._stats_lock:
            cur = self.stats()
            prev, self._prev_stats = self._prev_stats, dict(cur)
            out = {}
            for k, v in cur.items():
                if k in ("cur_dma_count", "max_dma_count"):
                    out[k] = v
                else:
                    out[k] = v - prev.get(k, 0)
            return out

    def lat_hist(self) -> Optional[List[int]]:
        """Absolute per-request service-latency histogram (log2-ns
        buckets), or None on an older .so without the export."""
        if not hasattr(self._lib, "nstpu_engine_lat_hist"):
            return None
        out = (ctypes.c_uint64 * LAT_HIST_BUCKETS)()
        n = self._lib.nstpu_engine_lat_hist(self._h, out, LAT_HIST_BUCKETS)
        if n < 0:
            return None
        return list(out[:min(n, LAT_HIST_BUCKETS)])

    def lat_hist_delta(self) -> Optional[List[int]]:
        """Histogram bucket deltas since the previous call (serialized
        like stats_delta so concurrent folders never double-count)."""
        with self._stats_lock:
            cur = self.lat_hist()
            if cur is None:
                return None
            cur += [0] * (LAT_HIST_BUCKETS - len(cur))
            prev, self._prev_hist = self._prev_hist, list(cur)
            return [c - p for c, p in zip(cur, prev)]

    def member_lat_hist(self, member: int) -> Optional[List[int]]:
        """Absolute per-member latency histogram, or None (older .so)."""
        if not hasattr(self._lib, "nstpu_engine_member_lat_hist"):
            return None
        out = (ctypes.c_uint64 * LAT_HIST_BUCKETS)()
        n = self._lib.nstpu_engine_member_lat_hist(self._h, member, out,
                                                   LAT_HIST_BUCKETS)
        if n < 0:
            return None
        return list(out[:min(n, LAT_HIST_BUCKETS)])

    def member_lat_hist_delta(self, members: Sequence[int]
                              ) -> Dict[int, List[int]]:
        """Per-member histogram bucket deltas since the previous call
        (serialized like stats_delta).  Members with no new completions
        are omitted."""
        if not hasattr(self._lib, "nstpu_engine_member_lat_hist"):
            return {}
        with self._stats_lock:
            out: Dict[int, List[int]] = {}
            for m in sorted({min(max(m, 0), MAX_MEMBERS - 1)
                             for m in members}):
                cur = self.member_lat_hist(m)
                if cur is None:
                    continue
                cur += [0] * (LAT_HIST_BUCKETS - len(cur))
                prev = self._prev_member_hist.get(m, [0] * LAT_HIST_BUCKETS)
                delta = [c - p for c, p in zip(cur, prev)]
                if any(delta):
                    out[m] = delta
                    self._prev_member_hist[m] = cur
            return out

    def member_occ(self, member: int) -> Optional[Tuple[int, int]]:
        """Monotonic (occ_integral_ns, occ_busy_ns) for one member, or
        None on an older .so."""
        if not hasattr(self._lib, "nstpu_engine_member_occ"):
            return None
        out = (ctypes.c_uint64 * 2)()
        if self._lib.nstpu_engine_member_occ(self._h, member, out) < 0:
            return None
        return out[0], out[1]

    def member_occ_delta(self, members: Sequence[int]
                         ) -> Dict[int, Tuple[int, int]]:
        """Per-member (occ_integral_ns, occ_busy_ns) deltas since the
        previous call (serialized like stats_delta)."""
        if not hasattr(self._lib, "nstpu_engine_member_occ"):
            return {}
        with self._stats_lock:
            out: Dict[int, Tuple[int, int]] = {}
            for m in sorted({min(max(m, 0), MAX_MEMBERS - 1)
                             for m in members}):
                cur = self.member_occ(m)
                if cur is None:
                    continue
                prev = self._prev_member_occ.get(m, (0, 0))
                if cur != prev:
                    out[m] = (cur[0] - prev[0], cur[1] - prev[1])
                    self._prev_member_occ[m] = cur
            return out

    def trace_enable(self, on: bool = True) -> bool:
        """Turn the native flight-recorder ring on/off.  Returns the
        PREVIOUS state; False also covers an older .so without the export
        (callers lose only native spans, never correctness)."""
        if not hasattr(self._lib, "nstpu_engine_trace"):
            return False
        return self._lib.nstpu_engine_trace(self._h, 1 if on else 0) > 0

    def trace_drain(self, cap: int = TRACE_RING_EVENTS) -> List[Dict[str, int]]:
        """Drain recorded device events (oldest first per lane); [] on an
        older .so.  Each dict carries the measured submit->complete window
        in CLOCK_MONOTONIC ns — the same domain as time.monotonic_ns()."""
        if not hasattr(self._lib, "nstpu_engine_trace_drain") or not self._h:
            return []
        out = (_TraceEvent * cap)()
        n = self._lib.nstpu_engine_trace_drain(self._h, out, cap)
        if n <= 0:
            return []
        return [{"submit_ns": e.submit_ns, "complete_ns": e.complete_ns,
                 "file_off": e.file_off, "len": e.len, "member": e.member,
                 "lane": e.lane, "result": e.result, "seq": e.seq}
                for e in out[:min(n, cap)]]

    def member_stats_delta(self, members: Sequence[int]) -> Dict[int, Tuple[int, int, int]]:
        """Per-member (nreq, bytes, ns) deltas since the previous call,
        for the given member indices.  Serialized like stats_delta.
        Indices clamp to the engine's member table the same way submit()
        clamps them, so callers may pass raw source indices."""
        if not hasattr(self._lib, "nstpu_engine_member_stats"):
            return {}  # older .so without per-member accounting
        with self._stats_lock:
            out: Dict[int, Tuple[int, int, int]] = {}
            for m in sorted({min(max(m, 0), MAX_MEMBERS - 1)
                             for m in members}):
                cur = self.member_stats(m)
                prev = self._prev_members.get(m, (0, 0, 0))
                if cur != prev:
                    out[m] = tuple(c - p for c, p in zip(cur, prev))
                    self._prev_members[m] = cur
            return out

    def close(self) -> None:
        # swap the handle out under the lock so two racing closers (user
        # close vs __del__ on another thread) cannot double-destroy
        with self._stats_lock:
            h, self._h = self._h, 0
        if h:
            self._lib.nstpu_engine_destroy(h)

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
