"""stromd thin client: the engine-shaped API over the daemon socket.

:class:`DaemonSession` mirrors the in-process engine Session's command
surface — ``alloc_dma_buffer`` / ``open_source`` / ``memcpy_ssd2ram`` /
``memcpy_wait`` / ``unmap_buffer`` / ``stat_info`` — so callers written
against the engine (``ssd2ram_test``, ``ssd2tpu_test``, the scan path)
run unmodified against a shared daemon: swap the constructor, keep the
loop.

Destination memory is genuinely shared, not copied: ``alloc_dma_buffer``
backs the buffer with ``memfd_create`` pages, ships the descriptor to the
daemon via SCM_RIGHTS, and the daemon registers its own mapping of the
SAME pages with the engine — DMA completions appear in :meth:`DaemonBuffer
.view` with zero socket traffic (the MAP_GPU_MEMORY handle-passing analog).

This module stays import-light on purpose (no engine, no jax, no numpy):
a subprocess client in the SIGKILL-reap test must start in milliseconds,
and a monitoring tool must not drag the whole engine in to ping a socket.
"""

from __future__ import annotations

import errno as _errno
import mmap
import os
import socket
import threading
from typing import List, Optional, Tuple

from ..api import MemCopyResult, StatInfo, StromError
from ..config import config
from .protocol import PROTOCOL_VERSION, Framer, default_socket_path, send_msg

__all__ = ["DaemonBuffer", "DaemonSource", "DaemonSession"]


class DaemonBuffer:
    """Client-side shared DMA destination: memfd pages both processes map.

    ``view()`` exposes the bytes the daemon's engine lands into; ``close``
    is idempotent and the session closes any still-registered buffers on
    teardown, so leak-free either way."""

    def __init__(self, length: int):
        if length <= 0:
            raise StromError(_errno.EINVAL, f"bad buffer length {length}")
        self.length = int(length)
        self._fd = os.memfd_create("strom-daemon-buf")
        try:
            os.ftruncate(self._fd, self.length)
            self._mm = mmap.mmap(self._fd, self.length)
        except BaseException:
            os.close(self._fd)
            raise
        self._open = True

    def fileno(self) -> int:
        return self._fd

    def view(self) -> memoryview:
        return memoryview(self._mm)

    def close(self) -> None:
        if not self._open:
            return
        self._open = False
        try:
            self._mm.close()
        except BufferError:
            pass    # live view()s pin the mapping; it unmaps when they die
        try:
            os.close(self._fd)
        except OSError:
            pass


class DaemonSource:
    """Handle to a source the daemon opened on this session's behalf.
    The opening spec rides along so :meth:`DaemonSession.reattach` can
    re-open it against a restarted daemon (``handle`` is updated in
    place — callers keep using the same object)."""

    def __init__(self, sess: "DaemonSession", handle: int, size: int,
                 spec=None, kw=None):
        self._sess = sess
        self.handle = handle
        self.size = int(size)
        self._spec = spec
        self._kw = dict(kw or {})

    def close(self) -> None:
        self._sess._close_source(self.handle)


class DaemonSession:
    """One attached client session.

    Thread-safe the way the engine Session is: one lock serializes the
    socket (request/reply protocol — one RPC in flight per session), and
    submitted tasks are waited via their daemon task id, so a submit-ahead
    /wait-behind pipeline works exactly as against the engine."""

    def __init__(self, socket_path: Optional[str] = None, *,
                 tenant: Optional[str] = None,
                 qos_class: Optional[str] = None,
                 weight: Optional[float] = None,
                 rate: Optional[float] = None,
                 timeout: float = 30.0):
        path = socket_path or config.get("daemon_socket") \
            or default_socket_path()
        self._path = path
        self._timeout = timeout
        self._lock = threading.Lock()
        self._closed = False
        self._buffers: dict = {}
        self._server_handle: dict = {}   # caller handle -> current server
        self._sources: dict = {}         # id(src) -> DaemonSource
        self.tenant = tenant or f"pid{os.getpid()}"
        self._qos_class = qos_class
        self._weight = weight
        self._rate = rate
        self.lease: Optional[str] = None
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            self._sock.settimeout(timeout)
            self._sock.connect(path)
            self._framer = Framer(self._sock)
            reply = self._rpc(self._attach_msg())
        except BaseException:
            self._sock.close()
            raise
        self.session_id = int(reply["session"])
        self.lease = reply.get("lease")

    def _attach_msg(self) -> dict:
        attach = {"op": "attach", "version": PROTOCOL_VERSION,
                  "tenant": self.tenant, "pid": os.getpid()}
        if self._qos_class is not None:
            attach["class"] = self._qos_class
        if self._weight is not None:
            attach["weight"] = float(self._weight)
        if self._rate is not None:
            attach["rate"] = float(self._rate)
        if self.lease is not None:
            attach["lease"] = self.lease
        return attach

    def reattach(self, socket_path: Optional[str] = None) -> bool:
        """Reconnect after a dropped connection or daemon restart,
        presenting the lease token from the original attach.  Mapped
        buffers are re-shipped (same memfd pages — the data survives)
        and sources re-opened in place, so caller-held handles keep
        working; returns True when the daemon still knew the lease
        (reconnect) and False when it adopted it fresh (restart —
        replay unacked submits with their ``submit_id``s; dedup makes
        the replay idempotent either way)."""
        if self.lease is None:
            raise StromError(_errno.EINVAL, "no lease to present")
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self._timeout)
        sock.connect(socket_path or self._path)
        with self._lock:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = sock
            self._framer = Framer(sock)
            self._closed = False
        reply = self._rpc(self._attach_msg())
        with self._lock:
            self.session_id = int(reply["session"])
            self.lease = reply.get("lease", self.lease)
            buffers = dict(self._buffers)
        for handle, buf in buffers.items():
            mapped = self._rpc({"op": "map", "length": buf.length},
                               fds=(buf.fileno(),))
            with self._lock:
                self._server_handle[handle] = int(mapped["handle"])
        for src in list(self._sources.values()):
            if src._spec is None:
                continue
            msg = {"op": "open", "spec": src._spec}
            msg.update(src._kw)
            opened = self._rpc(msg)
            src.handle = int(opened["handle"])
        return bool(reply.get("reattach"))

    # -- plumbing -----------------------------------------------------------
    def _rpc(self, msg: dict, fds: Tuple[int, ...] = ()) -> dict:
        with self._lock:
            if self._closed:
                raise StromError(_errno.EBADF, "session closed")
            send_msg(self._sock, msg, fds)
            got = self._framer.recv()
        if got is None:
            raise StromError(_errno.ECONNRESET,
                             "daemon closed the connection")
        reply, stray = got
        for fd in stray:        # this protocol never sends fds back
            os.close(fd)
        if not reply.get("ok"):
            raise StromError(int(reply.get("errno", _errno.EIO)),
                             reply.get("error", "daemon error"))
        return reply

    # -- engine-shaped API --------------------------------------------------
    def ping(self) -> bool:
        return bool(self._rpc({"op": "ping"}).get("pong"))

    def configure(self, *, qos_class: Optional[str] = None,
                  weight: Optional[float] = None,
                  rate: Optional[float] = None) -> dict:
        msg = {"op": "configure"}
        if qos_class is not None:
            msg["class"] = qos_class
        if weight is not None:
            msg["weight"] = float(weight)
        if rate is not None:
            msg["rate"] = float(rate)
        return self._rpc(msg)

    def alloc_dma_buffer(self, length: int, *,
                         numa_node: int = -1) -> Tuple[int, DaemonBuffer]:
        """Engine ``alloc_dma_buffer`` analog: returns (daemon buffer
        handle, shared :class:`DaemonBuffer`).  *numa_node* is accepted
        for signature parity; placement is the daemon's concern."""
        buf = DaemonBuffer(length)
        try:
            reply = self._rpc({"op": "map", "length": buf.length},
                              fds=(buf.fileno(),))
        except BaseException:
            buf.close()
            raise
        handle = int(reply["handle"])
        with self._lock:
            self._buffers[handle] = buf
            self._server_handle[handle] = handle
        return handle, buf

    def unmap_buffer(self, handle: int, *, wait: bool = True,
                     timeout: float = 30.0) -> None:
        with self._lock:
            server = self._server_handle.get(handle, handle)
        self._rpc({"op": "unmap", "handle": int(server)})
        with self._lock:
            buf = self._buffers.pop(handle, None)
            self._server_handle.pop(handle, None)
        if buf is not None:
            buf.close()

    def open_source(self, spec, **kw) -> DaemonSource:
        """Open a source daemon-side.  *spec* is a path/url string (the
        engine ``open_source`` forms) or — against an ``allow_fake``
        daemon — a dict naming the loopback test source."""
        msg = {"op": "open", "spec": spec}
        for k in ("stripe_chunk_size", "segment_size", "mirror"):
            if kw.get(k) is not None:
                msg[k] = kw[k]
        reply = self._rpc(msg)
        src = DaemonSource(self, int(reply["handle"]), reply["size"],
                           spec=spec,
                           kw={k: v for k, v in msg.items()
                               if k not in ("op", "spec")})
        with self._lock:
            self._sources[id(src)] = src
        return src

    def _close_source(self, handle: int) -> None:
        self._rpc({"op": "close_source", "handle": int(handle)})
        with self._lock:
            for key, src in list(self._sources.items()):
                if src.handle == handle:
                    del self._sources[key]
                    break

    def memcpy_ssd2ram(self, source: DaemonSource, buf_handle: int,
                       chunk_ids: List[int], chunk_size: int, *,
                       dest_offset: int = 0, wb_buffer=None,
                       submit_id: Optional[str] = None) -> MemCopyResult:
        """Submit one DMA command through the daemon's QoS queue.

        Returns the submit-time result (task id + preliminary routing,
        like the engine's async submit); :meth:`memcpy_wait` returns the
        authoritative result including the engine's chunk reordering.
        *submit_id* is the idempotency key for replay after
        :meth:`reattach`: resubmitting the same id to a daemon that
        already holds the task returns the live task instead of
        double-running it."""
        ids = [int(c) for c in chunk_ids]
        with self._lock:
            server_buf = self._server_handle.get(int(buf_handle),
                                                 int(buf_handle))
        msg = {"op": "submit", "source": source.handle,
               "buffer": server_buf, "chunk_ids": ids,
               "chunk_size": int(chunk_size),
               "dest_offset": int(dest_offset)}
        if submit_id is not None:
            msg["submit_id"] = str(submit_id)
        reply = self._rpc(msg)
        return MemCopyResult(dma_task_id=int(reply["task_id"]),
                             nr_chunks=len(ids), nr_ssd2dev=len(ids),
                             nr_ram2dev=0, chunk_ids=ids)

    def memcpy_wait(self, task_id: int,
                    timeout: Optional[float] = None) -> MemCopyResult:
        msg = {"op": "wait", "task_id": int(task_id)}
        if timeout is not None:
            msg["timeout"] = float(timeout)
        reply = self._rpc(msg)
        return MemCopyResult(dma_task_id=int(reply["task_id"]),
                             nr_chunks=int(reply["nr_chunks"]),
                             nr_ssd2dev=int(reply["nr_ssd2dev"]),
                             nr_ram2dev=int(reply["nr_ram2dev"]),
                             chunk_ids=[int(c) for c in reply["chunk_ids"]],
                             landing=reply.get("landing", ""))

    def stat_info(self, *, debug: bool = False) -> StatInfo:
        reply = self._rpc({"op": "stat", "debug": debug})
        return StatInfo(version=1, has_debug=debug,
                        timestamp_ns=int(reply["timestamp_ns"]),
                        counters=reply["counters"])

    # -- KV-cache paging (ISSUE 15) -----------------------------------------
    def kv_open(self, spill, *, block_bytes: Optional[int] = None,
                ram_blocks: int = 16, **kw) -> dict:
        """Open (or join) the daemon's shared KV block pool.  *spill* is
        a writable source spec — a path/path-list, or a dict naming a
        fake spill against an ``allow_fake`` daemon."""
        msg = {"op": "kv_open", "spill": spill, "ram_blocks": ram_blocks}
        if block_bytes is not None:
            msg["block_bytes"] = int(block_bytes)
        for k in ("stripe_chunk_size", "segment_size", "mirror"):
            if kw.get(k) is not None:
                msg[k] = kw[k]
        return self._rpc(msg)

    def _kv(self, kv_op: str, **fields) -> dict:
        msg = {"op": "kv", "kv_op": kv_op}
        msg.update({k: v for k, v in fields.items() if v is not None})
        return self._rpc(msg)

    def kv_append(self, seq, data) -> int:
        import base64
        return int(self._kv("append", seq=seq,
                            data=base64.b64encode(bytes(data))
                            .decode("ascii"))["idx"])

    def kv_read(self, seq, idx: int) -> bytes:
        import base64
        return base64.b64decode(self._kv("read", seq=seq,
                                         idx=int(idx))["data"])

    def kv_write(self, seq, idx: int, data) -> None:
        import base64
        self._kv("write", seq=seq, idx=int(idx),
                 data=base64.b64encode(bytes(data)).decode("ascii"))

    def kv_resume(self, seq) -> int:
        return int(self._kv("resume", seq=seq)["paged_in"])

    def kv_release(self, seq) -> None:
        self._kv("release", seq=seq)

    def kv_residency(self) -> dict:
        return self._kv("residency")["residency"]

    def daemon_stat(self, *, debug: bool = False) -> dict:
        """Full daemon scoreboard: counters + per-tenant table + session
        count + queue depth (what ``tpu_stat --daemon`` renders)."""
        return self._rpc({"op": "stat", "debug": debug})

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            bufs, self._buffers = dict(self._buffers), {}
            try:
                send_msg(self._sock, {"op": "detach"})
                self._framer.recv()
            except (OSError, StromError):
                pass            # daemon already gone: nothing to detach
            try:
                self._sock.close()
            except OSError:
                pass
        for buf in bufs.values():
            buf.close()

    def __enter__(self) -> "DaemonSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
