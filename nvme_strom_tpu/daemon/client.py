"""stromd thin client: the engine-shaped API over the daemon socket.

:class:`DaemonSession` mirrors the in-process engine Session's command
surface — ``alloc_dma_buffer`` / ``open_source`` / ``memcpy_ssd2ram`` /
``memcpy_wait`` / ``unmap_buffer`` / ``stat_info`` — so callers written
against the engine (``ssd2ram_test``, ``ssd2tpu_test``, the scan path)
run unmodified against a shared daemon: swap the constructor, keep the
loop.

Destination memory is genuinely shared, not copied: ``alloc_dma_buffer``
backs the buffer with ``memfd_create`` pages, ships the descriptor to the
daemon via SCM_RIGHTS, and the daemon registers its own mapping of the
SAME pages with the engine — DMA completions appear in :meth:`DaemonBuffer
.view` with zero socket traffic (the MAP_GPU_MEMORY handle-passing analog).

This module stays import-light on purpose (no engine, no jax, no numpy):
a subprocess client in the SIGKILL-reap test must start in milliseconds,
and a monitoring tool must not drag the whole engine in to ping a socket.
"""

from __future__ import annotations

import errno as _errno
import mmap
import os
import socket
import threading
from typing import List, Optional, Tuple

from ..api import MemCopyResult, StatInfo, StromError
from ..config import config
from .protocol import PROTOCOL_VERSION, Framer, default_socket_path, send_msg

__all__ = ["DaemonBuffer", "DaemonSource", "DaemonSession"]


class DaemonBuffer:
    """Client-side shared DMA destination: memfd pages both processes map.

    ``view()`` exposes the bytes the daemon's engine lands into; ``close``
    is idempotent and the session closes any still-registered buffers on
    teardown, so leak-free either way."""

    def __init__(self, length: int):
        if length <= 0:
            raise StromError(_errno.EINVAL, f"bad buffer length {length}")
        self.length = int(length)
        self._fd = os.memfd_create("strom-daemon-buf")
        try:
            os.ftruncate(self._fd, self.length)
            self._mm = mmap.mmap(self._fd, self.length)
        except BaseException:
            os.close(self._fd)
            raise
        self._open = True

    def fileno(self) -> int:
        return self._fd

    def view(self) -> memoryview:
        return memoryview(self._mm)

    def close(self) -> None:
        if not self._open:
            return
        self._open = False
        try:
            self._mm.close()
        except BufferError:
            pass    # live view()s pin the mapping; it unmaps when they die
        try:
            os.close(self._fd)
        except OSError:
            pass


class DaemonSource:
    """Handle to a source the daemon opened on this session's behalf."""

    def __init__(self, sess: "DaemonSession", handle: int, size: int):
        self._sess = sess
        self.handle = handle
        self.size = int(size)

    def close(self) -> None:
        self._sess._close_source(self.handle)


class DaemonSession:
    """One attached client session.

    Thread-safe the way the engine Session is: one lock serializes the
    socket (request/reply protocol — one RPC in flight per session), and
    submitted tasks are waited via their daemon task id, so a submit-ahead
    /wait-behind pipeline works exactly as against the engine."""

    def __init__(self, socket_path: Optional[str] = None, *,
                 tenant: Optional[str] = None,
                 qos_class: Optional[str] = None,
                 weight: Optional[float] = None,
                 rate: Optional[float] = None,
                 timeout: float = 30.0):
        path = socket_path or config.get("daemon_socket") \
            or default_socket_path()
        self._lock = threading.Lock()
        self._closed = False
        self._buffers: dict = {}
        self.tenant = tenant or f"pid{os.getpid()}"
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            self._sock.settimeout(timeout)
            self._sock.connect(path)
            self._framer = Framer(self._sock)
            attach = {"op": "attach", "version": PROTOCOL_VERSION,
                      "tenant": self.tenant, "pid": os.getpid()}
            if qos_class is not None:
                attach["class"] = qos_class
            if weight is not None:
                attach["weight"] = float(weight)
            if rate is not None:
                attach["rate"] = float(rate)
            reply = self._rpc(attach)
        except BaseException:
            self._sock.close()
            raise
        self.session_id = int(reply["session"])

    # -- plumbing -----------------------------------------------------------
    def _rpc(self, msg: dict, fds: Tuple[int, ...] = ()) -> dict:
        with self._lock:
            if self._closed:
                raise StromError(_errno.EBADF, "session closed")
            send_msg(self._sock, msg, fds)
            got = self._framer.recv()
        if got is None:
            raise StromError(_errno.ECONNRESET,
                             "daemon closed the connection")
        reply, stray = got
        for fd in stray:        # this protocol never sends fds back
            os.close(fd)
        if not reply.get("ok"):
            raise StromError(int(reply.get("errno", _errno.EIO)),
                             reply.get("error", "daemon error"))
        return reply

    # -- engine-shaped API --------------------------------------------------
    def ping(self) -> bool:
        return bool(self._rpc({"op": "ping"}).get("pong"))

    def configure(self, *, qos_class: Optional[str] = None,
                  weight: Optional[float] = None,
                  rate: Optional[float] = None) -> dict:
        msg = {"op": "configure"}
        if qos_class is not None:
            msg["class"] = qos_class
        if weight is not None:
            msg["weight"] = float(weight)
        if rate is not None:
            msg["rate"] = float(rate)
        return self._rpc(msg)

    def alloc_dma_buffer(self, length: int, *,
                         numa_node: int = -1) -> Tuple[int, DaemonBuffer]:
        """Engine ``alloc_dma_buffer`` analog: returns (daemon buffer
        handle, shared :class:`DaemonBuffer`).  *numa_node* is accepted
        for signature parity; placement is the daemon's concern."""
        buf = DaemonBuffer(length)
        try:
            reply = self._rpc({"op": "map", "length": buf.length},
                              fds=(buf.fileno(),))
        except BaseException:
            buf.close()
            raise
        handle = int(reply["handle"])
        with self._lock:
            self._buffers[handle] = buf
        return handle, buf

    def unmap_buffer(self, handle: int, *, wait: bool = True,
                     timeout: float = 30.0) -> None:
        self._rpc({"op": "unmap", "handle": int(handle)})
        with self._lock:
            buf = self._buffers.pop(handle, None)
        if buf is not None:
            buf.close()

    def open_source(self, spec, **kw) -> DaemonSource:
        """Open a source daemon-side.  *spec* is a path/url string (the
        engine ``open_source`` forms) or — against an ``allow_fake``
        daemon — a dict naming the loopback test source."""
        msg = {"op": "open", "spec": spec}
        for k in ("stripe_chunk_size", "segment_size", "mirror"):
            if kw.get(k) is not None:
                msg[k] = kw[k]
        reply = self._rpc(msg)
        return DaemonSource(self, int(reply["handle"]), reply["size"])

    def _close_source(self, handle: int) -> None:
        self._rpc({"op": "close_source", "handle": int(handle)})

    def memcpy_ssd2ram(self, source: DaemonSource, buf_handle: int,
                       chunk_ids: List[int], chunk_size: int, *,
                       dest_offset: int = 0,
                       wb_buffer=None) -> MemCopyResult:
        """Submit one DMA command through the daemon's QoS queue.

        Returns the submit-time result (task id + preliminary routing,
        like the engine's async submit); :meth:`memcpy_wait` returns the
        authoritative result including the engine's chunk reordering."""
        ids = [int(c) for c in chunk_ids]
        reply = self._rpc({"op": "submit", "source": source.handle,
                           "buffer": int(buf_handle), "chunk_ids": ids,
                           "chunk_size": int(chunk_size),
                           "dest_offset": int(dest_offset)})
        return MemCopyResult(dma_task_id=int(reply["task_id"]),
                             nr_chunks=len(ids), nr_ssd2dev=len(ids),
                             nr_ram2dev=0, chunk_ids=ids)

    def memcpy_wait(self, task_id: int,
                    timeout: Optional[float] = None) -> MemCopyResult:
        msg = {"op": "wait", "task_id": int(task_id)}
        if timeout is not None:
            msg["timeout"] = float(timeout)
        reply = self._rpc(msg)
        return MemCopyResult(dma_task_id=int(reply["task_id"]),
                             nr_chunks=int(reply["nr_chunks"]),
                             nr_ssd2dev=int(reply["nr_ssd2dev"]),
                             nr_ram2dev=int(reply["nr_ram2dev"]),
                             chunk_ids=[int(c) for c in reply["chunk_ids"]],
                             landing=reply.get("landing", ""))

    def stat_info(self, *, debug: bool = False) -> StatInfo:
        reply = self._rpc({"op": "stat", "debug": debug})
        return StatInfo(version=1, has_debug=debug,
                        timestamp_ns=int(reply["timestamp_ns"]),
                        counters=reply["counters"])

    def daemon_stat(self, *, debug: bool = False) -> dict:
        """Full daemon scoreboard: counters + per-tenant table + session
        count + queue depth (what ``tpu_stat --daemon`` renders)."""
        return self._rpc({"op": "stat", "debug": debug})

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            bufs, self._buffers = dict(self._buffers), {}
            try:
                send_msg(self._sock, {"op": "detach"})
                self._framer.recv()
            except (OSError, StromError):
                pass            # daemon already gone: nothing to detach
            try:
                self._sock.close()
            except OSError:
                pass
        for buf in bufs.values():
            buf.close()

    def __enter__(self) -> "DaemonSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
