"""stromd: the shared serving daemon (ISSUE 12).

The reference arbitrates every process's DMA through one
``/proc/nvme-strom`` kernel entry; stromd is that shared-service seam in
userspace — one daemon owns the engine, clients attach over a Unix
socket with explicit session lifecycle, admission control and per-tenant
QoS.

This package namespace stays import-light (protocol + client only): a
subprocess test client or a monitoring tool must not pull the engine —
or jax — in just to talk to a socket.  The server side imports
explicitly: ``from nvme_strom_tpu.daemon.server import StromDaemon``.
"""

from .client import DaemonBuffer, DaemonSession, DaemonSource
from .protocol import PROTOCOL_VERSION, default_socket_path

__all__ = ["DaemonBuffer", "DaemonSession", "DaemonSource",
           "PROTOCOL_VERSION", "default_socket_path"]
