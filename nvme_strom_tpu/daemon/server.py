"""stromd: the shared serving daemon.

The reference is a *shared kernel service*: every process on the host
submits DMA through one ``/proc/nvme-strom`` ioctl entry and the kernel
arbitrates across them.  strom_tpu was a per-process library until this
module — two jobs on one host fought over the same lanes blind to each
other.  :class:`StromDaemon` is the missing arbiter:

* one long-running process owns ONE engine :class:`~nvme_strom_tpu.engine.
  Session` (the lanes, buffers, cache tier and fault ladder);
* clients attach over the Unix socket (``daemon/protocol.py``), get a
  **session handle** with an explicit lifecycle — attach → configure →
  map/open/submit/wait → detach — and share destination memory by
  passing ``memfd`` descriptors the daemon mmaps and registers with the
  engine (DMA lands directly in client-visible pages, no socket copy);
* **admission control** bounds the daemon: max attached sessions, and
  per-tenant in-flight task/byte quotas answered with EAGAIN
  *backpressure* instead of unbounded queueing;
* the **QoS scheduler** (``daemon/qos.py``) orders admitted work by
  priority class, token-bucket shaping and byte-weighted DRR before any
  byte reaches the engine's lanes;
* **orphan reaping**: a client that disconnects without detaching — a
  crash, a SIGKILL — has its queued work cancelled, its in-flight tasks
  drained, its buffer registrations revoked (blocking until engine DMA
  refcounts drain, the pmemmap revocation discipline) and its sources
  closed, so a dead client can never wedge a lane or leak a mapping.

Every hop is attributed: per-tenant counters/quota gauges/queue-wait
histograms in ``stats`` (exported, so ``tpu_stat --daemon`` and the
Prometheus render see them) and ``session_*``/``qos_*``/
``admission_reject`` events in the flight recorder.
"""

from __future__ import annotations

import base64
import errno as _errno
import mmap
import os
import secrets
import socket
import threading
from collections import OrderedDict
from typing import Dict, List, Optional

from ..api import StromError
from ..config import config
from ..stats import stats
from ..trace import recorder as _trace
from .protocol import PROTOCOL_VERSION, Framer, default_socket_path, send_msg
from .qos import QOS_CLASSES, QosScheduler, WorkItem

__all__ = ["StromDaemon"]

#: ops a session may issue after attach
_OPS = ("configure", "map", "unmap", "open", "close_source", "submit",
        "wait", "stat", "ping", "detach", "kv_open", "kv")

#: live lease records kept after an unclean disconnect (the re-attach
#: window) — bounded so a flapping client cannot grow the daemon
_MAX_LEASES = 256
#: per-lease idempotency window: submit_ids remembered for dedup
_MAX_LEASE_SUBMITS = 1024


class _Lease:
    """Session identity that survives the connection (ISSUE 15).

    Attach mints a lease token and returns it; a client that loses its
    connection — or outlives a daemon restart — RE-attaches presenting
    the token and gets its tenant/QoS identity back plus its unacked
    task table, so idempotent resubmission (``submit_id`` dedup) cannot
    double-run work the daemon already holds.  After a daemon restart
    the presented token is unknown; it is adopted as a fresh record
    (single-host trust domain — the socket mode is the privilege
    boundary), which makes the client's replay re-execute, exactly the
    recovery the restart lost."""

    __slots__ = ("token", "tenant", "qos_class", "weight", "submits")

    def __init__(self, token: str, tenant: str, qos_class: str,
                 weight: float) -> None:
        self.token = token
        self.tenant = tenant
        self.qos_class = qos_class
        self.weight = weight
        #: submit_id -> WorkItem (done items keep results until waited)
        self.submits: "OrderedDict[str, WorkItem]" = OrderedDict()

    def remember(self, submit_id: str, item: WorkItem) -> None:
        self.submits[submit_id] = item
        while len(self.submits) > _MAX_LEASE_SUBMITS:
            # oldest acked-or-done first; never drop an in-flight item
            for k, it in self.submits.items():
                if it.done.is_set():
                    del self.submits[k]
                    break
            else:
                break


class _MappedBuffer:
    """A client memfd mapped into the daemon and registered with the
    engine — the MAP_GPU_MEMORY analog: both processes see the same
    pages, so engine DMA lands in client memory with no copy."""

    def __init__(self, fd: int, length: int, engine):
        self._fd = fd
        self._mm = mmap.mmap(fd, length)
        try:
            self.handle = engine.map_buffer(memoryview(self._mm))
        except BaseException:
            self._mm.close()
            raise
        self.length = length

    def release(self, engine, *, timeout: float = 30.0) -> None:
        """Revoke the engine registration (blocking until in-flight DMA
        refcounts drain) and drop the mapping + descriptor."""
        try:
            engine.unmap_buffer(self.handle, wait=True, timeout=timeout)
        except StromError:
            pass                # already unmapped, or drain timed out
        self._mm.close()
        try:
            os.close(self._fd)
        except OSError:
            pass


class _ClientSession:
    """Per-connection state.  Only the connection's handler thread mutates
    the resource tables; cross-thread counters (in-flight quota usage) are
    guarded by the daemon lock."""

    def __init__(self, sid: int, tenant: str, qos_class: str, weight: float,
                 lease: Optional[_Lease] = None):
        self.sid = sid
        self.tenant = tenant
        self.qos_class = qos_class
        self.weight = weight
        self.lease = lease
        self.buffers: Dict[int, _MappedBuffer] = {}
        self.sources: Dict[int, object] = {}
        self.tasks: Dict[int, WorkItem] = {}
        self.inflight_tasks = 0
        self.inflight_bytes = 0
        self.next_handle = 1


class StromDaemon:
    """The stromd server.  ``start()`` binds the socket and spawns the
    accept, per-connection and dispatcher threads; ``close()`` tears the
    whole thing down (reaping every live session).

    ``allow_fake`` additionally accepts dict source specs naming the
    loopback :class:`~nvme_strom_tpu.testing.FakeNvmeSource` — the
    deterministic latency-bound backend the qos-gate and tests schedule
    against; never enable it on a production socket.
    """

    def __init__(self, socket_path: Optional[str] = None, *,
                 allow_fake: bool = False,
                 max_sessions: Optional[int] = None,
                 dispatchers: Optional[int] = None,
                 engine_session=None):
        from .. import engine as _engine_mod
        self.socket_path = socket_path or config.get("daemon_socket") \
            or default_socket_path()
        self._lock = threading.Lock()
        self._allow_fake = allow_fake
        self._max_sessions = int(config.get("daemon_max_sessions")
                                 if max_sessions is None else max_sessions)
        self._quota_tasks = int(config.get("daemon_quota_tasks"))
        self._quota_bytes = int(config.get("daemon_quota_bytes"))
        self._n_dispatch = int(config.get("daemon_dispatch")
                               if dispatchers is None else dispatchers)
        self._default_class = str(config.get("qos_default_class"))
        self._default_weight = float(config.get("qos_default_weight"))
        self._default_rate = int(config.get("qos_rate"))
        self._default_burst = int(config.get("qos_burst"))
        self._own_engine = engine_session is None
        self._engine = (engine_session if engine_session is not None
                        else _engine_mod.Session())
        self._sched = QosScheduler(quantum=int(config.get("qos_quantum")),
                                   on_throttle=self._throttled)
        self._sessions: Dict[int, _ClientSession] = {}
        self._leases: "OrderedDict[str, _Lease]" = OrderedDict()
        self._kv_pool = None
        self._kv_spill = None
        self._next_sid = 0
        self._next_task = 0
        self._sock: Optional[socket.socket] = None
        self._live_conns: Dict[int, socket.socket] = {}
        self._threads: List[threading.Thread] = []
        self._dispatch_threads: List[threading.Thread] = []
        self._started = False
        self._closed = False

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "StromDaemon":
        with self._lock:
            if self._started:
                raise StromError(_errno.EBUSY, "daemon already started")
            self._started = True
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        sock.bind(self.socket_path)
        # owner-only by default: the socket IS the privilege boundary
        # (deploy checklist item 17 widens it deliberately per host)
        os.chmod(self.socket_path, 0o600)
        sock.listen(64)
        self._sock = sock
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="stromd-accept")
        with self._lock:
            self._threads.append(t)
        t.start()
        self.start_dispatchers(self._n_dispatch)
        return self

    def start_dispatchers(self, n: int) -> None:
        """Spawn *n* more dispatcher threads.  ``daemon_dispatch=0`` plus
        a later explicit call is the deterministic-test idiom: stall
        dispatch, queue a known workload, then turn the crank."""
        for _ in range(max(0, int(n))):
            t = threading.Thread(target=self._dispatch_loop, daemon=True,
                                 name="stromd-dispatch")
            with self._lock:
                self._dispatch_threads.append(t)
            t.start()

    def __enter__(self) -> "StromDaemon":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            sids = list(self._sessions)
            threads = list(self._threads) + list(self._dispatch_threads)
            conns = list(self._live_conns.values())
        self._sched.close()
        if self._sock is not None:
            try:
                # shutdown() before close(): close() alone does not wake
                # a thread blocked in accept() on Linux
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        for c in conns:
            # wake handler threads blocked in recv() on still-attached
            # clients so the joins below do not burn their timeout
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        for sid in sids:
            self._release_session(sid, clean=False)
        for t in threads:
            t.join(timeout=10.0)
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        if self._kv_pool is not None:
            self._kv_pool.close()
            self._kv_spill.close()
        if self._own_engine:
            self._engine.close()

    def session_count(self) -> int:
        with self._lock:
            return len(self._sessions)

    def queue_depth(self) -> int:
        return self._sched.depth()

    # -- accept / serve -----------------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return          # socket closed: daemon shutting down
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="stromd-conn")
            with self._lock:
                if self._closed:
                    conn.close()
                    return
                self._threads.append(t)
                self._live_conns[id(conn)] = conn
            t.start()

    def _serve(self, conn: socket.socket) -> None:
        framer = Framer(conn)
        sid = None
        clean = False
        try:
            sess = self._attach(conn, framer)
            if sess is None:
                return
            sid = sess.sid
            while True:
                got = framer.recv()
                if got is None:
                    return      # EOF without detach: orphan, reap below
                msg, fds = got
                op = msg.get("op")
                if op != "map":
                    # only map consumes descriptors; drop strays so a
                    # confused client cannot leak fds into the daemon
                    for fd in fds:
                        os.close(fd)
                    fds = []
                try:
                    if op == "detach":
                        clean = True
                        send_msg(conn, {"ok": True})
                        return
                    if op not in _OPS:
                        raise StromError(_errno.EINVAL,
                                         f"unknown op {op!r}")
                    # the op owns fds from here (map closes on failure)
                    send_msg(conn, dict(
                        getattr(self, "_op_" + op)(sess, msg, fds), ok=True))
                except StromError as e:
                    send_msg(conn, {"ok": False, "errno": e.errno,
                                    "error": str(e)})
        except (OSError, StromError, ValueError):
            pass                # connection died mid-frame: reap below
        finally:
            with self._lock:
                self._live_conns.pop(id(conn), None)
            try:
                conn.close()
            except OSError:
                pass
            if sid is not None:
                self._release_session(sid, clean=clean)

    def _attach(self, conn: socket.socket,
                framer: Framer) -> Optional[_ClientSession]:
        """Mandatory first message.  A version mismatch fails CLOSED: an
        EPROTO reply, then the connection drops before any resource is
        allocated (the reference's ABI-mismatch ioctl failure analog)."""
        got = framer.recv()
        if got is None:
            return None
        msg, fds = got
        for fd in fds:
            os.close(fd)
        if msg.get("op") != "attach":
            send_msg(conn, {"ok": False, "errno": _errno.EPROTO,
                            "error": "first message must be attach"})
            return None
        if msg.get("version") != PROTOCOL_VERSION:
            send_msg(conn, {"ok": False, "errno": _errno.EPROTO,
                            "error": f"protocol version "
                                     f"{msg.get('version')!r} != "
                                     f"{PROTOCOL_VERSION}"})
            return None
        tenant = str(msg.get("tenant") or f"pid{msg.get('pid', '?')}")
        qos_class = str(msg.get("class") or self._default_class)
        weight = float(msg.get("weight") or self._default_weight)
        rate = float(msg.get("rate") if msg.get("rate") is not None
                     else self._default_rate)
        if qos_class not in QOS_CLASSES:
            send_msg(conn, {"ok": False, "errno": _errno.EINVAL,
                            "error": f"class must be one of {QOS_CLASSES}"})
            return None
        token = msg.get("lease")
        with self._lock:
            if self._closed:
                send_msg(conn, {"ok": False, "errno": _errno.ESHUTDOWN,
                                "error": "daemon shutting down"})
                return None
            if self._max_sessions and \
                    len(self._sessions) >= self._max_sessions:
                send_msg(conn, {"ok": False, "errno": _errno.EAGAIN,
                                "error": f"max sessions "
                                         f"({self._max_sessions}) attached"})
                return None
            # lease-renewal handshake: a presented token re-adopts the
            # surviving record (reconnect) or is adopted fresh (daemon
            # restarted and lost it); no token mints one
            reattach = False
            lease = self._leases.get(token) if token else None
            if lease is not None:
                reattach = True
                self._leases.move_to_end(token)
                tenant = lease.tenant       # identity rides the lease
                qos_class = str(msg.get("class") or lease.qos_class)
                weight = float(msg.get("weight") or lease.weight)
            else:
                lease = _Lease(token or secrets.token_hex(8), tenant,
                               qos_class, weight)
                self._leases[lease.token] = lease
                while len(self._leases) > _MAX_LEASES:
                    # oldest lease with no live session goes first
                    live = {s.lease.token for s in self._sessions.values()
                            if s.lease is not None}
                    for k in self._leases:
                        if k not in live:
                            del self._leases[k]
                            break
                    else:
                        break
            lease.qos_class, lease.weight = qos_class, weight
            self._next_sid += 1
            sess = _ClientSession(self._next_sid, tenant, qos_class, weight,
                                  lease=lease)
            # re-adopt the lease's surviving tasks so a wait issued after
            # the re-attach finds work submitted before the disconnect;
            # cancelled ones are forgotten so a resubmit re-runs them
            for sub_id in list(lease.submits):
                item = lease.submits[sub_id]
                if item.cancelled:
                    del lease.submits[sub_id]
                else:
                    item.session_id = sess.sid
                    sess.tasks[item.task_id] = item
            self._sessions[sess.sid] = sess
        self._sched.register_tenant(tenant, qos_class=qos_class,
                                    weight=weight, rate=rate,
                                    burst=self._default_burst)
        stats.add("nr_session_attach")
        stats.gauge_add("daemon_sessions", 1)
        stats.tenant_configure(tenant, qos_class=qos_class, weight=weight,
                               rate=rate, quota_tasks=self._quota_tasks,
                               quota_bytes=self._quota_bytes)
        if _trace.active:
            _trace.instant("session_attach",
                           args={"session": sess.sid, "tenant": tenant,
                                 "class": qos_class, "reattach": reattach})
        send_msg(conn, {"ok": True, "session": sess.sid, "tenant": tenant,
                        "version": PROTOCOL_VERSION,
                        "lease": sess.lease.token, "reattach": reattach})
        return sess

    # -- session ops --------------------------------------------------------
    def _op_ping(self, sess, msg, fds) -> dict:
        return {"pong": True, "session": sess.sid}

    def _op_configure(self, sess, msg, fds) -> dict:
        qos_class = str(msg.get("class") or sess.qos_class)
        weight = float(msg.get("weight") or sess.weight)
        rate = msg.get("rate")
        if qos_class not in QOS_CLASSES:
            raise StromError(_errno.EINVAL,
                             f"class must be one of {QOS_CLASSES}")
        sess.qos_class = qos_class
        sess.weight = weight
        self._sched.register_tenant(
            sess.tenant, qos_class=qos_class, weight=weight,
            rate=float(self._default_rate if rate is None else rate),
            burst=self._default_burst)
        stats.tenant_configure(sess.tenant, qos_class=qos_class,
                               weight=weight,
                               rate=None if rate is None else float(rate))
        return {"class": qos_class, "weight": weight}

    def _op_map(self, sess, msg, fds) -> dict:
        if not fds:
            raise StromError(_errno.EINVAL, "map needs an SCM_RIGHTS fd")
        fd, extra = fds[0], fds[1:]
        for f in extra:
            os.close(f)
        length = int(msg.get("length", 0))
        if length <= 0:
            os.close(fd)
            raise StromError(_errno.EINVAL, f"bad map length {length}")
        try:
            mb = _MappedBuffer(fd, length, self._engine)
        except (OSError, ValueError) as e:
            os.close(fd)
            raise StromError(_errno.EINVAL, f"cannot map client fd: {e}")
        sess.buffers[mb.handle] = mb
        return {"handle": mb.handle, "length": length}

    def _op_unmap(self, sess, msg, fds) -> dict:
        handle = int(msg.get("handle", -1))
        mb = sess.buffers.pop(handle, None)
        if mb is None:
            raise StromError(_errno.ENOENT, f"no mapped buffer {handle}")
        mb.release(self._engine)
        return {}

    def _op_open(self, sess, msg, fds) -> dict:
        spec = msg.get("spec")
        if isinstance(spec, dict):
            src = self._open_fake(spec)
        else:
            from ..engine import open_source
            kw = {}
            if msg.get("stripe_chunk_size"):
                kw["stripe_chunk_size"] = int(msg["stripe_chunk_size"])
            if msg.get("segment_size"):
                kw["segment_size"] = int(msg["segment_size"])
            if msg.get("mirror"):
                kw["mirror"] = str(msg["mirror"])
            src = open_source(spec, **kw)
        handle = sess.next_handle
        sess.next_handle += 1
        sess.sources[handle] = src
        return {"handle": handle, "size": src.size}

    def _open_fake(self, spec: dict):
        if not self._allow_fake:
            raise StromError(_errno.EPERM,
                             "fake sources need a daemon started with "
                             "allow_fake=True (test/gate only)")
        from ..testing import FakeNvmeSource, FaultPlan
        plan = None
        if spec.get("latency_s"):
            plan = FaultPlan(latency_s=float(spec["latency_s"]))
        kw = {}
        if spec.get("force_cached_fraction") is not None:
            kw["force_cached_fraction"] = float(spec["force_cached_fraction"])
        return FakeNvmeSource(str(spec["path"]), fault_plan=plan, **kw)

    def _op_close_source(self, sess, msg, fds) -> dict:
        handle = int(msg.get("handle", -1))
        src = sess.sources.pop(handle, None)
        if src is None:
            raise StromError(_errno.ENOENT, f"no open source {handle}")
        src.close()
        return {}

    def _op_submit(self, sess, msg, fds) -> dict:
        """Admission control then QoS enqueue.  The reply carries the
        daemon task id immediately — the engine runs the command later,
        when the scheduler dispatches it; WAIT returns the authoritative
        result (including the engine's chunk-id reordering)."""
        src = sess.sources.get(int(msg.get("source", -1)))
        if src is None:
            raise StromError(_errno.ENOENT, "unknown source handle")
        buf_handle = int(msg.get("buffer", -1))
        if buf_handle not in sess.buffers:
            raise StromError(_errno.ENOENT, "unknown buffer handle")
        chunk_ids = [int(c) for c in msg.get("chunk_ids", ())]
        chunk_size = int(msg.get("chunk_size", 0))
        if not chunk_ids or chunk_size <= 0:
            raise StromError(_errno.EINVAL, "need chunk_ids and chunk_size")
        submit_id = msg.get("submit_id")
        if submit_id is not None:
            # idempotent resubmission: a replayed submit_id the lease
            # already holds returns the live task instead of running the
            # DMA twice (a restarted daemon has an empty table, so the
            # replay genuinely re-executes — the recovery case)
            with self._lock:
                prior = sess.lease.submits.get(str(submit_id))
                if prior is not None and not prior.cancelled:
                    return {"task_id": prior.task_id,
                            "nr_chunks": len(prior.chunk_ids),
                            "dedup": True}
        nbytes = len(chunk_ids) * chunk_size
        with self._lock:
            if (self._quota_tasks
                    and sess.inflight_tasks + 1 > self._quota_tasks) or \
               (self._quota_bytes
                    and sess.inflight_bytes + nbytes > self._quota_bytes):
                rejected = True
            else:
                rejected = False
                sess.inflight_tasks += 1
                sess.inflight_bytes += nbytes
                self._next_task += 1
                task_id = self._next_task
        if rejected:
            stats.add("nr_admission_reject")
            stats.tenant_reject(sess.tenant)
            if _trace.active:
                _trace.instant("admission_reject",
                               args={"tenant": sess.tenant,
                                     "session": sess.sid, "nbytes": nbytes})
            raise StromError(_errno.EAGAIN,
                             f"tenant {sess.tenant} over quota "
                             f"({sess.inflight_tasks} tasks / "
                             f"{sess.inflight_bytes} bytes in flight): "
                             f"back off and retry")
        stats.tenant_inflight(sess.tenant, 1, nbytes)
        item = WorkItem(session_id=sess.sid, tenant=sess.tenant,
                        task_id=task_id, source_handle=id(src),
                        buf_handle=buf_handle, chunk_ids=chunk_ids,
                        chunk_size=chunk_size,
                        dest_offset=int(msg.get("dest_offset", 0)),
                        submit_id=None if submit_id is None
                        else str(submit_id))
        item.source = src       # resolved object rides the item
        sess.tasks[task_id] = item
        if item.submit_id is not None:
            with self._lock:
                sess.lease.remember(item.submit_id, item)
        if _trace.active:
            item.trace_tid = task_id
            _trace.instant("qos_enqueue",
                           args={"tenant": sess.tenant, "session": sess.sid,
                                 "task": task_id, "nbytes": nbytes})
        self._sched.enqueue(item)
        stats.gauge_set("qos_queue_depth", self._sched.depth())
        return {"task_id": task_id, "nr_chunks": len(chunk_ids)}

    def _op_wait(self, sess, msg, fds) -> dict:
        task_id = int(msg.get("task_id", -1))
        item = sess.tasks.get(task_id)
        if item is None:
            raise StromError(_errno.ENOENT, f"unknown daemon task {task_id}")
        timeout = msg.get("timeout")
        if not item.done.wait(None if timeout is None else float(timeout)):
            raise StromError(_errno.ETIMEDOUT,
                             f"daemon task {task_id} timeout")
        sess.tasks.pop(task_id, None)
        if item.submit_id is not None:
            # the wait IS the ack: the idempotency window closes here
            with self._lock:
                sess.lease.submits.pop(item.submit_id, None)
        if item.cancelled:
            raise StromError(_errno.ECANCELED,
                             f"daemon task {task_id} cancelled by session "
                             f"teardown")
        if item.error is not None:
            raise StromError(item.error[0], item.error[1])
        res = item.result
        if isinstance(res, dict):       # KV-pool item: payload as-is
            return dict(res, task_id=task_id,
                        wait_ns=item.dispatch_ns - item.enqueue_ns)
        return {"task_id": task_id, "nr_chunks": res.nr_chunks,
                "nr_ssd2dev": res.nr_ssd2dev, "nr_ram2dev": res.nr_ram2dev,
                "chunk_ids": list(res.chunk_ids), "landing": res.landing,
                "wait_ns": item.dispatch_ns - item.enqueue_ns}

    # -- KV-cache paging (ISSUE 15): one shared pool, QoS-scheduled ---------
    def _op_kv_open(self, sess, msg, fds) -> dict:
        """Open (or join) the daemon's shared KV block pool.  The first
        caller supplies the spill spec; later callers just get the pool
        geometry — one pool, many sequences, every tenant's page
        traffic ordered by the same QoS classes as its DMA."""
        with self._lock:
            pool = self._kv_pool
        if pool is None:
            from ..serving.kvcache import KvBlockPool
            spill = self._open_spill(msg.get("spill"), msg)
            try:
                pool = KvBlockPool(
                    self._engine, spill,
                    block_bytes=msg.get("block_bytes"),
                    ram_blocks=int(msg.get("ram_blocks", 16)))
            except BaseException:
                spill.close()
                raise
            with self._lock:
                if self._kv_pool is None:
                    self._kv_pool, self._kv_spill = pool, spill
                else:           # racing open won; keep theirs
                    pool.close()
                    spill.close()
                    pool = self._kv_pool
        return {"block_bytes": pool.block_bytes,
                "residency": pool.residency()}

    def _open_spill(self, spec, msg):
        if isinstance(spec, dict):
            if not self._allow_fake:
                raise StromError(_errno.EPERM,
                                 "fake spill needs allow_fake=True")
            from ..testing import FakeNvmeSource, FakeStripedNvmeSource
            if "paths" in spec:
                return FakeStripedNvmeSource(
                    [str(p) for p in spec["paths"]],
                    int(spec["stripe_chunk_size"]),
                    mirror=str(spec.get("mirror") or "none"),
                    writable=True, force_cached_fraction=0.0)
            return FakeNvmeSource(str(spec["path"]), writable=True,
                                  force_cached_fraction=0.0)
        if not spec:
            raise StromError(_errno.EINVAL, "kv_open needs a spill spec")
        from ..engine import open_source
        kw = {k: msg[k] for k in ("stripe_chunk_size", "segment_size",
                                  "mirror") if msg.get(k)}
        return open_source(spec, writable=True, **kw)

    def _op_kv(self, sess, msg, fds) -> dict:
        """One KV-pool operation, admitted and QoS-scheduled exactly
        like a DMA submit (the block's bytes are the shaping weight),
        then answered synchronously — the page-in a latency tenant
        issues overtakes a bulk tenant's queued scan traffic."""
        with self._lock:
            pool = self._kv_pool
        if pool is None:
            raise StromError(_errno.ENXIO, "no KV pool: kv_open first")
        kvop = str(msg.get("kv_op"))
        if kvop not in ("append", "read", "write", "resume", "release",
                        "residency"):
            raise StromError(_errno.EINVAL, f"unknown kv_op {kvop!r}")
        args = {"seq": msg.get("seq"), "idx": msg.get("idx")}
        if msg.get("data") is not None:
            args["data"] = base64.b64decode(msg["data"])
        nbytes = pool.block_bytes if kvop in ("append", "read",
                                              "write") else 0
        with self._lock:
            if (self._quota_tasks
                    and sess.inflight_tasks + 1 > self._quota_tasks) or \
               (self._quota_bytes and nbytes
                    and sess.inflight_bytes + nbytes > self._quota_bytes):
                rejected = True
            else:
                rejected = False
                sess.inflight_tasks += 1
                sess.inflight_bytes += nbytes
                self._next_task += 1
                task_id = self._next_task
        if rejected:
            stats.add("nr_admission_reject")
            stats.tenant_reject(sess.tenant)
            raise StromError(_errno.EAGAIN,
                             f"tenant {sess.tenant} over quota: back off")
        stats.tenant_inflight(sess.tenant, 1, nbytes)
        item = WorkItem(session_id=sess.sid, tenant=sess.tenant,
                        task_id=task_id, source_handle=0, buf_handle=0,
                        chunk_ids=[0], chunk_size=max(1, nbytes),
                        kv=(kvop, args))
        sess.tasks[task_id] = item
        self._sched.enqueue(item)
        stats.gauge_set("qos_queue_depth", self._sched.depth())
        if not item.done.wait(float(msg.get("timeout", 60.0))):
            raise StromError(_errno.ETIMEDOUT, f"kv {kvop} timeout")
        sess.tasks.pop(task_id, None)
        if item.cancelled:
            raise StromError(_errno.ECANCELED, f"kv {kvop} cancelled")
        if item.error is not None:
            raise StromError(item.error[0], item.error[1])
        return dict(item.result)

    def _kv_execute(self, kvop: str, args: dict) -> dict:
        pool = self._kv_pool
        seq = args.get("seq")
        if kvop == "append":
            return {"idx": pool.append(seq, args["data"])}
        if kvop == "read":
            data = pool.read(seq, int(args["idx"]))
            return {"data": base64.b64encode(data).decode("ascii")}
        if kvop == "write":
            pool.write(seq, int(args["idx"]), args["data"])
            return {}
        if kvop == "resume":
            return {"paged_in": pool.resume(seq)}
        if kvop == "release":
            pool.release(seq)
            return {}
        return {"residency": pool.residency()}

    def _op_stat(self, sess, msg, fds) -> dict:
        snap = stats.snapshot(debug=bool(msg.get("debug")))
        with self._lock:
            nsess = len(self._sessions)
        return {"counters": snap.counters, "timestamp_ns": snap.timestamp_ns,
                "tenants": stats.tenant_snapshot(), "sessions": nsess,
                "queue_depth": self._sched.depth(),
                "lat_hist": stats.lat_hist_snapshot()}

    # -- dispatch -----------------------------------------------------------
    def _throttled(self, tenant: str) -> None:
        stats.add("nr_qos_throttle")
        stats.tenant_throttle(tenant)
        if _trace.active:
            _trace.instant("qos_throttle", args={"tenant": tenant})

    def _dispatch_loop(self) -> None:
        while not self._closed:
            item = self._sched.next_item(timeout=0.2)
            if item is None:
                continue
            self._execute(item)
            stats.gauge_set("qos_queue_depth", self._sched.depth())

    def _execute(self, item: WorkItem) -> None:
        wait_ns = item.dispatch_ns - item.enqueue_ns
        stats.count_clock("qos_wait", wait_ns)
        if _trace.active:
            _trace.span("qos_wait", item.enqueue_ns, item.dispatch_ns,
                        tid=item.trace_tid,
                        args={"tenant": item.tenant,
                              "session": item.session_id})
        try:
            if item.kv is not None:
                item.result = self._kv_execute(*item.kv)
            else:
                res = self._engine.memcpy_ssd2ram(
                    item.source, item.buf_handle, list(item.chunk_ids),
                    item.chunk_size, dest_offset=item.dest_offset)
                item.result = self._engine.memcpy_wait(res.dma_task_id)
        except StromError as e:
            item.error = (e.errno or _errno.EIO, str(e))
        except Exception as e:          # noqa: BLE001 — must not kill the
            item.error = (_errno.EIO, f"dispatch failed: {e}")  # dispatcher
        finally:
            self._finalize(item)

    def _finalize(self, item: WorkItem) -> None:
        """Single completion path for executed AND cancelled items:
        quota release, tenant accounting, then the done event (last, so a
        waiter observing done sees final accounting)."""
        with self._lock:
            sess = self._sessions.get(item.session_id)
            if sess is not None:
                sess.inflight_tasks -= 1
                sess.inflight_bytes -= item.nbytes
        stats.tenant_inflight(item.tenant, -1, -item.nbytes)
        if item.error is None and not item.cancelled:
            stats.tenant_task(item.tenant, item.nbytes,
                              item.dispatch_ns - item.enqueue_ns)
        item.done.set()

    # -- teardown / reaping -------------------------------------------------
    def _release_session(self, sid: int, *, clean: bool) -> None:
        """Release everything a session holds.  Runs on the connection
        handler's way out — for a clean detach AND for the orphan case
        (crash, SIGKILL, dropped socket), so a dead client can never
        wedge a lane: queued work is cancelled, dispatched work is
        drained, buffer registrations are revoked after the drain, and
        sources close last."""
        with self._lock:
            sess = self._sessions.pop(sid, None)
        if sess is None:
            return
        for item in self._sched.drop_session(sid):
            item.error = (_errno.ECONNRESET, "session torn down")
            self._finalize(item)
        stats.gauge_set("qos_queue_depth", self._sched.depth())
        # dispatched items still run on the engine; wait them out so the
        # buffer revocation below cannot race in-flight DMA
        for item in list(sess.tasks.values()):
            item.done.wait(timeout=60.0)
        for mb in list(sess.buffers.values()):
            mb.release(self._engine)
        sess.buffers.clear()
        for src in list(sess.sources.values()):
            try:
                src.close()
            except (OSError, StromError):
                pass
        sess.sources.clear()
        stats.gauge_add("daemon_sessions", -1)
        stats.add("nr_session_detach" if clean else "nr_session_reap")
        if _trace.active:
            if clean:
                _trace.instant("session_detach",
                               args={"session": sid, "tenant": sess.tenant})
            else:
                _trace.instant("session_reap",
                               args={"session": sid, "tenant": sess.tenant})
