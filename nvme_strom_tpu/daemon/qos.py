"""stromd QoS scheduler: priority classes, token-bucket shaping, and
byte-weighted deficit round-robin across tenants.

The reference arbitrates DMA across every process on the host inside the
kernel — submission order IS the QoS policy, and a bulk scan can starve a
latency-sensitive reader.  stromd puts an explicit scheduler in front of
the engine's per-member lanes instead:

* **priority classes** (``latency`` > ``normal`` > ``bulk``) are strict:
  an admissible latency-class item always dispatches before any normal or
  bulk item, so a bulk antagonist bounds a latency tenant's queue wait at
  roughly one in-service item;
* **token-bucket shaping** per tenant (``qos_rate``/``qos_burst``) gates
  a tenant whose head-of-line item would exceed its configured bandwidth
  — shaped-out tenants yield their slot (work-conserving: lower classes
  run rather than the lane idling) and do NOT accrue round-robin deficit
  while gated;
* **byte-weighted deficit round-robin** within a class: each round a
  tenant earns ``quantum × weight`` bytes of deficit and the tenant whose
  head item needs the fewest whole rounds dispatches next (the classic
  virtual-rounds trick, so one pass computes the next dispatch instead of
  spinning empty rounds).  Over any busy interval tenants receive bytes
  proportional to their weights within one quantum's slack — the 3:1
  fairness the qos-gate asserts.

The scheduler is deliberately engine-agnostic: it orders opaque
:class:`WorkItem` objects and knows nothing about sockets or sessions, so
unit tests drive it deterministically with no I/O at all.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["QOS_CLASSES", "TokenBucket", "WorkItem", "QosScheduler"]

#: strict-priority dispatch order, highest first
QOS_CLASSES = ("latency", "normal", "bulk")


class TokenBucket:
    """Byte token bucket: ``rate`` bytes/s refill up to ``burst`` capacity.

    ``rate <= 0`` means unshaped (always admissible).  Items larger than
    the burst are admitted once the bucket is full — shaping stays
    approximate for oversized items instead of wedging them forever.
    Callers serialize access (the scheduler holds its lock)."""

    __slots__ = ("rate", "burst", "_tokens", "_t_last")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._tokens = self.burst
        self._t_last = time.monotonic()

    def _refill(self, now: float) -> None:
        if now > self._t_last:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t_last) * self.rate)
            self._t_last = now

    def ready_in(self, nbytes: int, now: float) -> float:
        """Seconds until *nbytes* is admissible (0.0 = admissible now)."""
        if self.rate <= 0:
            return 0.0
        self._refill(now)
        need = min(float(nbytes), self.burst)
        if self._tokens >= need:
            return 0.0
        return (need - self._tokens) / self.rate

    def consume(self, nbytes: int, now: float) -> None:
        if self.rate <= 0:
            return
        self._refill(now)
        self._tokens -= min(float(nbytes), self.burst)


class WorkItem:
    """One queued DMA command with its tenant/session attribution.

    ``done`` is set exactly once — after dispatch completes (``result`` or
    ``error`` populated) or when the item is cancelled by session teardown
    (``cancelled`` True) — so a waiter can never hang on a reaped item."""

    __slots__ = ("session_id", "tenant", "task_id", "source_handle",
                 "buf_handle", "chunk_ids", "chunk_size", "dest_offset",
                 "nbytes", "enqueue_ns", "dispatch_ns", "done", "result",
                 "error", "cancelled", "trace_tid", "source", "kv",
                 "submit_id", "speculative")

    def __init__(self, *, session_id: int, tenant: str, task_id: int,
                 source_handle: int, buf_handle: int, chunk_ids: List[int],
                 chunk_size: int, dest_offset: int = 0,
                 kv: Optional[tuple] = None, submit_id: Optional[str] = None,
                 speculative: bool = False):
        self.session_id = session_id
        self.tenant = tenant
        self.task_id = task_id
        self.source_handle = source_handle
        self.buf_handle = buf_handle
        self.chunk_ids = list(chunk_ids)
        self.chunk_size = int(chunk_size)
        self.dest_offset = int(dest_offset)
        self.nbytes = len(self.chunk_ids) * self.chunk_size
        self.enqueue_ns = time.monotonic_ns()
        self.dispatch_ns = 0
        self.done = threading.Event()
        self.result = None
        self.error: Optional[Tuple[int, str]] = None
        self.cancelled = False
        self.trace_tid = 0
        self.source = None      # server attaches the resolved source object
        self.kv = kv            # (op, args) for KV-pool items, else None
        self.submit_id = submit_id  # client idempotency key, else None
        self.speculative = bool(speculative)  # readahead fill (ISSUE 18)


class _Tenant:
    __slots__ = ("name", "qos_class", "weight", "bucket", "queue", "deficit",
                 "gated")

    def __init__(self, name: str, qos_class: str, weight: float,
                 bucket: TokenBucket):
        self.name = name
        self.qos_class = qos_class
        self.weight = max(1e-3, float(weight))
        self.bucket = bucket
        self.queue: deque = deque()
        self.deficit = 0.0
        self.gated = False


class QosScheduler:
    """Strict-class + shaped + deficit-round-robin work queue.

    One condition variable guards everything: enqueue/dispatch rates here
    are per-DMA-command (milliseconds of service each), so a single lock
    is nowhere near contended and keeps the invariants auditable."""

    def __init__(self, *, quantum: int = 256 << 10,
                 on_throttle: Optional[Callable[[str], None]] = None):
        self._cv = threading.Condition()
        self._quantum = max(1, int(quantum))
        self._tenants: Dict[str, _Tenant] = {}
        #: per-class round-robin order of tenants with queued work
        self._active: Dict[str, deque] = {c: deque() for c in QOS_CLASSES}
        self._depth = 0
        self._closed = False
        self._on_throttle = on_throttle

    # -- tenant management --------------------------------------------------
    def register_tenant(self, name: str, *, qos_class: str = "normal",
                        weight: float = 1.0, rate: float = 0.0,
                        burst: float = 8 << 20) -> None:
        """Create or reconfigure a tenant (idempotent; reconfiguring keeps
        its queue and deficit so a mid-stream weight change is smooth)."""
        if qos_class not in QOS_CLASSES:
            raise ValueError(f"qos_class must be one of {QOS_CLASSES}, "
                             f"got {qos_class!r}")
        with self._cv:
            t = self._tenants.get(name)
            if t is None:
                self._tenants[name] = _Tenant(name, qos_class, weight,
                                              TokenBucket(rate, burst))
            else:
                if t.qos_class != qos_class and t.queue:
                    # move the queued tenant to its new class ring
                    try:
                        self._active[t.qos_class].remove(name)
                    except ValueError:
                        pass
                    self._active[qos_class].append(name)
                t.qos_class = qos_class
                t.weight = max(1e-3, float(weight))
                t.bucket = TokenBucket(rate, burst)
            self._cv.notify_all()

    def tenant_config(self, name: str) -> Optional[dict]:
        with self._cv:
            t = self._tenants.get(name)
            if t is None:
                return None
            return {"class": t.qos_class, "weight": t.weight,
                    "rate": t.bucket.rate, "queued": len(t.queue)}

    # -- queue operations ---------------------------------------------------
    def enqueue(self, item: WorkItem) -> None:
        with self._cv:
            if self._closed:
                raise RuntimeError("scheduler closed")
            t = self._tenants.get(item.tenant)
            if t is None:
                raise KeyError(f"unregistered tenant {item.tenant!r}")
            if item.speculative:
                # readahead rides the bulk class (ISSUE 18): speculative
                # fills re-attribute to a shadow "<tenant>#ra" tenant so
                # strict-class dispatch drains every demand read first
                # and the tenant's own shaping/accounting stays clean
                shadow = item.tenant + "#ra"
                st = self._tenants.get(shadow)
                if st is None:
                    st = self._tenants[shadow] = _Tenant(
                        shadow, "bulk", t.weight,
                        TokenBucket(t.bucket.rate, t.bucket.burst))
                item.tenant = shadow
                t = st
            t.queue.append(item)
            if len(t.queue) == 1:
                self._active[t.qos_class].append(t.name)
            self._depth += 1
            self._cv.notify_all()

    def depth(self) -> int:
        with self._cv:
            return self._depth

    def next_item(self, timeout: Optional[float] = None) -> Optional[WorkItem]:
        """Dispatch the next admissible item per class/shaping/DRR policy;
        blocks up to *timeout* seconds (None = forever) when nothing is
        admissible.  Returns None on timeout or scheduler close."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                if self._closed:
                    return None
                now = time.monotonic()
                item, wake = self._pick(now)
                if item is not None:
                    self._depth -= 1
                    item.dispatch_ns = time.monotonic_ns()
                    return item
                remain = None if deadline is None else deadline - now
                if remain is not None and remain <= 0:
                    return None
                if wake is not None:
                    remain = wake if remain is None else min(remain, wake)
                self._cv.wait(remain)

    def _pick(self, now: float) -> Tuple[Optional[WorkItem], Optional[float]]:
        """One scheduling decision under the lock: highest class with an
        admissible tenant wins; within the class, fewest virtual DRR
        rounds wins.  Returns (item, seconds-until-a-gated-head-readies)."""
        wake: Optional[float] = None
        for cls in QOS_CLASSES:
            ring = self._active[cls]
            ready: List[Tuple[float, int, _Tenant]] = []
            for pos, name in enumerate(ring):
                t = self._tenants[name]
                head: WorkItem = t.queue[0]
                wait_s = t.bucket.ready_in(head.nbytes, now)
                if wait_s > 0:
                    if not t.gated:
                        t.gated = True
                        if self._on_throttle is not None:
                            self._on_throttle(t.name)
                    wake = wait_s if wake is None else min(wake, wait_s)
                    continue
                t.gated = False
                q = self._quantum * t.weight
                rounds = max(0.0, math.ceil((head.nbytes - t.deficit) / q))
                ready.append((rounds, pos, t))
            if not ready:
                continue        # shaped-out class yields to lower classes
            rounds, _pos, best = min(ready)
            if rounds > 0:
                # virtual rounds: advance every admissible tenant's
                # deficit by the rounds the winner needed, in one step
                for _r, _p, t in ready:
                    t.deficit += rounds * self._quantum * t.weight
            item = best.queue.popleft()
            best.deficit -= item.nbytes
            # rotate the winner behind its class peers; drop it from the
            # ring (and zero its deficit) once drained, per classic DRR
            try:
                ring.remove(best.name)
            except ValueError:
                pass
            if best.queue:
                ring.append(best.name)
            else:
                best.deficit = 0.0
            best.bucket.consume(item.nbytes, now)
            return item, None
        return None, wake

    def drop_session(self, session_id: int) -> List[WorkItem]:
        """Remove every queued item belonging to *session_id* (orphan
        reaping / clean detach with work still queued).  Items are marked
        cancelled and returned; the CALLER finalizes them (sets errors,
        adjusts accounting, fires ``done``) so scheduler and server
        accounting cannot drift."""
        dropped: List[WorkItem] = []
        with self._cv:
            for t in self._tenants.values():
                if not t.queue:
                    continue
                keep = deque()
                for item in t.queue:
                    if item.session_id == session_id:
                        item.cancelled = True
                        dropped.append(item)
                    else:
                        keep.append(item)
                if len(keep) != len(t.queue):
                    t.queue = keep
                    if not keep:
                        try:
                            self._active[t.qos_class].remove(t.name)
                        except ValueError:
                            pass
                        t.deficit = 0.0
            self._depth -= len(dropped)
            self._cv.notify_all()
        return dropped

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
