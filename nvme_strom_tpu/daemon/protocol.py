"""stromd wire protocol: versioned, length-prefixed JSON frames over a
Unix domain socket, with SCM_RIGHTS file-descriptor passing.

The reference's IPC boundary is the ``/proc/nvme-strom`` ioctl entry —
fixed-layout argument structs, a version handshake via
``STROM_IOCTL__CHECK_FILE``'s ABI, and fd-based resource passing (the
caller's file descriptor IS the ioctl argument).  Here the boundary is a
SOCK_STREAM Unix socket:

* every message is ``!I`` big-endian length + a JSON object body;
* the FIRST client message must be ``{"op": "attach", "version": N}`` —
  a version mismatch fails closed (EPROTO reply, connection dropped)
  before any resource is touched;
* shared memory travels as SCM_RIGHTS descriptors (the client's
  ``memfd_create`` region is the MAP_GPU_MEMORY analog: the daemon mmaps
  the SAME pages and registers them with the engine, so DMA lands
  directly in client-visible memory with no socket copy);
* replies are ``{"ok": true, ...}`` or ``{"ok": false, "errno": n,
  "error": msg}`` — the client re-raises the errno as a
  :class:`~nvme_strom_tpu.api.StromError`, preserving the reference's
  -errno error model across the process boundary.
"""

from __future__ import annotations

import errno as _errno
import json
import os
import socket
import struct
import tempfile
from typing import List, Optional, Tuple

from ..api import StromError

__all__ = ["PROTOCOL_VERSION", "MAX_FRAME", "MAX_FDS_PER_FRAME",
           "default_socket_path", "send_msg", "Framer"]

#: bumped on any incompatible message-schema change; the attach handshake
#: pins it on both sides (tests drive the mismatch path)
PROTOCOL_VERSION = 1

#: ceiling on one frame body — a corrupt/hostile length prefix must not
#: make the daemon allocate unbounded memory
MAX_FRAME = 16 << 20

#: descriptors accepted per recv segment (one buffer fd per map op today)
MAX_FDS_PER_FRAME = 8

_LEN = struct.Struct("!I")


def default_socket_path(uid: Optional[int] = None) -> str:
    """Per-uid default socket path (the ``/proc/nvme-strom`` well-known
    entry analog; per-uid so unprivileged test runs cannot collide)."""
    return os.path.join(tempfile.gettempdir(),
                        f"stromd.{os.getuid() if uid is None else uid}.sock")


def send_msg(sock: socket.socket, obj: dict, fds: Tuple[int, ...] = ()) -> None:
    """Send one framed message, attaching *fds* via SCM_RIGHTS on the
    first segment (ancillary data rides exactly one sendmsg)."""
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME:
        raise StromError(_errno.EMSGSIZE,
                         f"frame body {len(body)} exceeds {MAX_FRAME}")
    data = _LEN.pack(len(body)) + body
    if fds:
        sent = socket.send_fds(sock, [data], list(fds))
    else:
        sent = sock.send(data)
    while sent < len(data):
        sent += sock.send(data[sent:])


class Framer:
    """Buffered frame reader for one connection.

    Accumulates stream bytes and any SCM_RIGHTS descriptors arriving with
    them; descriptors are attributed to the frame whose body completes on
    (or after) the segment that carried them — sufficient for this
    protocol, where the sender attaches fds to the frame's own first
    segment.  The caller owns returned fds (must close them).
    """

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = bytearray()
        self._fds: List[int] = []

    def recv(self) -> Optional[Tuple[dict, List[int]]]:
        """Next (message, fds) pair, or None on clean EOF.  Raises
        :class:`StromError` (EPROTO) on a malformed frame."""
        while True:
            if len(self._buf) >= _LEN.size:
                (n,) = _LEN.unpack_from(self._buf)
                if n > MAX_FRAME:
                    self._drop_fds()
                    raise StromError(_errno.EPROTO,
                                     f"frame length {n} exceeds {MAX_FRAME}")
                if len(self._buf) >= _LEN.size + n:
                    body = bytes(self._buf[_LEN.size:_LEN.size + n])
                    del self._buf[:_LEN.size + n]
                    fds, self._fds = self._fds, []
                    try:
                        msg = json.loads(body.decode("utf-8"))
                    except (UnicodeDecodeError, json.JSONDecodeError) as e:
                        for fd in fds:
                            os.close(fd)
                        raise StromError(_errno.EPROTO,
                                         f"undecodable frame: {e}") from None
                    if not isinstance(msg, dict):
                        for fd in fds:
                            os.close(fd)
                        raise StromError(_errno.EPROTO,
                                         "frame body is not an object")
                    return msg, fds
            try:
                data, fds, _flags, _addr = socket.recv_fds(
                    self._sock, 1 << 16, MAX_FDS_PER_FRAME)
            except OSError as e:
                self._drop_fds()
                if e.errno in (_errno.ECONNRESET, _errno.EPIPE):
                    return None
                raise
            if fds:
                self._fds.extend(fds)
            if not data:
                # EOF mid-frame loses nothing the peer still owns; any
                # stray descriptors must not leak into this process
                self._drop_fds()
                return None
            self._buf += data

    def _drop_fds(self) -> None:
        fds, self._fds = self._fds, []
        for fd in fds:
            try:
                os.close(fd)
            except OSError:
                pass
