"""Fault-tolerance policy for the I/O runtime (PR 1).

The reference retains a task's first error until the caller reaps it
(kmod/nvme_strom.c first-error latch) but has no recovery tier: any EIO
fails the whole memcpy.  Production SSD fleets see transient medium
errors, congested members and torn reads; this module supplies the policy
half of the recovery stack:

* :class:`RetryPolicy` — bounded attempts with exponential backoff +
  jitter, built from the ``io_retries`` / ``retry_backoff_ms`` /
  ``retry_backoff_max_ms`` / ``retry_jitter`` config vars.
* :class:`MemberHealth` — per-stripe-member consecutive-failure counters
  feeding a quarantine decision (``quarantine_after`` failures route the
  member's reads to the buffered path for ``quarantine_s`` seconds), the
  error-side analog of the reference's per-disk part_stat accounting.

The mechanism half (where retries and fallbacks actually happen) lives in
``engine.Session._do_request``; corruption re-reads in ``hbm.staging``.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass

from .config import config
from .stats import stats

__all__ = ["RetryPolicy", "MemberHealth"]


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry schedule for TRANSIENT I/O errors.

    ``attempts`` is the number of *re*-tries after the first failure; the
    backoff before retry ``i`` (0-based) is ``base * 2**i`` clamped to
    ``ceiling``, scaled by a uniform jitter in ``[1 - jitter, 1]`` so a
    striped set's members don't retry in lockstep.
    """

    attempts: int = 3
    backoff_s: float = 0.005
    backoff_max_s: float = 1.0
    jitter: float = 0.5

    @classmethod
    def from_config(cls) -> "RetryPolicy":
        return cls(attempts=int(config.get("io_retries")),
                   backoff_s=float(config.get("retry_backoff_ms")) / 1e3,
                   backoff_max_s=float(config.get("retry_backoff_max_ms")) / 1e3,
                   jitter=float(config.get("retry_jitter")))

    def delay(self, attempt: int, rng: random.Random = None) -> float:
        """Sleep before retry number ``attempt`` (0-based)."""
        d = min(self.backoff_s * (2 ** attempt), self.backoff_max_s)
        if d <= 0:
            return 0.0
        scale = 1.0 - (rng or random).uniform(0.0, self.jitter)
        return d * scale

    def sleep(self, attempt: int, rng: random.Random = None) -> None:
        d = self.delay(attempt, rng)
        if d > 0:
            time.sleep(d)


class MemberHealth:
    """Per-member consecutive-failure tracking with timed quarantine.

    A member accumulating ``quarantine_after`` consecutive direct-read
    failures is quarantined: :meth:`quarantined` returns True for
    ``quarantine_s`` seconds and the engine routes that member's extents
    straight to the buffered path (no direct attempts, no retry storms
    against a dying disk).  Any direct-read success resets the streak and
    lifts an active quarantine early.  Transitions and counters surface
    through ``stats.member_snapshot()`` / ``tpu_stat -v``.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._streak: dict = {}      # member -> consecutive failures
        self._until: dict = {}       # member -> quarantine expiry (monotonic)

    def record_failure(self, member: int) -> bool:
        """Account one failure; returns True if this pushed the member
        into quarantine."""
        threshold = int(config.get("quarantine_after"))
        hold = float(config.get("quarantine_s"))
        with self._lock:
            n = self._streak.get(member, 0) + 1
            self._streak[member] = n
            if n >= threshold and hold > 0 \
                    and member not in self._until:
                self._until[member] = time.monotonic() + hold
                stats.member_quarantine(member, True)
                return True
        return False

    def record_success(self, member: int) -> None:
        with self._lock:
            self._streak[member] = 0
            if self._until.pop(member, None) is not None:
                stats.member_quarantine(member, False)

    def quarantined(self, member: int) -> bool:
        with self._lock:
            until = self._until.get(member)
            if until is None:
                return False
            if time.monotonic() >= until:
                # expiry: allow a direct re-probe; streak keeps history
                # so one more failure re-enters immediately
                del self._until[member]
                self._streak[member] = \
                    max(0, int(config.get("quarantine_after")) - 1)
                stats.member_quarantine(member, False)
                return False
            return True
