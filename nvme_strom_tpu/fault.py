"""Fault-tolerance policy for the I/O runtime (PR 1, extended PR 6).

The reference retains a task's first error until the caller reaps it
(kmod/nvme_strom.c first-error latch) but has no recovery tier: any EIO
fails the whole memcpy.  Production SSD fleets see transient medium
errors, congested members and torn reads; this module supplies the policy
half of the recovery stack:

* :class:`RetryPolicy` — bounded attempts with exponential backoff +
  jitter, built from the ``io_retries`` / ``retry_backoff_ms`` /
  ``retry_backoff_max_ms`` / ``retry_jitter`` config vars.
* :class:`MemberHealthMachine` — a per-stripe-member health state machine
  (PR 6) replacing the binary quarantine flag::

      healthy <-> suspect          (latency: p99 > suspect_ratio x median)
      healthy/suspect -> quarantined  (quarantine_after consecutive
                                       transient failures, quarantine_s hold)
      healthy/suspect -> failed       (PERSISTENT error: the disk is gone)
      quarantined --timer--> rejoining
      failed --canary success--> rejoining
      rejoining --rejoin_successes--> healthy   (token-bucket warmup)
      rejoining --transient failure--> quarantined  (fresh hold)
      rejoining --persistent failure--> failed

  SUSPECT members stay on the direct path but are prime hedge targets;
  QUARANTINED/FAILED members route to their mirror (degraded striping)
  or the buffered path; REJOINING members take direct traffic at the
  ``rejoin_tokens_s`` token-bucket rate instead of a recovery cliff.

The mechanism half (where retries, hedges and fallbacks actually happen)
lives in ``engine.Session``; corruption re-reads in ``hbm.staging``.
"""

from __future__ import annotations

import enum
import random
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .config import config
from .stats import LAT_HIST_BUCKETS, hist_percentiles, stats
from .trace import recorder as _trace

__all__ = ["RetryPolicy", "HealthState", "MemberHealthMachine",
           "MemberHealth", "DirtyExtentJournal"]


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry schedule for TRANSIENT I/O errors.

    ``attempts`` is the number of *re*-tries after the first failure; the
    backoff before retry ``i`` (0-based) is ``base * 2**i`` clamped to
    ``ceiling``, scaled by a uniform jitter in ``[1 - jitter, 1]`` so a
    striped set's members don't retry in lockstep.
    """

    attempts: int = 3
    backoff_s: float = 0.005
    backoff_max_s: float = 1.0
    jitter: float = 0.5

    @classmethod
    def from_config(cls) -> "RetryPolicy":
        return cls(attempts=int(config.get("io_retries")),
                   backoff_s=float(config.get("retry_backoff_ms")) / 1e3,
                   backoff_max_s=float(config.get("retry_backoff_max_ms")) / 1e3,
                   jitter=float(config.get("retry_jitter")))

    def delay(self, attempt: int, rng: random.Random = None) -> float:
        """Sleep before retry number ``attempt`` (0-based)."""
        d = min(self.backoff_s * (2 ** attempt), self.backoff_max_s)
        if d <= 0:
            return 0.0
        scale = 1.0 - (rng or random).uniform(0.0, self.jitter)
        return d * scale

    def sleep(self, attempt: int, rng: random.Random = None) -> None:
        d = self.delay(attempt, rng)
        if d > 0:
            time.sleep(d)


class HealthState(enum.Enum):
    HEALTHY = "healthy"
    SUSPECT = "suspect"
    QUARANTINED = "quarantined"
    FAILED = "failed"
    REJOINING = "rejoining"


# Every edge the machine may take; the chaos harness asserts observed
# transition logs stay inside this set.
ALLOWED_TRANSITIONS = frozenset({
    (HealthState.HEALTHY, HealthState.SUSPECT),
    (HealthState.SUSPECT, HealthState.HEALTHY),
    (HealthState.HEALTHY, HealthState.QUARANTINED),
    (HealthState.SUSPECT, HealthState.QUARANTINED),
    (HealthState.HEALTHY, HealthState.FAILED),
    (HealthState.SUSPECT, HealthState.FAILED),
    (HealthState.QUARANTINED, HealthState.FAILED),
    (HealthState.QUARANTINED, HealthState.REJOINING),
    (HealthState.FAILED, HealthState.REJOINING),
    (HealthState.REJOINING, HealthState.HEALTHY),
    (HealthState.REJOINING, HealthState.QUARANTINED),
    # a PERSISTENT error during warmup (or from a straggler read issued
    # before the fail-stop) re-fails the member outright
    (HealthState.REJOINING, HealthState.FAILED),
})

# decay the per-member latency histogram once it holds this many samples
# so SUSPECT can clear after the member recovers
_HIST_DECAY_AT = 2048
# minimum samples before a member's p99 participates in suspect math
_SUSPECT_MIN_SAMPLES = 32
# evaluate the suspect predicate every N observations (it walks every
# member's histogram; per-request would be wasteful)
_SUSPECT_EVERY = 32


#: replay granularity: merged journal intervals are consumed in chunks of
#: this size so one token-bucket token maps to a bounded burst and the
#: replay scratch buffer stays small
_RESYNC_CHUNK = 1 << 20


class DirtyExtentJournal:
    """Per-member dirty-extent journal for mirror-coherent writes
    (ISSUE 11).

    When a write degrades to mirror-only because the health machine holds
    a member QUARANTINED/FAILED, the extents the member *missed* are
    recorded here (keyed by a weak sink reference so a closed sink drops
    its debt).  The rejoin path replays them — read-from-mirror, write-to-
    rejoiner — and :class:`MemberHealthMachine` refuses the
    REJOINING→HEALTHY edge while a member still owes bytes, so a rejoined
    disk never serves stale data.  Adjacent/overlapping records merge, so
    rewriting one hot range while degraded journals it once.

    The ``resync_pending_bytes`` gauge tracks journal content exactly:
    :meth:`record` adds, :meth:`take_extent` subtracts, :meth:`put_back`
    re-adds (replay failures don't leak debt).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # member -> sink weakref -> sorted disjoint [start, end) intervals
        self._ext: Dict[int, Dict["weakref.ref", List[List[int]]]] = {}

    def _drop_ref(self, ref: "weakref.ref") -> None:
        dropped = 0
        with self._lock:
            for member in list(self._ext):
                ivs = self._ext[member].pop(ref, None)
                if ivs:
                    dropped += sum(e - s for s, e in ivs)
                if not self._ext[member]:
                    del self._ext[member]
        if dropped:
            stats.gauge_add("resync_pending_bytes", -dropped)

    def record(self, sink, member: int, file_off: int, length: int) -> None:
        """Journal [file_off, file_off+length) as stale on *member*."""
        if length <= 0:
            return
        start, end = int(file_off), int(file_off) + int(length)
        with self._lock:
            per = self._ext.setdefault(member, {})
            ivs = None
            for ref in per:
                if ref() is sink:
                    ivs = per[ref]
                    break
            if ivs is None:
                ivs = per[weakref.ref(sink, self._drop_ref)] = []
            before = sum(e - s for s, e in ivs)
            merged: List[List[int]] = []
            for s, e in ivs:
                if e < start or s > end:
                    merged.append([s, e])
                else:
                    start, end = min(start, s), max(end, e)
            merged.append([start, end])
            merged.sort()
            ivs[:] = merged
            added = sum(e - s for s, e in ivs) - before
        if added:
            stats.gauge_add("resync_pending_bytes", added)

    def members(self) -> List[int]:
        with self._lock:
            return [m for m, per in self._ext.items()
                    if any(ivs for ivs in per.values())]

    def sink_refs(self, member: int) -> List["weakref.ref"]:
        with self._lock:
            return list(self._ext.get(member, {}))

    def pending_bytes(self, member: int) -> int:
        with self._lock:
            per = self._ext.get(member)
            if not per:
                return 0
            return sum(e - s for ivs in per.values() for s, e in ivs)

    def pending_extents(self, member: int) -> List[Tuple[int, int]]:
        """Snapshot of ``(file_off, length)`` owed by *member* (tests)."""
        with self._lock:
            per = self._ext.get(member, {})
            return sorted((s, e - s) for ivs in per.values()
                          for s, e in ivs)

    def take_extent(self, ref: "weakref.ref", member: int
                    ) -> Optional[Tuple[int, int]]:
        """Pop up to ``_RESYNC_CHUNK`` bytes of the first owed interval
        for replay; returns ``(file_off, length)`` or None when drained."""
        with self._lock:
            ivs = self._ext.get(member, {}).get(ref)
            if not ivs:
                return None
            s, e = ivs[0]
            take = min(e - s, _RESYNC_CHUNK)
            if s + take >= e:
                ivs.pop(0)
            else:
                ivs[0][0] = s + take
        stats.gauge_add("resync_pending_bytes", -take)
        return s, take

    def put_back(self, sink, member: int, file_off: int,
                 length: int) -> None:
        """Re-journal an extent whose replay failed (no debt leaks)."""
        self.record(sink, member, file_off, length)

    def drop_sink(self, ref: "weakref.ref") -> None:
        """Forget a sink's debt (its fds are gone; nothing to resync)."""
        self._drop_ref(ref)


@dataclass
class _Member:
    state: HealthState = HealthState.HEALTHY
    since: float = 0.0
    streak: int = 0              # consecutive direct-read failures
    until: float = 0.0           # quarantine expiry (monotonic)
    rejoin_ok: int = 0           # warmup successes accumulated
    tokens: float = 1.0          # rejoin token bucket level
    tokens_t: float = 0.0        # last refill timestamp
    hist: List[int] = field(default_factory=lambda: [0] * LAT_HIST_BUCKETS)
    hist_n: int = 0


class MemberHealthMachine:
    """Per-member health state machine with latency-driven suspicion,
    timed quarantine, fail-stop detection, and token-bucket rejoin.

    Thread-safe; one instance per :class:`engine.Session`.  Transitions
    are appended to a bounded log (:meth:`transitions`) and mirrored into
    the global stats registry (``stats.member_state`` + the PR 1
    ``member_quarantine`` counters, which keep their exact semantics:
    entering QUARANTINED bumps ``nr_member_quarantine`` and the member's
    ``quarantines``; leaving clears the live flag).
    """

    _LOG_MAX = 512

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._m: Dict[int, _Member] = {}
        self._log: List[Tuple[int, str, str, float]] = []
        # dirty-extent resync barrier (ISSUE 11): while attached, the
        # REJOINING->HEALTHY edge is refused and REJOINING routes away
        # until the member's journal is drained — a rejoined disk never
        # serves bytes it missed while degraded
        self._resync: Optional[DirtyExtentJournal] = None

    def attach_resync(self, journal: DirtyExtentJournal) -> None:
        self._resync = journal

    def _resync_pending(self, member: int) -> bool:
        j = self._resync
        return j is not None and j.pending_bytes(member) > 0

    # -- internals -------------------------------------------------------

    def _rec(self, member: int) -> _Member:
        rec = self._m.get(member)
        if rec is None:
            rec = _Member(since=time.monotonic())
            self._m[member] = rec
        return rec

    def _to(self, member: int, rec: _Member, new: HealthState,
            now: float) -> None:
        old = rec.state
        if old is new:
            return
        if len(self._log) < self._LOG_MAX:
            self._log.append((member, old.value, new.value, now))
        if new is HealthState.QUARANTINED:
            stats.member_quarantine(member, True)
        elif old is HealthState.QUARANTINED:
            stats.member_quarantine(member, False)
        if new is HealthState.FAILED:
            stats.add("nr_member_failed")
        if old is HealthState.REJOINING and new is HealthState.HEALTHY:
            stats.add("nr_member_rejoin")
        if new is HealthState.REJOINING:
            rec.rejoin_ok = 0
            rec.tokens = 1.0
            rec.tokens_t = now
        rec.state = new
        rec.since = now
        stats.member_state(member, new.value)
        if _trace.active:
            _trace.instant("health", member=member,
                           args={"from": old.value, "to": new.value})

    def _expire(self, member: int, rec: _Member, now: float) -> None:
        """QUARANTINED -> REJOINING once the hold lapses (the PR 1 cliff
        back to healthy becomes a warmup)."""
        if rec.state is HealthState.QUARANTINED and rec.until \
                and now >= rec.until:
            rec.streak = 0
            self._to(member, rec, HealthState.REJOINING, now)

    def _take_token(self, rec: _Member, now: float) -> bool:
        rate = float(config.get("rejoin_tokens_s"))
        if rate <= 0:
            return True
        cap = max(1.0, float(int(config.get("rejoin_successes"))))
        rec.tokens = min(cap, rec.tokens + (now - rec.tokens_t) * rate)
        rec.tokens_t = now
        if rec.tokens >= 1.0:
            rec.tokens -= 1.0
            return True
        return False

    # -- failure / success accounting -----------------------------------

    def record_failure(self, member: int, *, fatal: bool = False) -> bool:
        """Account one direct-read failure; ``fatal`` (a PERSISTENT
        error) drives the member straight to FAILED.  Returns True if
        this call moved the member off the direct path."""
        now = time.monotonic()
        with self._lock:
            rec = self._rec(member)
            self._expire(member, rec, now)
            if fatal:
                if rec.state is HealthState.FAILED:
                    return False
                rec.streak = 0
                self._to(member, rec, HealthState.FAILED, now)
                return True
            if rec.state in (HealthState.QUARANTINED, HealthState.FAILED):
                return False
            rec.streak += 1
            hold = float(config.get("quarantine_s"))
            if rec.state is HealthState.REJOINING:
                # warmup failure: back behind a fresh hold, no cliff retry
                rec.until = now + hold if hold > 0 else 0.0
                self._to(member, rec, HealthState.QUARANTINED, now)
                return True
            if rec.streak >= int(config.get("quarantine_after")) and hold > 0:
                rec.until = now + hold
                self._to(member, rec, HealthState.QUARANTINED, now)
                return True
        return False

    def record_success(self, member: int) -> None:
        now = time.monotonic()
        with self._lock:
            rec = self._m.get(member)
            if rec is None:
                return
            self._expire(member, rec, now)
            rec.streak = 0
            if rec.state in (HealthState.QUARANTINED, HealthState.FAILED):
                # a direct read got through anyway: begin warmup, counting
                # this success toward it
                self._to(member, rec, HealthState.REJOINING, now)
                rec.rejoin_ok = 1
            elif rec.state is HealthState.REJOINING:
                rec.rejoin_ok += 1
                if rec.rejoin_ok >= int(config.get("rejoin_successes")) \
                        and not self._resync_pending(member):
                    # resync completes before HEALTHY: warmup successes
                    # alone never clear a member that still owes extents
                    self._to(member, rec, HealthState.HEALTHY, now)

    def record_canary(self, member: int, ok: bool) -> None:
        """Account one background canary probe: success moves FAILED to
        REJOINING and advances a REJOINING warmup; failure sends a
        REJOINING member back behind a fresh quarantine hold."""
        stats.add("nr_canary_probe")
        if ok:
            self.record_success(member)
        else:
            now = time.monotonic()
            with self._lock:
                rec = self._m.get(member)
                if rec is not None and rec.state is HealthState.REJOINING:
                    hold = float(config.get("quarantine_s"))
                    rec.until = now + hold if hold > 0 else 0.0
                    rec.streak = 0
                    self._to(member, rec, HealthState.QUARANTINED, now)

    # -- latency-driven suspicion ---------------------------------------

    def observe_latency(self, member: int, ns: int) -> None:
        """Feed one direct-read service time into the member's log2-ns
        histogram; every ``_SUSPECT_EVERY`` samples re-evaluate the
        suspect predicate (p99 > ``suspect_ratio`` x the stripe median
        p99, lower-median across members with enough samples)."""
        b = min(max(int(ns), 1).bit_length() - 1, LAT_HIST_BUCKETS - 1)
        with self._lock:
            rec = self._rec(member)
            rec.hist[b] += 1
            rec.hist_n += 1
            if rec.hist_n >= _HIST_DECAY_AT:
                rec.hist = [v >> 1 for v in rec.hist]
                rec.hist_n = sum(rec.hist)
            if rec.hist_n % _SUSPECT_EVERY:
                return
            if rec.state not in (HealthState.HEALTHY, HealthState.SUSPECT):
                return
            p99s = {}
            for m, r in self._m.items():
                if r.hist_n >= _SUSPECT_MIN_SAMPLES:
                    p = hist_percentiles(r.hist, (0.99,))[0]
                    if p is not None:
                        p99s[m] = p
            mine = p99s.get(member)
            if mine is None or len(p99s) < 2:
                return
            med = sorted(p99s.values())[(len(p99s) - 1) // 2]
            if med <= 0:
                return
            ratio = float(config.get("suspect_ratio"))
            now = time.monotonic()
            if rec.state is HealthState.HEALTHY and mine > ratio * med:
                self._to(member, rec, HealthState.SUSPECT, now)
            elif rec.state is HealthState.SUSPECT \
                    and mine <= (ratio / 2.0) * med:
                self._to(member, rec, HealthState.HEALTHY, now)

    def observe_hist(self, member: int, deltas) -> None:
        """Fold a native per-member latency-histogram delta (the lane
        reaper's view) so suspect detection also covers the native path."""
        with self._lock:
            rec = self._rec(member)
            for i, v in enumerate(deltas[:LAT_HIST_BUCKETS]):
                rec.hist[i] += v
                rec.hist_n += v

    # -- routing queries -------------------------------------------------

    def allow_direct(self, member: int) -> bool:
        """May the engine issue a direct read against this member right
        now?  HEALTHY/SUSPECT: yes.  QUARANTINED/FAILED: no.  REJOINING:
        one warmup token per request."""
        now = time.monotonic()
        with self._lock:
            rec = self._m.get(member)
            if rec is None:
                return True
            self._expire(member, rec, now)
            if rec.state in (HealthState.HEALTHY, HealthState.SUSPECT):
                return True
            if rec.state is HealthState.REJOINING:
                # a rejoiner still owing resync extents serves nothing:
                # any direct read could return bytes it missed while
                # degraded (its mirror has the truth)
                if self._resync_pending(member):
                    return False
                return self._take_token(rec, now)
            return False

    def take_rejoin_token(self, member: int) -> bool:
        """Draw one warmup token for the resync replay (the same bucket
        client traffic draws from, so replay rides the rejoin budget).
        Non-REJOINING members are unthrottled."""
        now = time.monotonic()
        with self._lock:
            rec = self._m.get(member)
            if rec is None or rec.state is not HealthState.REJOINING:
                return True
            return self._take_token(rec, now)

    def quarantined(self, member: int) -> bool:
        """PR 1 compatibility predicate: True when the member's extents
        must route away from the direct path."""
        return not self.allow_direct(member)

    def routes_away(self, member: int) -> bool:
        """True for QUARANTINED/FAILED — the native-path mirror-remap
        predicate (no token consumed, REJOINING serves native traffic)
        — and for a REJOINING member still owing resync extents (stale
        until the journal drains)."""
        now = time.monotonic()
        with self._lock:
            rec = self._m.get(member)
            if rec is None:
                return False
            self._expire(member, rec, now)
            if rec.state in (HealthState.QUARANTINED, HealthState.FAILED):
                return True
            return rec.state is HealthState.REJOINING \
                and self._resync_pending(member)

    def hedge_delay_s(self, member: int) -> Optional[float]:
        """Hedge latch for a chunk on *member*, or None when hedging is
        off.  ``fixed`` uses ``hedge_ms``; ``p99`` derives the latch from
        the member's own p99 with ``hedge_ms`` as the floor."""
        policy = str(config.get("hedge_policy"))
        if policy == "off":
            return None
        floor = float(config.get("hedge_ms")) / 1e3
        if policy == "fixed":
            return floor
        with self._lock:
            rec = self._m.get(member)
            p99 = None
            if rec is not None and rec.hist_n >= 16:
                p99 = hist_percentiles(rec.hist, (0.99,))[0]
        if not p99:
            return floor
        return max(p99 / 1e9, floor)

    # -- introspection ---------------------------------------------------

    def state(self, member: int) -> HealthState:
        with self._lock:
            rec = self._m.get(member)
            if rec is None:
                return HealthState.HEALTHY
            self._expire(member, rec, time.monotonic())
            return rec.state

    def time_in_state(self, member: int) -> float:
        with self._lock:
            rec = self._m.get(member)
            if rec is None:
                return 0.0
            return max(0.0, time.monotonic() - rec.since)

    def unhealthy_members(self) -> List[Tuple[int, str]]:
        """Members off plain HEALTHY, with their state names — the
        autotune freeze predicate (ISSUE 18): the controller suspends
        probing whenever the fault ladder owns any part of the stripe."""
        now = time.monotonic()
        out: List[Tuple[int, str]] = []
        with self._lock:
            for m, rec in self._m.items():
                self._expire(m, rec, now)
                if rec.state is not HealthState.HEALTHY:
                    out.append((m, rec.state.value))
        return out

    def canary_candidates(self) -> List[int]:
        """Members the background prober should touch: FAILED (detect
        recovery) and REJOINING (advance warmup without client traffic).
        QUARANTINED waits out its timer."""
        with self._lock:
            return [m for m, r in self._m.items()
                    if r.state in (HealthState.FAILED,
                                   HealthState.REJOINING)]

    def transitions(self, member: Optional[int] = None
                    ) -> List[Tuple[int, str, str, float]]:
        """Bounded transition log ``[(member, from, to, t_monotonic)]`` in
        order — the chaos harness asserts these walk ALLOWED_TRANSITIONS."""
        with self._lock:
            if member is None:
                return list(self._log)
            return [t for t in self._log if t[0] == member]


# PR 1 name, kept for external callers; the engine now uses the machine.
MemberHealth = MemberHealthMachine
