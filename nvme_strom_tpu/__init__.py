"""nvme_strom_tpu — TPU-native SSD→HBM direct-loading framework.

A brand-new framework with the capabilities of NVMe-Strom (SSD→GPU
peer-to-peer DMA; reference at charles-achilefu/nvme-strom), rebuilt
idiomatically for TPU: a native async I/O engine (io_uring / O_DIRECT) feeds
pinned host staging buffers that stream into TPU HBM through PJRT, with
JAX/XLA/Pallas consuming the data in place.  See SURVEY.md for the layer map
and BASELINE.md for performance targets.

Public surface:

* :mod:`~nvme_strom_tpu.api` — UAPI-equivalent command/result types.
* :mod:`~nvme_strom_tpu.engine` — sessions, sources, buffers, planner.
* :mod:`~nvme_strom_tpu.stripe` — RAID-0 stripe remapping.
* :mod:`~nvme_strom_tpu.testing` — loopback fake backends for CI.
"""

from .api import (BufferInfo, DmaTaskState, FileInfo, FsKind, MemCopyResult,
                  StatInfo, StromError)
from .config import config
from .engine import (DmaBuffer, PlainSource, SegmentedSource, Session, Source,
                     StripedSource, check_file, open_source)
from .stats import stats
from .stripe import StripeMap

__version__ = "0.1.0"

__all__ = [
    "BufferInfo", "DmaBuffer", "DmaTaskState", "FileInfo", "FsKind",
    "MemCopyResult", "PlainSource", "SegmentedSource", "Session", "Source",
    "StatInfo", "StripeMap", "StripedSource", "StromError", "check_file",
    "config", "open_source", "stats", "__version__",
]
