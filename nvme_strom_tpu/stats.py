"""Statistics registry.

Capability analog of the reference's stats engine: 26 global atomic64
counters arranged as count+clock pairs per pipeline stage, plus DMA byte/
in-flight gauges and four spare debug pairs (`kmod/nvme_strom.c:83-119`),
snapshotted by ``STROM_IOCTL__STAT_INFO`` (`:2056-2103`) and rendered by
``nvme_stat`` (`utils/nvme_stat.c`).

Differences from the reference, deliberately: clocks are CLOCK_MONOTONIC
nanoseconds instead of rdtsc (no tsc_hz shipping needed), and the registry is
per-process with the native engine contributing its own counters which are
merged into snapshots.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from contextlib import contextmanager

from .api import STAT_FIELDS, StatInfo
from .config import config

__all__ = ["StatRegistry", "stats", "DEFAULT_STAT_EXPORT",
           "STAT_EXPORT_DIR", "pid_export_path", "list_exports",
           "LAT_HIST_BUCKETS", "hist_percentiles", "bytes_touched_ratio"]

#: per-request service-latency histogram: log2-ns buckets (bucket b covers
#: [2^b, 2^(b+1)) ns), enough for 1ns..584y.  Matches the native engine's
#: lat_hist so deltas fold 1:1.
LAT_HIST_BUCKETS = 64


def hist_percentiles(hist, qs=(0.50, 0.95, 0.99)):
    """Percentile estimates (ns) from a log2 histogram, one per q in *qs*.

    Each bucket's mass is placed at its geometric midpoint (1.5 * 2^b);
    with power-of-two buckets the estimate is within ~1.5x of the true
    value, which is the right resolution for latency triage (is p99 in
    the us, ms, or s regime).  Returns None per q when the histogram is
    empty."""
    total = sum(hist)
    out = []
    for q in qs:
        if total <= 0:
            out.append(None)
            continue
        target = q * total
        acc = 0
        val = None
        for b, n in enumerate(hist):
            acc += n
            if acc >= target and n:
                val = (1 << b) + ((1 << b) >> 1)
                break
        out.append(val)
    return out

def bytes_touched_ratio(counters: dict):
    """Bytes touched per byte delivered (ROADMAP item 5 gate metric).

    ``(payload + staging copies + verify re-reads + hedge duplicate legs)
    / payload`` — 1.0 means every byte moved exactly once (the
    reference's peer-to-peer ideal); today's staging pipeline sits near
    2.0 because each staged byte crosses the pinned-host→device hop.
    Returns None until any payload bytes have been delivered."""
    delivered = counters.get("total_dma_length", 0)
    if delivered <= 0:
        return None
    touched = (delivered
               + counters.get("bytes_staging_copy", 0)
               + counters.get("bytes_verify_reread", 0)
               + counters.get("bytes_hedge_dup", 0))
    return touched / delivered


#: cross-process observability: the reference exposes counters through
#: /proc/nvme-strom readable by nvme_stat from any process; here an exporter
#: thread publishes JSON snapshots to a well-known path for tpu_stat
DEFAULT_STAT_EXPORT = os.environ.get(
    "STROM_TPU_STAT_EXPORT",
    os.path.join(tempfile.gettempdir(), f"strom_tpu_stat.{os.getuid()}.json"))

#: zero-cooperation observability (round 5, VERDICT r4 missing #4): every
#: Session exports to a per-pid file under this directory by DEFAULT
#: (STROM_STAT_EXPORT=0 gates it off), so `tpu_stat -l` / `tpu_stat -p
#: PID` monitor an UNMODIFIED workload the way nvme_stat reads the
#: kernel's /proc counters from any terminal (utils/nvme_stat.c:168-175)
STAT_EXPORT_DIR = os.environ.get(
    "STROM_STAT_EXPORT_DIR",
    "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir())


def pid_export_path(pid: int = None) -> str:
    return os.path.join(STAT_EXPORT_DIR,
                        f"strom_stat.{pid or os.getpid()}.json")


def list_exports() -> list:
    """Discover per-pid export files: ``[(pid, path, alive)]`` —
    *alive* = the exporting process still exists (stale files survive a
    SIGKILL; callers may prune dead ones)."""
    import re
    out = []
    try:
        names = os.listdir(STAT_EXPORT_DIR)
    except OSError:
        return out
    for name in sorted(names):
        m = re.fullmatch(r"strom_stat\.(\d+)\.json", name)
        if not m:
            continue
        pid = int(m.group(1))
        out.append((pid, os.path.join(STAT_EXPORT_DIR, name),
                    os.path.exists(f"/proc/{pid}")))
    return out


class StatRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._c = {name: 0 for name in STAT_FIELDS}
        # per-stripe-member request/byte/latency accounting — the
        # part_stat_add per-disk iostat analog incl. the md aggregate
        # (kmod/nvme_strom.c:1101-1123): member index -> [nreq, bytes, ns].
        # Indexed by position within the (striped) source; single-file
        # sources are member 0.
        self._members: dict = {}
        # fault accounting per member (PR 1): member -> [errors, retries,
        # quarantines_entered, quarantined_now].  Kept separate from the
        # hot-path request triple so the common case stays a 3-add.
        self._member_health: dict = {}
        # per-request service-latency histogram (log2-ns buckets) — the
        # native engine keeps a matching one and its deltas fold in here
        self._hist = [0] * LAT_HIST_BUCKETS
        # per-member latency histograms and queue-occupancy integrals
        # (PR 5 lane scale-out): member -> [64 buckets] / [integral_ns,
        # busy_ns].  Populated from the native engine's per-member deltas;
        # the python pool path feeds _members only (its per-request service
        # times are already member-attributed there).
        self._member_hist: dict = {}
        self._member_occ: dict = {}
        # health-state machine surface (PR 6): member -> (state_name,
        # entered_monotonic).  Written on every transition by
        # fault.MemberHealthMachine; tpu_stat renders state + time-in-state.
        self._member_state: dict = {}
        # applied-knob gauges (ISSUE 18): member -> {"knob_window",
        # "knob_cap", "knob_hedge_ms", "knob_step", "knob_freeze"}.
        # Written by the autotune controller each epoch; surfaced in
        # member_snapshot()/tpu_stat -v as the live operating point.
        self._member_knobs: dict = {}
        # last cur_dma_count transition timestamp for the occupancy
        # integral (0 = no transition seen yet)
        self._occ_last_ns = 0
        # per-tenant QoS accounting (ISSUE 12): stromd attributes every
        # admitted byte to the tenant that submitted it — config echo
        # (class/weight/rate/quota), delivered totals, in-flight gauges,
        # reject/throttle counts, and a log2-ns queue-wait histogram.
        # tenant -> dict; shape documented at tenant_snapshot().
        self._tenants: dict = {}
        # per-shard completion fan-in wait histograms (ISSUE 17): mesh
        # shard index -> log2-ns buckets of submit->completion wait.  A
        # straggler device/host shows up as one shard's distribution
        # sitting a regime above its peers — the aggregate clk_shard_wait
        # hides exactly that.
        self._shard_hist: dict = {}
        # resolved engine backend name (PR 19): which rung of the
        # passthru->io_uring->threadpool ladder this process landed on;
        # set once per Session, surfaced by the export and tpu_stat
        self._backend = ""

    def enabled(self) -> bool:
        return bool(config.get("stat_info"))

    def add(self, name: str, delta: int = 1) -> None:
        if not self.enabled():
            return
        with self._lock:
            self._c[name] += delta

    def set_backend(self, name: str) -> None:
        """Record the resolved engine backend (the ladder rung the session
        landed on).  Not a counter: a plain string surfaced verbatim."""
        with self._lock:
            self._backend = str(name)

    def backend(self) -> str:
        with self._lock:
            return self._backend

    def count_clock(self, name: str, ns: int, n: int = 1) -> None:
        """Bump an ``nr_<name>``/``clk_<name>`` pair."""
        if not self.enabled():
            return
        with self._lock:
            self._c["nr_" + name] += n
            self._c["clk_" + name] += ns

    def gauge_max(self, name: str, value: int) -> None:
        """atomic64_max_return analog (kmod/nvme_strom.c:108-119)."""
        with self._lock:
            if value > self._c[name]:
                self._c[name] = value

    def gauge_set(self, name: str, value: int) -> None:
        with self._lock:
            self._c[name] = value

    def gauge_add(self, name: str, delta: int) -> int:
        with self._lock:
            if name == "cur_dma_count":
                # occupancy integral: account the interval that ends at
                # this transition against the OLD in-flight level, so
                # d(occ_integral_ns)/d(occ_busy_ns) is the time-weighted
                # mean queue depth while the queue was non-empty
                now = time.monotonic_ns()
                cur = self._c["cur_dma_count"]
                if self._occ_last_ns and cur > 0:
                    dt = now - self._occ_last_ns
                    self._c["occ_integral_ns"] += cur * dt
                    self._c["occ_busy_ns"] += dt
                self._occ_last_ns = now
            self._c[name] += delta
            return self._c[name]

    def observe_latency(self, ns: int, n: int = 1) -> None:
        """Record *n* request completions with service time *ns* into the
        log2 latency histogram (tpu_stat derives p50/p95/p99 from it)."""
        if not self.enabled():
            return
        b = min(max(int(ns), 1).bit_length() - 1, LAT_HIST_BUCKETS - 1)
        with self._lock:
            self._hist[b] += n

    def merge_native_hist(self, deltas) -> None:
        """Fold a native-engine latency-histogram *delta* (bucket counts)."""
        with self._lock:
            for i, v in enumerate(deltas[:LAT_HIST_BUCKETS]):
                self._hist[i] += v

    def lat_hist_snapshot(self) -> list:
        with self._lock:
            return list(self._hist)

    def merge_member_hist(self, member: int, deltas) -> None:
        """Fold a native per-member latency-histogram delta (PR 5): the
        per-lane slow-member signal that the aggregate histogram hides."""
        with self._lock:
            h = self._member_hist.setdefault(member, [0] * LAT_HIST_BUCKETS)
            for i, v in enumerate(deltas[:LAT_HIST_BUCKETS]):
                h[i] += v

    def member_hist_snapshot(self) -> dict:
        """{member: [64 buckets]} copy of the per-member latency
        histograms — the autotune controller's per-member p99 sensor
        (epoch deltas of these, not absolutes)."""
        with self._lock:
            return {m: list(h) for m, h in self._member_hist.items()}

    def member_knobs(self, member: int, *, window=None, cap=None,
                     hedge_ms=None, step=None, freeze=None) -> None:
        """Publish the controller's applied knob values for a member
        (ISSUE 18); None leaves a field untouched so partial updates
        compose."""
        with self._lock:
            d = self._member_knobs.setdefault(member, {})
            for k, v in (("knob_window", window), ("knob_cap", cap),
                         ("knob_hedge_ms", hedge_ms), ("knob_step", step),
                         ("knob_freeze", freeze)):
                if v is not None:
                    d[k] = v

    def member_occ_add(self, member: int, integral_ns: int,
                       busy_ns: int) -> None:
        """Fold a per-member queue-occupancy delta: mean in-flight depth
        for the member's lane over a window is d(integral)/d(busy)."""
        with self._lock:
            o = self._member_occ.setdefault(member, [0, 0])
            o[0] += integral_ns
            o[1] += busy_ns

    def member_add(self, member: int, nbytes: int, ns: int, n: int = 1) -> None:
        """Account one request against a stripe member (part_stat_add
        analog): a slow member in a 4-way set becomes visible in
        ``tpu_stat -v`` instead of hiding inside the aggregate."""
        if not self.enabled():
            return
        with self._lock:
            m = self._members.setdefault(member, [0, 0, 0])
            m[0] += n
            m[1] += nbytes
            m[2] += ns

    def member_error(self, member: int, *, retried: bool = False) -> None:
        """Account one direct-read failure (and optionally the retry it
        triggered) against a stripe member — the per-disk error half of
        the part_stat analog, feeding the quarantine policy."""
        if not self.enabled():
            return
        with self._lock:
            h = self._member_health.setdefault(member, [0, 0, 0, False])
            h[0] += 1
            if retried:
                h[1] += 1

    def member_quarantine(self, member: int, active: bool) -> None:
        """Record a quarantine transition for a member (entry bumps the
        counter; exit just clears the live flag)."""
        with self._lock:
            h = self._member_health.setdefault(member, [0, 0, 0, False])
            if active and not h[3]:
                h[2] += 1
                self._c["nr_member_quarantine"] += 1
            h[3] = active

    def member_state(self, member: int, state: str) -> None:
        """Record a health-state transition for a member (PR 6): the state
        name plus its entry time surface as ``state``/``state_s`` in
        :meth:`member_snapshot`."""
        with self._lock:
            self._member_state[member] = (state, time.monotonic())

    def member_snapshot(self) -> dict:
        """{member: {"nreq", "bytes", "clk_ns"[, "errors", "retries",
        "quarantines", "quarantined", "state", "state_s"]}} snapshot;
        health keys appear once a member has seen any fault accounting."""
        with self._lock:
            out = {k: {"nreq": v[0], "bytes": v[1], "clk_ns": v[2]}
                   for k, v in sorted(self._members.items())}
            for k, h in self._member_health.items():
                d = out.setdefault(k, {"nreq": 0, "bytes": 0, "clk_ns": 0})
                d.update(errors=h[0], retries=h[1], quarantines=h[2],
                         quarantined=bool(h[3]))
            for k, hist in self._member_hist.items():
                d = out.setdefault(k, {"nreq": 0, "bytes": 0, "clk_ns": 0})
                p50, p95, _ = hist_percentiles(hist)
                if p50 is not None:
                    d["p50_ns"] = p50
                if p95 is not None:
                    d["p95_ns"] = p95
            for k, o in self._member_occ.items():
                d = out.setdefault(k, {"nreq": 0, "bytes": 0, "clk_ns": 0})
                d["occ_integral_ns"] = o[0]
                d["occ_busy_ns"] = o[1]
            now = time.monotonic()
            for k, (st, since) in self._member_state.items():
                d = out.setdefault(k, {"nreq": 0, "bytes": 0, "clk_ns": 0})
                d["state"] = st
                d["state_s"] = round(now - since, 3)
            for k, knobs in self._member_knobs.items():
                d = out.setdefault(k, {"nreq": 0, "bytes": 0, "clk_ns": 0})
                d.update(knobs)
            return out

    def shard_wait(self, shard: int, ns: int) -> None:
        """Account one shard's submit->completion wait (fan-in observer,
        ISSUE 17): bumps the ``nr_/clk_shard_wait`` pair and the shard's
        own log2-ns histogram for straggler attribution."""
        if not self.enabled():
            return
        b = min(max(int(ns), 1).bit_length() - 1, LAT_HIST_BUCKETS - 1)
        with self._lock:
            self._c["nr_shard_wait"] += 1
            self._c["clk_shard_wait"] += ns
            h = self._shard_hist.setdefault(int(shard),
                                            [0] * LAT_HIST_BUCKETS)
            h[b] += 1

    def shard_snapshot(self) -> dict:
        """{shard: {"n", "p50_ns", "p95_ns"}} from the per-shard wait
        histograms (percentile keys only when the histogram has mass)."""
        with self._lock:
            hists = {k: list(h) for k, h in sorted(self._shard_hist.items())}
        out = {}
        for k, h in hists.items():
            d = {"n": sum(h)}
            p50, p95, _ = hist_percentiles(h)
            if p50 is not None:
                d["p50_ns"] = p50
            if p95 is not None:
                d["p95_ns"] = p95
            out[k] = d
        return out

    def _tenant(self, tenant: str) -> dict:
        # caller holds self._lock
        return self._tenants.setdefault(tenant, {
            "class": "normal", "weight": 1.0, "rate": 0.0,
            "quota_tasks": 0, "quota_bytes": 0,
            "tasks": 0, "bytes": 0, "rejects": 0, "throttles": 0,
            "inflight_tasks": 0, "inflight_bytes": 0,
            "wait_hist": [0] * LAT_HIST_BUCKETS,
        })

    def tenant_configure(self, tenant: str, *, qos_class: str = None,
                         weight: float = None, rate: float = None,
                         quota_tasks: int = None,
                         quota_bytes: int = None) -> None:
        """Record a tenant's QoS configuration (attach/configure echo) so
        the scoreboard shows policy next to delivery.  None = keep."""
        with self._lock:
            t = self._tenant(tenant)
            if qos_class is not None:
                t["class"] = qos_class
            if weight is not None:
                t["weight"] = float(weight)
            if rate is not None:
                t["rate"] = float(rate)
            if quota_tasks is not None:
                t["quota_tasks"] = int(quota_tasks)
            if quota_bytes is not None:
                t["quota_bytes"] = int(quota_bytes)

    def tenant_inflight(self, tenant: str, dtasks: int, dbytes: int) -> None:
        """Adjust a tenant's in-flight quota gauges (admission +, finalize
        -).  Not gated on enabled(): quota gauges must track reality."""
        with self._lock:
            t = self._tenant(tenant)
            t["inflight_tasks"] += dtasks
            t["inflight_bytes"] += dbytes

    def tenant_task(self, tenant: str, nbytes: int, wait_ns: int) -> None:
        """Account one delivered task: bytes plus its scheduler queue wait
        into the tenant's log2 wait histogram (p50/p95 via
        :func:`hist_percentiles`)."""
        with self._lock:
            t = self._tenant(tenant)
            t["tasks"] += 1
            t["bytes"] += nbytes
            b = min(max(int(wait_ns), 1).bit_length() - 1,
                    LAT_HIST_BUCKETS - 1)
            t["wait_hist"][b] += 1

    def tenant_reject(self, tenant: str) -> None:
        with self._lock:
            self._tenant(tenant)["rejects"] += 1

    def tenant_throttle(self, tenant: str) -> None:
        with self._lock:
            self._tenant(tenant)["throttles"] += 1

    def tenant_snapshot(self) -> dict:
        """{tenant: {class, weight, rate, quota_tasks, quota_bytes, tasks,
        bytes, rejects, throttles, inflight_tasks, inflight_bytes,
        wait_hist}} — deep-copied so callers can diff intervals."""
        with self._lock:
            return {k: dict(v, wait_hist=list(v["wait_hist"]))
                    for k, v in sorted(self._tenants.items())}

    @contextmanager
    def stage(self, name: str):
        """Time a pipeline stage into its count+clock pair."""
        if not self.enabled():
            yield
            return
        t0 = time.monotonic_ns()
        try:
            yield
        finally:
            self.count_clock(name, time.monotonic_ns() - t0)

    def snapshot(self, *, debug: bool = False, reset_max: bool = False) -> StatInfo:
        """STAT_INFO: consistent snapshot.

        ``reset_max=True`` additionally reads-and-resets ``max_dma_count``
        to the current in-flight count, as the reference does on each
        STAT_INFO (kmod/nvme_strom.c:2087) — but ONLY the exporter passes
        it (the single resetter, :meth:`export`).  With multiple attached
        readers the reference semantics race: two concurrent
        read-and-resets make one watcher report a too-low high-water
        mark, so plain reads (stat_info, tools, tests) observe without
        consuming and the gauge covers the export interval."""
        with self._lock:
            counters = dict(self._c)
            if reset_max:
                self._c["max_dma_count"] = self._c["cur_dma_count"]
        if not debug:
            counters = {k: v for k, v in counters.items() if "debug" not in k}
        return StatInfo(version=1, has_debug=debug,
                        timestamp_ns=time.monotonic_ns(), counters=counters)

    def as_arrays(self, *, debug: bool = False):
        """Snapshot as (names, np.int64 values) — JAX-visible counters
        (SURVEY.md SS5.1): feed the values array straight into jitted
        monitoring/regression code via device_put."""
        import numpy as np
        snap = self.snapshot(debug=debug, reset_max=False)
        names = sorted(snap.counters)
        return names, np.asarray([snap.counters[n] for n in names],
                                 dtype=np.int64)

    def default_export_start(self) -> None:
        """Session-construction hook: publish this process's counters to
        the discoverable per-pid path by default (idempotent; env
        ``STROM_STAT_EXPORT=0`` opts out).  The file is removed at clean
        exit — a kill leaves it behind, flagged stale by ``tpu_stat
        -l``."""
        if os.environ.get("STROM_STAT_EXPORT", "1").lower() \
                in ("0", "off", "false"):
            return
        if getattr(self, "_exporter", None):
            return
        import atexit
        self.start_export(pid_export_path())
        with self._lock:
            if getattr(self, "_cleanup_registered", False):
                return
            self._cleanup_registered = True

            def cleanup():
                self.stop_export()
                try:
                    os.unlink(pid_export_path())
                except OSError:
                    pass
            atexit.register(cleanup)

    def start_export(self, path: str = None, interval: float = 0.5) -> None:
        """Start the background exporter (idempotent).  Tools call this so a
        concurrently-running ``tpu_stat`` can watch, like ``nvme_stat``
        watching the kernel counters."""
        path = path or DEFAULT_STAT_EXPORT
        stop = threading.Event()

        def loop():
            while not stop.wait(interval):
                self.export(path)

        t = threading.Thread(target=loop, daemon=True, name="strom-stat-export")
        # atomic test-and-set: two racing callers (session construction vs
        # a tool's explicit start) must not spawn two exporter threads
        # both rewriting the same file (the PR 7 snapshot-race shape)
        with self._lock:
            if getattr(self, "_exporter", None):
                return
            self._exporter = (t, stop, path)
        t.start()

    def stop_export(self) -> None:
        """Stop the exporter and write one final *synchronous* snapshot.

        The final export happens on the caller's thread, not the daemon
        thread: a daemon thread racing process exit can die before its
        last write, leaving the export file stale or absent (the round-1
        flake).  Joining then exporting inline makes the file's final
        content a postcondition of stop_export()."""
        with self._lock:
            exp, self._exporter = getattr(self, "_exporter", None), None
        if exp:
            # join OUTSIDE the lock: the exporter loop's export() takes
            # it for the snapshot, and a held lock would deadlock here
            t, stop, path = exp
            stop.set()
            t.join(timeout=5.0)
            self.export(path)

    def add_export_hook(self, fn) -> None:
        """Register a pre-export callback (idempotent).  The engine uses
        this to fold live native-engine counter deltas into the registry
        right before each publish — without it an io_uring-backed
        workload would export zeros until stat_info/close (found driving
        `tpu_stat -l` against an unmodified workload, round 5)."""
        with self._lock:
            hooks = getattr(self, "_export_hooks", None)
            if hooks is None:
                hooks = self._export_hooks = []
            if fn not in hooks:
                hooks.append(fn)

    def export(self, path: str = None) -> None:
        path = path or DEFAULT_STAT_EXPORT
        for fn in list(getattr(self, "_export_hooks", ())):
            try:
                fn()
            except Exception:   # noqa: BLE001 — publish must not die
                pass
        # the exporter is the SINGLE resetter of the max_dma_count
        # high-water mark: every reader sees the same per-interval peak
        # instead of racing concurrent read-and-resets
        snap = self.snapshot(debug=True, reset_max=True)
        payload = {"timestamp_ns": snap.timestamp_ns, "pid": os.getpid(),
                   "version": snap.version, "counters": snap.counters,
                   "backend": self.backend(),
                   "members": self.member_snapshot(),
                   "lat_hist": self.lat_hist_snapshot(),
                   "tenants": self.tenant_snapshot(),
                   "shards": self.shard_snapshot()}
        try:
            # mkstemp: O_EXCL private temp (no symlink following in shared
            # /tmp), then atomic replace
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                                       prefix=os.path.basename(path) + ".")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(payload, f)
                os.replace(tmp, path)
            except BaseException:
                os.unlink(tmp)
                raise
        except OSError:
            self._export_errors = getattr(self, "_export_errors", 0) + 1

    def merge_native(self, native_counters: dict) -> None:
        """Fold a native-engine *monotonic* counter delta into this registry.

        Gauges (cur/max_dma_count) are never merged here: the Python path
        owns its own in-flight accounting and a native engine's gauge must
        not clobber it — callers combine gauges at snapshot time instead."""
        with self._lock:
            for k, v in native_counters.items():
                if k in self._c and k not in ("cur_dma_count", "max_dma_count",
                                              "cache_resident_bytes",
                                              "resync_pending_bytes",
                                              "hbm_resident_bytes",
                                              "coldstart_bytes_per_sec",
                                              "cache_unpinned_bytes"):
                    self._c[k] += v


#: process-global registry (the reference's counters are module-global too)
stats = StatRegistry()
