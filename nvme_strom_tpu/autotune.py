"""Self-driving data path (ISSUE 18): per-session online autotuner +
trace-driven predictive readahead.

Every hot-path knob the engine grew across PRs 4-17 (``submit_window``,
the per-member chunk cap, ``hedge_ms``, lane count) is a static config
Var, while the observability stack already measures everything a
controller needs.  This module closes the sensors->knobs loop:

* **AutoTuner** — one controller per :class:`~.engine.Session`.  Each
  epoch (``autotune_interval_ms``) it samples the global and per-member
  latency-histogram deltas plus the delivered-byte delta, and feeds a
  :class:`HillClimber` that adjusts, per stripe member, the effective
  submit window (which is also the member's executor-lane width on the
  Python path), the chunk/coalesce cap, and the hedge latch — plus the
  global native lane count at engine-rebuild boundaries.  All bounds
  come from each Var's declared ``minval``/``maxval`` (the stromlint
  ``config-bounds`` rule makes an unbounded controlled knob a finding).
  When the fault ladder has any member in suspect/quarantined/rejoining
  the controller FREEZES — it never fights the health machine.
* **ReadaheadPredictor** — per-source stride + extent-graph successor
  detection over recent demand submit spans.  Predictions are issued as
  bounded speculative fills into the PR 9 residency tier through the
  normal fault ladder, budgeted by a :class:`~.daemon.qos.TokenBucket`
  (``readahead_budget_mb_s``) so prefetch can never starve demand
  reads; speculative fills are provenance-tagged so the ARC ghost lists
  are never trained by speculation (cache.py).

Both halves follow the flight recorder's one-branch-when-off contract:
``autotune``/``readahead`` are read once at Session construction and
the engine hot paths test plain attributes.  The satellite fold of the
per-member :class:`~.engine.AdaptiveChunkSizer` lives in
:meth:`AutoTuner.chunk_cap`: the tuner hosts the sizer dict and is the
single writer of the effective chunk cap — ``autotune=off`` preserves
the sizer's halve/restore behavior bit-for-bit.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

from .cache import residency_cache as _rcache
from .config import config
from .daemon.qos import TokenBucket
from .stats import hist_percentiles, stats
from .trace import recorder as _trace

__all__ = ["Reading", "KnobFamily", "HillClimber", "ReadaheadPredictor",
           "AutoTuner"]


class Reading:
    """One epoch's sensor deltas.

    ``throughput`` is delivered bytes per nanosecond of wall clock over
    the epoch (only ratios between epochs matter), ``p99_ns`` the worst
    per-member p99 service latency from the histogram deltas (global
    histogram when no member delta has mass), ``nreq`` the completed
    request count — 0 marks an idle epoch the climber must not
    attribute a probe to."""

    __slots__ = ("throughput", "p99_ns", "nreq")

    def __init__(self, throughput: float = 0.0,
                 p99_ns: Optional[int] = None, nreq: int = 0) -> None:
        self.throughput = float(throughput)
        self.p99_ns = p99_ns
        self.nreq = int(nreq)

    @property
    def idle(self) -> bool:
        return self.nreq <= 0


class KnobFamily:
    """One controlled knob across stripe members.

    Hard bounds come from the backing Var's declared minval/maxval;
    steps are geometric (x2 / /2) and clamp per member, so members can
    diverge only at the bounds.  ``armed=False`` (e.g. the hedge latch
    under ``hedge_policy=off``) removes the family from probing without
    losing its state."""

    __slots__ = ("name", "lo", "hi", "integral", "armed", "values")

    def __init__(self, name: str, lo: float, hi: float, *,
                 integral: bool = True) -> None:
        self.name = name
        self.lo = float(lo)
        self.hi = float(hi)
        self.integral = bool(integral)
        self.armed = True
        self.values: Dict[int, float] = {}

    def _clamp(self, v: float) -> float:
        v = min(max(v, self.lo), self.hi)
        return float(int(v)) if self.integral else v

    def ensure(self, member: int, initial: float) -> None:
        if member not in self.values:
            self.values[member] = self._clamp(float(initial))

    def stepped(self, direction: str) -> Dict[int, float]:
        """{member: new value} for one geometric step; empty when every
        member is already pinned at the relevant bound."""
        out: Dict[int, float] = {}
        for m, v in self.values.items():
            nv = self._clamp(v * 2.0 if direction == "up" else v / 2.0)
            if nv != v:
                out[m] = nv
        return out


class HillClimber:
    """Pure hill-climb policy: knob families + epoch readings in,
    step/revert/freeze decisions out.  No session coupling, so unit
    tests drive it with synthetic readings (tests/test_autotune.py).

    Probe lifecycle (two epochs per decision):

    * epoch N — apply one geometric probe on one (family, direction);
    * epoch N+1 — compare the reading against the pre-probe baseline.
      An accepted probe (throughput gain >= ``min_gain`` with p99
      within ``p99_tol`` x baseline) keeps climbing the same direction
      immediately; a rejection or p99 regression steps BACK and marks
      the (family, direction) pair rejected at that value.

    Rejected markers are the hysteresis: a settled trajectory never
    re-probes a direction whose outcome it has already measured at the
    current operating point, so it cannot oscillate (the
    no-reversals-in-the-last-epochs contract the autotune-gate
    asserts).  Accepted steps also mark the opposite direction rejected
    — the climb just came from there and measured it worse.  Idle
    epochs defer evaluation; a freeze (the health machine owns the
    stripe) reverts any outstanding probe and suspends probing, while
    rejected markers survive the freeze."""

    def __init__(self, families: List[KnobFamily], *,
                 min_gain: float = 0.05, p99_tol: float = 1.5,
                 cooldown: int = 4) -> None:
        self.families = list(families)
        self.min_gain = float(min_gain)
        self.p99_tol = float(p99_tol)
        self.cooldown = int(cooldown)
        #: per-epoch event tuples — the gate's knob-trajectory record
        self.history: List[list] = []
        self._probe: Optional[tuple] = None  # (family, dir, {m: old})
        self._baseline: Optional[Reading] = None
        self._cooldown: Dict[Tuple[str, str], int] = {}
        self._rejected: Dict[Tuple[str, str], Dict[int, float]] = {}

    def family(self, name: str) -> Optional[KnobFamily]:
        for fam in self.families:
            if fam.name == name:
                return fam
        return None

    def step(self, reading: Reading, *, frozen: bool = False) -> List[tuple]:
        """One epoch: returns [(kind, family, direction, values)] with
        kind in step/revert/freeze (values is {member: applied value},
        None for freeze)."""
        events: List[tuple] = []
        for k in [k for k, v in self._cooldown.items() if v <= 1]:
            del self._cooldown[k]
        for k in self._cooldown:
            self._cooldown[k] -= 1
        if frozen:
            if self._probe is not None:
                fam, d, olds = self._probe
                fam.values.update(olds)
                self._probe = None
                events.append(("revert", fam.name, d, dict(olds)))
            self._baseline = None
            events.append(("freeze", None, None, None))
            self.history.append(events)
            return events
        if reading.idle:
            # no traffic: nothing to attribute an outstanding probe to
            self.history.append(events)
            return events
        if self._probe is not None:
            events.extend(self._evaluate(reading))
        else:
            self._baseline = reading
            ev = self._try_probe()
            if ev is not None:
                events.append(ev)
        self.history.append(events)
        return events

    def _evaluate(self, reading: Reading) -> List[tuple]:
        fam, d, olds = self._probe
        self._probe = None
        base = self._baseline
        gain = (reading.throughput / base.throughput
                if base is not None and base.throughput > 0 else 0.0)
        p99_bad = bool(base is not None and base.p99_ns and reading.p99_ns
                       and reading.p99_ns > base.p99_ns * self.p99_tol)
        if gain >= 1.0 + self.min_gain and not p99_bad:
            # accepted: the opposite direction is now measured-worse
            opp = "down" if d == "up" else "up"
            self._rejected[(fam.name, opp)] = dict(fam.values)
            self._rejected.pop((fam.name, d), None)
            self._baseline = reading
            nxt = self._apply(fam, d)
            return [("step", fam.name, d, nxt)] if nxt else []
        fam.values.update(olds)
        self._rejected[(fam.name, d)] = dict(olds)
        self._cooldown[(fam.name, d)] = self.cooldown
        self._baseline = reading
        return [("revert", fam.name, d, dict(olds))]

    def _try_probe(self) -> Optional[tuple]:
        for fam in self.families:
            if not fam.armed or not fam.values:
                continue
            for d in ("up", "down"):
                key = (fam.name, d)
                if key in self._cooldown:
                    continue
                rej = self._rejected.get(key)
                if rej is not None and rej == fam.values:
                    continue
                nxt = self._apply(fam, d)
                if nxt:
                    return ("step", fam.name, d, nxt)
        return None

    def _apply(self, fam: KnobFamily, d: str) -> Optional[Dict[int, float]]:
        """Apply one geometric step on *fam* as the outstanding probe;
        None when every member is pinned at the bound."""
        olds = dict(fam.values)
        stepped = fam.stepped(d)
        if not stepped:
            return None
        fam.values.update(stepped)
        self._probe = (fam, d, olds)
        return dict(fam.values)


class ReadaheadPredictor:
    """Access-pattern model for one source, in chunk-grid units.

    A constant-stride detector over the last three demand spans (equal
    stride AND equal extent) predicts the next span; non-strided but
    repeating walks fall back to an extent-graph successor table — the
    last observed follower of each span start."""

    __slots__ = ("_recent", "_succ")

    def __init__(self) -> None:
        self._recent: deque = deque(maxlen=8)   # (first_chunk, nchunks)
        self._succ: Dict[int, Tuple[int, int]] = {}

    def observe(self, first: int, nchunks: int) -> None:
        if self._recent:
            pf, _pn = self._recent[-1]
            if first != pf:
                self._succ[pf] = (int(first), int(nchunks))
                if len(self._succ) > 512:
                    self._succ.pop(next(iter(self._succ)))
        self._recent.append((int(first), int(nchunks)))

    def predict(self) -> Optional[Tuple[int, int]]:
        r = self._recent
        if len(r) >= 3:
            (f0, n0), (f1, n1), (f2, n2) = r[-3], r[-2], r[-1]
            s = f2 - f1
            if s != 0 and f1 - f0 == s and n0 == n1 == n2:
                return f2 + s, n2
        if r:
            return self._succ.get(r[-1][0])
        return None


class AutoTuner:
    """Per-session controller thread: sensors -> knobs, plus the
    predictive-readahead issue loop.

    ``autotune``/``readahead``/``autotune_interval_ms``/
    ``readahead_budget_mb_s`` are read once at Session construction
    (the recorder/cache configure() convention); with both off the
    session pays one predicted branch per hot-path site and no thread
    is spawned.  ``step_epoch()`` is public so the autotune-gate and
    tests drive epochs synchronously and deterministically."""

    #: token-bucket burst: this many seconds of budget may be issued
    #: back-to-back before shaping bites (floor 1 MiB)
    BURST_S = 0.25
    #: executor-lane width ceiling the window knob may drive a member
    #: pool to (native lanes are separately capped at 16 rings)
    MAX_POOL_WIDTH = 64
    #: chunks per speculative fill ceiling (one fill never outweighs a
    #: demand task's planning slice)
    MAX_PREFETCH_CHUNKS = 64

    def __init__(self, session) -> None:
        self._sess = session
        self.enabled = bool(config.get("autotune"))
        self.ra_active = bool(config.get("readahead"))
        self.active = self.enabled or self.ra_active
        self.interval_s = max(float(config.get("autotune_interval_ms")),
                              10.0) / 1e3
        #: the per-member AdaptiveChunkSizer dict (PR 4/5), hosted HERE
        #: so the controller is the single writer of the effective chunk
        #: cap; Session._chunk_sizers aliases this dict for test access
        self.chunk_sizers: Dict[int, object] = {}
        self.freeze_reason = ""
        self.last_step = ""
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # applied per-member knob values (hot paths read these dicts
        # directly; epoch application keeps them in sync with the
        # climber's family values)
        self._windows: Dict[int, int] = {}
        self._caps: Dict[int, int] = {}
        self._hedges: Dict[int, float] = {}
        self._last_sample: Optional[tuple] = None
        self._climber: Optional[HillClimber] = None
        if self.enabled:
            self._climber = self._make_climber()
        dvar = config.describe().get("dma_max_size")
        self._dma_lo = int(dvar.minval) if dvar and dvar.minval else 4 << 10
        self._dma_hi = int(dvar.maxval) if dvar and dvar.maxval else 16 << 20
        # readahead state: id(source) -> (weakref, predictor, chunk_size)
        self._predictors: Dict[int, tuple] = {}
        self._issued: deque = deque(maxlen=256)
        self._issued_set: set = set()
        self._ra_rate = float(config.get("readahead_budget_mb_s")) * (1 << 20)
        self._bucket = TokenBucket(
            self._ra_rate, max(self._ra_rate * self.BURST_S, 1 << 20))

    # -- controller policy wiring -------------------------------------

    @staticmethod
    def _make_climber() -> HillClimber:
        vars_ = config.describe()

        def bounds(name: str, lo: float, hi: float) -> Tuple[float, float]:
            v = vars_.get(name)
            if v is not None:
                if v.minval is not None:
                    lo = float(v.minval)
                if v.maxval is not None:
                    hi = float(v.maxval)
            return lo, hi

        wlo, whi = bounds("submit_window", 1, 256)
        clo, chi = bounds("coalesce_limit", 0, 256 << 20)
        dlo, _dhi = bounds("dma_max_size", 4 << 10, 16 << 20)
        hlo, hhi = bounds("hedge_ms", 0.0, 60000.0)
        return HillClimber([
            KnobFamily("window", max(wlo, 1.0), whi),
            KnobFamily("cap", max(clo, dlo), chi),
            KnobFamily("hedge_ms", max(hlo, 1.0), hhi, integral=False),
        ])

    def _applied(self, fname: str) -> dict:
        return {"window": self._windows, "cap": self._caps,
                "hedge_ms": self._hedges}[fname]

    def _seed_members(self) -> None:
        """Arm knob families for every member the stats registry has
        seen (member 0 always exists), at the current static values —
        the controller starts where the operator's config sits."""
        members = set(stats.member_snapshot()) | {0}
        init = {"window": float(max(int(config.get("submit_window")), 1)),
                "cap": float(int(config.get("dma_max_size"))),
                "hedge_ms": float(config.get("hedge_ms"))}
        for fam in self._climber.families:
            v0 = init[fam.name]
            for m in members:
                if m not in fam.values:
                    fam.ensure(m, v0)
                    applied = self._applied(fam.name)
                    applied[m] = int(fam.values[m]) if fam.integral \
                        else fam.values[m]
            if fam.name == "hedge_ms":
                # never probe a knob with no effect: the hedge latch is
                # dead weight under hedge_policy=off
                fam.armed = str(config.get("hedge_policy")) != "off"

    # -- sensors -------------------------------------------------------

    def _read_sensors(self) -> Reading:
        """Epoch deltas of delivered bytes, the global service-latency
        histogram, and every per-member histogram (worst member p99 is
        the regression signal; the global histogram covers the Python
        pool path, whose per-member service times feed the aggregate)."""
        now = time.monotonic_ns()
        counters = stats.snapshot(debug=True, reset_max=False).counters
        total = counters.get("total_dma_length", 0)
        hist = stats.lat_hist_snapshot()
        mh = stats.member_hist_snapshot()
        last, self._last_sample = self._last_sample, (now, total, hist, mh)
        if last is None:
            return Reading(0.0, None, 0)
        dt = max(now - last[0], 1)
        dbytes = total - last[1]
        dh = [a - b for a, b in zip(hist, last[2])]
        nreq = sum(dh)
        p99 = None
        for m, h in mh.items():
            prev = last[3].get(m)
            dm = [a - b for a, b in zip(h, prev)] if prev else list(h)
            if sum(dm):
                mp99 = hist_percentiles(dm, (0.99,))[0]
                if mp99 and (p99 is None or mp99 > p99):
                    p99 = mp99
        if p99 is None and nreq:
            p99 = hist_percentiles(dh, (0.99,))[0]
        return Reading(dbytes / dt, p99, nreq)

    def _health_freeze(self) -> bool:
        """Freeze predicate: the controller never fights the fault
        ladder — any member off plain HEALTHY suspends probing."""
        try:
            bad = self._sess._member_health.unhealthy_members()
        except Exception:   # noqa: BLE001 — sensors must not kill tuning
            bad = []
        if bad:
            m, state = bad[0]
            self.freeze_reason = f"member {m} {state}"
            return True
        self.freeze_reason = ""
        return False

    # -- epoch ---------------------------------------------------------

    def step_epoch(self) -> None:
        """One controller epoch: sample sensors, run the climber, apply
        knob movements, then run one readahead issue pass.  Public so
        the gate and unit tests drive it synchronously; the background
        thread calls exactly this."""
        if self.enabled:
            self._tune_epoch()
        if self.ra_active:
            self.readahead_tick()

    def _tune_epoch(self) -> None:
        self._seed_members()
        reading = self._read_sensors()
        frozen = self._health_freeze()
        events = self._climber.step(reading, frozen=frozen)
        for kind, fname, direction, vals in events:
            if kind == "freeze":
                stats.add("nr_autotune_freeze")
                if _trace.active:
                    _trace.instant("autotune_step",
                                   args={"dir": "freeze",
                                         "reason": self.freeze_reason})
                continue
            stats.add("nr_autotune_step" if kind == "step"
                      else "nr_autotune_revert")
            self.last_step = f"{fname}:{direction}" \
                + (" (revert)" if kind == "revert" else "")
            self._apply(fname, direction, vals, kind)
            if _trace.active:
                _trace.instant(
                    "autotune_step",
                    args={"knob": fname, "dir": direction, "kind": kind,
                          "values": {str(m): v for m, v in vals.items()}})
        self._publish_knobs()

    def _apply(self, fname: str, direction: str, vals: Dict[int, float],
               kind: str) -> None:
        applied = self._applied(fname)
        retire: List[int] = []
        for m, v in vals.items():
            nv = float(v) if fname == "hedge_ms" else int(v)
            if applied.get(m) != nv:
                applied[m] = nv
                if fname == "window":
                    retire.append(m)
        sess = self._sess
        for m in retire:
            # the member's executor lane is recreated at the tuned
            # width on its next submit; queued work drains on the old
            try:
                sess._retire_member_pool(m)
            except Exception:   # noqa: BLE001 — knobs must not kill I/O
                pass
        if fname == "window" and kind == "step" and direction == "up" \
                and retire:
            # engine-rebuild boundary: give the native engine one lane
            # per unit of tuned concurrency, up to its 16-ring cap
            try:
                sess._autotune_scale_lanes(max(self._windows.values()))
            except Exception:   # noqa: BLE001
                pass

    def _publish_knobs(self) -> None:
        for m in self._windows:
            stats.member_knobs(m, window=self._windows.get(m),
                               cap=self._caps.get(m),
                               hedge_ms=self._hedges.get(m),
                               step=self.last_step,
                               freeze=self.freeze_reason)

    # -- effective knobs (engine indirection) --------------------------

    def submit_window(self, default: int) -> int:
        """Effective planning-slice width (max across members: the
        slice is a per-task global while lane widths are per member)."""
        w = self._windows
        return max(w.values()) if w else default

    def pool_width(self, member: int, default: int) -> int:
        """Tuned executor-lane width for *member* (the real concurrency
        bound on the Python path), clamped to MAX_POOL_WIDTH."""
        if not self.enabled:
            return default
        v = self._windows.get(member)
        return default if v is None else max(1, min(int(v),
                                                    self.MAX_POOL_WIDTH))

    def dma_cap(self, default: int) -> int:
        """Effective request split/coalesce cap for the planner, from
        the tuned per-member caps (max), inside dma_max_size's declared
        bounds."""
        caps = self._caps
        if not caps:
            return default
        return max(self._dma_lo, min(max(caps.values()), self._dma_hi))

    def hedge_delay(self, member: int, base_s: float) -> float:
        """Tuned hedge latch for *member* in seconds; the health
        machine's policy decision (None = no hedging) stays upstream."""
        v = self._hedges.get(member)
        return base_s if v is None else max(float(v), 1.0) / 1e3

    def chunk_cap(self, floor: int, limit: int, member: int = 0) -> int:
        """Single writer of the effective chunk cap (satellite fold of
        the PR 4/5 AdaptiveChunkSizer): the sizer stays the burst
        halve/restore policy, the tuner supplies its ceiling.  With
        ``autotune=off`` this is bit-for-bit the old Session._adaptive_cap."""
        if self.enabled:
            tuned = self._caps.get(member)
            if tuned is not None:
                limit = max(floor, int(tuned))
        szr = self.chunk_sizers.get(member)
        if szr is None or szr.floor != floor or szr.limit != limit:
            from .engine import AdaptiveChunkSizer
            szr = self.chunk_sizers[member] = AdaptiveChunkSizer(floor, limit)
        return szr.effective

    # -- predictive readahead ------------------------------------------

    def observe_submit(self, source, chunk_size: int, chunk_ids) -> None:
        """Feed one demand submit span (engine hot path; called only
        when ``ra_active`` and never for speculative tasks, so the
        predictor cannot train on its own prefetches)."""
        sid = id(source)
        ent = self._predictors.get(sid)
        if ent is None or ent[0]() is not source or ent[2] != chunk_size:
            if len(self._predictors) >= 64:
                self._gc_predictors()
            try:
                ref = weakref.ref(source)
            except TypeError:
                return
            ent = (ref, ReadaheadPredictor(), int(chunk_size))
            self._predictors[sid] = ent
        ent[1].observe(min(chunk_ids), len(chunk_ids))

    def _gc_predictors(self) -> None:
        for sid in [s for s, e in self._predictors.items() if e[0]() is None]:
            del self._predictors[sid]

    def readahead_tick(self) -> None:
        """One issue pass: predict per source, drop already-resident
        and already-issued spans, then fill through the normal fault
        ladder under the token-bucket budget — over-budget predictions
        are SKIPPED (counted), never blocked on, so prefetch cannot
        starve demand reads."""
        if not self.ra_active or not _rcache.active:
            return
        now = time.monotonic()
        for sid, (wref, pred, cs) in list(self._predictors.items()):
            src = wref()
            if src is None:
                self._predictors.pop(sid, None)
                continue
            p = pred.predict()
            if p is None:
                continue
            first, n = p
            try:
                size = int(src.size)
            except Exception:   # noqa: BLE001 — source may be closing
                continue
            total = (size + cs - 1) // cs
            if first < 0 or first >= total:
                continue
            n = max(1, min(int(n), total - first, self.MAX_PREFETCH_CHUNKS))
            key = (sid, first, n)
            if key in self._issued_set:
                continue
            skey = _rcache.source_key(src)
            ids = [cid for cid in range(first, first + n)
                   if not _rcache.peek(skey, cid * cs,
                                       min(cs, size - cid * cs))]
            if not ids:
                self._remember(key)
                continue
            nbytes = sum(min(cs, size - cid * cs) for cid in ids)
            if self._ra_rate <= 0 \
                    or self._bucket.ready_in(nbytes, now) > 0:
                # budget exhausted (or budget 0 = predict-only): skip,
                # never wait — demand reads own the device time
                stats.add("nr_readahead_skip")
                continue
            self._bucket.consume(nbytes, now)
            self._remember(key)
            self._prefetch(src, ids, cs, nbytes)

    def _remember(self, key: tuple) -> None:
        if len(self._issued) == self._issued.maxlen:
            self._issued_set.discard(self._issued[0])
        self._issued.append(key)
        self._issued_set.add(key)

    def _prefetch(self, src, ids: List[int], cs: int, nbytes: int) -> None:
        sess = self._sess
        t0 = time.monotonic_ns()
        try:
            handle, _buf = sess.alloc_dma_buffer(len(ids) * cs)
        except Exception:   # noqa: BLE001 — allocation pressure: skip
            stats.add("nr_readahead_skip")
            return
        try:
            res = sess.memcpy_ssd2ram(src, handle, ids, cs,
                                      speculative=True)
            sess.memcpy_wait(res.dma_task_id, timeout=60.0)
            stats.add("nr_readahead_fill")
            stats.add("bytes_readahead", nbytes)
            if _trace.active:
                _trace.span("readahead_fill", t0, time.monotonic_ns(),
                            offset=ids[0] * cs, length=nbytes,
                            args={"chunks": len(ids)})
        except Exception:   # noqa: BLE001 — prefetch must never surface
            pass            # errors; demand reads retry through the ladder
        finally:
            try:
                sess.unmap_buffer(handle)
            except Exception:   # noqa: BLE001
                pass

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        """Spawn the controller thread (no-op with both halves off)."""
        if not self.active or self._thread is not None:
            return
        t = threading.Thread(target=self._loop, daemon=True,
                             name="strom-autotune")
        self._thread = t
        t.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step_epoch()
            except Exception:   # noqa: BLE001 — the controller must
                pass            # never take the data path down with it

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
