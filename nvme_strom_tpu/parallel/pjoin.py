"""Partitioned hash join over the device mesh (all_to_all repartition).

The scale-out face of :mod:`..ops.join`: the broadcast join replicates the
whole build side on every device, which stops working when the dimension
table approaches HBM size.  Here **both sides repartition by key hash**
instead — the classic distributed hash join, mapped TPU-first:

* the build side hash-splits across the ``dp`` axis at setup (each device
  holds ~1/dp of it, sorted, as a sharded array — not a broadcast
  constant);
* each scanned fact batch routes rows to their key's owner device with
  the MoE-style :func:`..parallel.exchange.bucket_dispatch` all_to_all;
* each device probes only its local partition with the same vectorized
  ``searchsorted`` discipline as the broadcast kernel, and the per-batch
  aggregates ``psum`` back over ``dp``.

Capacity is set to the full per-device batch (a join must not drop rows,
unlike MoE token dispatch), so the exchange is always lossless; HBM cost
per device is build/dp + one batch slab — the degrade-instead-of-OOM
contract (VERDICT r2 missing #7 / next #8).

The reference has no analog (its joins happened in PostgreSQL above the
scan, `pgsql/nvme_strom.c` hands tuples up); this is where the TPU
framework's mesh collectives earn the capability.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import shard_map
from ..api import StromError

from ..ops.filter_xla import decode_pages, global_row_positions
from ..ops.join import _emit_mask, _sorted_build, check_join_how, key_hash32
from ..scan.heap import HeapSchema
from .exchange import bucket_dispatch

__all__ = ["make_partitioned_join_step", "make_partitioned_join_rows_step",
           "partition_build_sharded", "partition_build_sharded_from_table",
           "combine_pos_words"]

_I32_MAX = np.int32((1 << 31) - 1)


def partition_build_sharded(build_keys, build_values, mesh: Mesh,
                            schema: HeapSchema, probe_col: int):
    """Hash-partition the (validated) build table across ``dp`` and place
    it as sharded device arrays.

    Returns ``(keys_dev, vals_dev, nreal_dev)`` with shapes
    ``(dp, cap)`` / ``(dp, cap)`` / ``(dp, 1)``, sharded ``P("dp", ...)``:
    partition ``p`` = keys whose ``key_hash32 % dp == p``, sorted
    ascending, padded to the max partition size with ``INT32_MAX`` keys;
    ``nreal`` masks the pads out of probe hits (a genuine INT32_MAX key
    still matches — it sorts before the pads, searchsorted finds it
    first)."""
    bk, bv = _sorted_build(build_keys, build_values, schema, probe_col)
    dp = mesh.shape["dp"]
    part = (key_hash32(bk) % np.uint32(dp)).astype(np.int64)
    sizes = np.bincount(part, minlength=dp)
    cap = max(1, int(sizes.max()))
    keys_p = np.full((dp, cap), _I32_MAX, np.int32)
    vals_p = np.zeros((dp, cap), bv.dtype)   # payload keeps its dtype
    for p in range(dp):
        sel = part == p
        n = int(sizes[p])
        keys_p[p, :n] = bk[sel]   # bk already sorted -> slices stay sorted
        vals_p[p, :n] = bv[sel]
    nreal = sizes.astype(np.int32).reshape(dp, 1)
    sh2 = NamedSharding(mesh, P("dp", None))
    # make_array_from_callback: every process computes the identical
    # partition tables from the (replicated) host build side and places
    # only its ADDRESSABLE rows.  device_put with a global sharding also
    # works (jax replicates host data across processes); this form just
    # states the per-process placement explicitly, matching the
    # checkpoint harness's pattern.
    return tuple(
        jax.make_array_from_callback(a.shape, sh2, lambda i, a=a: a[i])
        for a in (keys_p, vals_p, nreal))


def partition_build_sharded_from_table(table_path: str, build_schema,
                                       key_col: int, value_col: int,
                                       mesh: Mesh, *,
                                       session=None, device=None,
                                       budget: Optional[int] = None):
    """Hash-partitioned build side STREAMED from an on-disk heap table
    (VERDICT r3 #8): host RAM during setup is bounded to one partition
    plus a scan batch, not the dp x cap full-table materialization of
    :func:`partition_build_sharded`.

    When the build table is at most *budget* bytes (config
    ``join_build_host_max`` by default), it is loaded with ONE projection
    scan and handed to the in-memory partitioner (fast path — the extra
    scans below buy nothing a budget-sized table needs).  Above the
    budget, the Grace discipline the local join already applies to probe
    passes is applied to the BUILD: one streamed counting scan sizes the
    partitions, then each ADDRESSABLE partition is built by its own
    predicate-pushdown scan (only rows hashing to that partition are
    collected), sorted, padded, and placed directly on its owner device —
    the bounded buffer-pool discipline of the reference's scan tier,
    ``pgsql/nvme_strom.c:1186-1260``, applied to join setup.

    Returns ``(keys_dev, vals_dev, nreal_dev)`` with the exact layout of
    :func:`partition_build_sharded` (bit-identical partitions: same hash,
    same sort, same padding), for ``build_parts=`` of the step factories.
    """
    from ..config import config
    from ..scan.query import Query
    dp = mesh.shape["dp"]
    dt_k = build_schema.col_dtype(key_col)
    if dt_k != np.dtype(np.int32):
        raise ValueError("build key column must be int32")
    dt_v = build_schema.col_dtype(value_col)
    if budget is None:
        budget = int(config.get("join_build_host_max"))
    table_bytes = os.path.getsize(table_path)
    if table_bytes <= budget:
        out = Query(table_path, build_schema) \
            .select([key_col, value_col]).run(session=session,
                                              device=device)
        # in-memory partitioner (validates key uniqueness)
        return partition_build_sharded(
            out[f"col{key_col}"], out[f"col{value_col}"], mesh,
            build_schema, key_col)

    def owner(cols):
        return (key_hash32(cols[key_col]) % jnp.uint32(dp)) \
            .astype(jnp.int32)

    # pass 0: partition sizes (streamed GROUP BY on the owner hash) —
    # cap must be the GLOBAL max so every device's slab shape agrees
    sizes_out = Query(table_path, build_schema).group_by(
        owner, dp, agg_cols=[value_col]).run(session=session,
                                             device=device)
    sizes = np.asarray(sizes_out["count"]).reshape(-1).astype(np.int64)
    cap = max(1, int(sizes.max()))

    sh2 = NamedSharding(mesh, P("dp", None))
    idx_map = sh2.addressable_devices_indices_map((dp, cap))
    kshards, vshards, nshards = [], [], []
    for dev, idx in idx_map.items():
        p = idx[0].start or 0
        # one bounded scan per addressable partition: ONLY rows hashing
        # to p are collected (predicate pushdown), then sorted stably —
        # identical ordering contract to the in-memory path
        part = Query(table_path, build_schema) \
            .where(lambda cols, p=p: owner(cols) == p) \
            .select([key_col, value_col]) \
            .run(session=session, device=device)
        pk = np.asarray(part[f"col{key_col}"], np.int32)
        pv = np.asarray(part[f"col{value_col}"], dt_v)
        if len(np.unique(pk)) != len(pk):
            raise ValueError("build_keys must be unique (inner join on "
                             "a dimension key)")
        order = np.argsort(pk, kind="stable")
        n = len(pk)
        if n != int(sizes[p]):
            raise StromError(5, f"build table changed between passes "
                                f"(partition {p}: {n} != {sizes[p]})")
        kp = np.full(cap, _I32_MAX, np.int32)
        vp = np.zeros(cap, dt_v)
        kp[:n] = pk[order]
        vp[:n] = pv[order]
        kshards.append(jax.device_put(kp[None], dev))
        vshards.append(jax.device_put(vp[None], dev))
        nshards.append(jax.device_put(
            np.array([[n]], np.int32), dev))
    mk = jax.make_array_from_single_device_arrays
    return (mk((dp, cap), sh2, kshards),
            mk((dp, cap), sh2, vshards),
            mk((dp, 1), sh2, nshards))


def make_partitioned_join_step(mesh: Mesh, schema: HeapSchema,
                               probe_col: int, build_keys=None,
                               build_values=None, *,
                               predicate: Optional[Callable] = None,
                               build_parts=None, how: str = "inner"):
    """Build ``step(global_pages) -> dict`` for
    :func:`..parallel.stream.distributed_scan_filter`: the partitioned
    join over one dp-sharded page batch.  Result contract matches
    :func:`..ops.join.make_join_fn` for the same *how* (``matched`` /
    ``sums`` / inner+left ``payload_sum`` / left ``null_count``,
    ``step.sum_cols``), so the two strategies are drop-in comparable.
    Every routed row reaches its key's owner exactly once, so the
    left/anti faces need no Grace ownership restriction here.

    ``build_parts`` — prebuilt ``(keys_dev, vals_dev, nreal_dev)`` from
    :func:`partition_build_sharded_from_table` (the bounded-host-RAM
    build); otherwise ``build_keys``/``build_values`` host arrays are
    partitioned in memory."""
    from ..ops.groupby import acc_dtypes
    check_join_how(how)
    dp = mesh.shape["dp"]
    keys_dev, vals_dev, nreal_dev = build_parts or \
        partition_build_sharded(build_keys, build_values, mesh, schema,
                                probe_col)
    sum_cols = list(range(schema.n_cols))
    col_dts = [schema.col_dtype(c) for c in sum_cols]
    accs = [acc_dtypes(dt)[0] for dt in col_dts]

    def _local(pages, keys_row, vals_row, nreal_row):
        cols, valid = decode_pages(pages, schema)
        sel = valid if predicate is None else valid & predicate(cols)
        probe = cols[probe_col].reshape(-1)
        sel_flat = sel.reshape(-1)

        def enc(c):
            # the exchange slab is int32-wide: float32/uint32 fact
            # columns travel BITCAST (value-preserving), not converted
            a = cols[c].reshape(-1)
            return a if a.dtype == jnp.int32 else \
                jax.lax.bitcast_convert_type(a, jnp.int32)

        rows = jnp.stack([probe] + [enc(c) for c in sum_cols], axis=-1)
        bucket = (key_hash32(probe) % jnp.uint32(dp)).astype(jnp.int32)
        n = probe.shape[0]
        # capacity = the full local batch: the exchange can never drop a
        # row, whatever the key skew (worst case: every row one owner)
        recv, recv_counts, _keep = bucket_dispatch(
            rows, bucket, sel_flat, dp, n)
        slot = jnp.arange(dp * n)
        rvalid = (slot % n) < recv_counts[slot // n]
        k = keys_row.reshape(-1)
        v = vals_row.reshape(-1)
        rk = recv[:, 0]
        idx = jnp.clip(jnp.searchsorted(k, rk), 0, k.shape[0] - 1)
        hit = rvalid & (idx < nreal_row[0]) & (k[idx] == rk)
        # only selected rows were dispatched, so among routed slots
        # rvalid IS the selection mask the broadcast kernel calls sel
        emit = _emit_mask(how, rvalid, hit)

        def dec(i):
            w = recv[:, 1 + i]
            dt = col_dts[i]
            return w if dt == np.dtype(np.int32) else \
                jax.lax.bitcast_convert_type(w, dt)

        out = {"matched": jax.lax.psum(
                   jnp.sum(emit.astype(jnp.int32)), "dp"),
               "sums": jax.lax.psum(
                   [jnp.sum(jnp.where(emit, dec(i), col_dts[i].type(0)),
                            dtype=accs[i])
                    for i in range(len(sum_cols))], "dp")}
        if how in ("inner", "left"):
            from ..ops.groupby import acc_dtypes as _adt
            out["payload_sum"] = jax.lax.psum(
                jnp.sum(jnp.where(hit, v[idx], v.dtype.type(0)),
                        dtype=_adt(np.dtype(v.dtype))[0]), "dp")
        if how == "left":
            out["null_count"] = jax.lax.psum(
                jnp.sum((emit & ~hit).astype(jnp.int32)), "dp")
        return out

    out_specs = {"matched": P(), "sums": [P()] * len(sum_cols)}
    if how in ("inner", "left"):
        out_specs["payload_sum"] = P()
    if how == "left":
        out_specs["null_count"] = P()
    shard_mapped = shard_map(
        _local, mesh=mesh,
        in_specs=(P("dp", None), P("dp", None), P("dp", None),
                  P("dp", None)),
        out_specs=out_specs)
    jitted = jax.jit(shard_mapped)

    def step(global_pages):
        return jitted(global_pages, keys_dev, vals_dev, nreal_dev)

    step.sum_cols = sum_cols
    return step


def combine_pos_words(lo: np.ndarray, hi: np.ndarray,
                      dtype=np.int64) -> np.ndarray:
    """Host-side reassembly of row positions routed through the int32
    exchange as (lo, hi) words — the exchange slab is int32-wide, so an
    int64 position (x64 mode) travels split and rejoins here; in int32
    mode ``hi`` is all zeros and this is the identity."""
    full = (lo.astype(np.uint32).astype(np.int64)
            | (hi.astype(np.int64) << 32))
    return full.astype(dtype)


def make_partitioned_join_rows_step(mesh: Mesh, schema: HeapSchema,
                                    probe_col: int, build_keys=None,
                                    build_values=None, *,
                                    predicate: Optional[Callable] = None,
                                    build_parts=None, how: str = "inner"):
    """Row-materializing twin of :func:`make_partitioned_join_step`
    (VERDICT r3 #3): same all_to_all routing, but instead of psum'ing
    aggregates each owner device reports the per-routed-row join outcome
    — ``hit`` mask, probed ``key``, matched build ``payload`` and the
    row's global position as (``pos_lo``, ``pos_hi``) int32 words — so
    the host compresses matched rows per batch exactly like the
    broadcast row face (:func:`..ops.join.make_join_rows_fn`), and
    ``join_broadcast_max`` never changes what a query can return (the
    reference's scan always hands tuples back to the executor,
    pgsql/nvme_strom.c:941-979).  *how* picks the emitted face exactly
    as in the broadcast kernel: ``hit`` is the EMIT mask; inner/left
    include ``payload``, and left adds ``partner`` (has-a-partner) —
    dropped columns are never computed, psum'd, or transferred.

    Positions ride the exchange alongside the key: the probe outcome
    lives on the key's owner device, not the scanning device, so the
    position must travel with the row.  ``step(global_pages) -> dict``
    of global ``(dp * dp * n_local,)`` arrays; rows where ``hit`` is
    False are routing pads or non-emitted rows.  ``build_parts`` as in
    :func:`make_partitioned_join_step`."""
    check_join_how(how)
    dp = mesh.shape["dp"]
    keys_dev, vals_dev, nreal_dev = build_parts or \
        partition_build_sharded(build_keys, build_values, mesh, schema,
                                probe_col)

    def _local(pages, keys_row, vals_row, nreal_row):
        cols, valid = decode_pages(pages, schema)
        sel = valid if predicate is None else valid & predicate(cols)
        probe = cols[probe_col].reshape(-1)
        sel_flat = sel.reshape(-1)
        pos = global_row_positions(pages, schema).reshape(-1)
        if pos.dtype == jnp.int64:
            w = jax.lax.bitcast_convert_type(pos, jnp.int32)   # (N, 2)
            pos_lo, pos_hi = w[:, 0], w[:, 1]
        else:
            pos_lo, pos_hi = pos, jnp.zeros_like(pos)
        rows = jnp.stack([probe, pos_lo, pos_hi], axis=-1)
        bucket = (key_hash32(probe) % jnp.uint32(dp)).astype(jnp.int32)
        n = probe.shape[0]
        # lossless exchange: capacity = the full local batch, as in the
        # aggregate step (a join must never drop rows)
        recv, recv_counts, _keep = bucket_dispatch(
            rows, bucket, sel_flat, dp, n)
        slot = jnp.arange(dp * n)
        rvalid = (slot % n) < recv_counts[slot // n]
        k = keys_row.reshape(-1)
        v = vals_row.reshape(-1)
        rk = recv[:, 0]
        idx = jnp.clip(jnp.searchsorted(k, rk), 0, k.shape[0] - 1)
        hit = rvalid & (idx < nreal_row[0]) & (k[idx] == rk)
        emit = _emit_mask(how, rvalid, hit)
        out = {"hit": emit, "key": rk,
               "pos_lo": recv[:, 1], "pos_hi": recv[:, 2]}
        # faces that drop a column never psum/D2H-transfer it (the
        # same per-how field set as Query._join_row_fields)
        if how in ("inner", "left"):
            out["payload"] = jnp.where(hit, v[idx], 0)
        if how == "left":
            out["partner"] = hit
        return out

    out_specs = {"hit": P("dp"), "key": P("dp"),
                 "pos_lo": P("dp"), "pos_hi": P("dp")}
    if how in ("inner", "left"):
        out_specs["payload"] = P("dp")
    if how == "left":
        out_specs["partner"] = P("dp")
    shard_mapped = shard_map(
        _local, mesh=mesh,
        in_specs=(P("dp", None), P("dp", None), P("dp", None),
                  P("dp", None)),
        out_specs=out_specs)
    jitted = jax.jit(shard_mapped)

    def step(global_pages):
        return jitted(global_pages, keys_dev, vals_dev, nreal_dev)

    return step
