"""Partitioned hash join over the device mesh (all_to_all repartition).

The scale-out face of :mod:`..ops.join`: the broadcast join replicates the
whole build side on every device, which stops working when the dimension
table approaches HBM size.  Here **both sides repartition by key hash**
instead — the classic distributed hash join, mapped TPU-first:

* the build side hash-splits across the ``dp`` axis at setup (each device
  holds ~1/dp of it, sorted, as a sharded array — not a broadcast
  constant);
* each scanned fact batch routes rows to their key's owner device with
  the MoE-style :func:`..parallel.exchange.bucket_dispatch` all_to_all;
* each device probes only its local partition with the same vectorized
  ``searchsorted`` discipline as the broadcast kernel, and the per-batch
  aggregates ``psum`` back over ``dp``.

Capacity is set to the full per-device batch (a join must not drop rows,
unlike MoE token dispatch), so the exchange is always lossless; HBM cost
per device is build/dp + one batch slab — the degrade-instead-of-OOM
contract (VERDICT r2 missing #7 / next #8).

The reference has no analog (its joins happened in PostgreSQL above the
scan, `pgsql/nvme_strom.c` hands tuples up); this is where the TPU
framework's mesh collectives earn the capability.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.filter_xla import decode_pages
from ..ops.join import _sorted_build, key_hash32
from ..scan.heap import HeapSchema
from .exchange import bucket_dispatch

__all__ = ["make_partitioned_join_step", "partition_build_sharded"]

_I32_MAX = np.int32((1 << 31) - 1)


def partition_build_sharded(build_keys, build_values, mesh: Mesh,
                            schema: HeapSchema, probe_col: int):
    """Hash-partition the (validated) build table across ``dp`` and place
    it as sharded device arrays.

    Returns ``(keys_dev, vals_dev, nreal_dev)`` with shapes
    ``(dp, cap)`` / ``(dp, cap)`` / ``(dp, 1)``, sharded ``P("dp", ...)``:
    partition ``p`` = keys whose ``key_hash32 % dp == p``, sorted
    ascending, padded to the max partition size with ``INT32_MAX`` keys;
    ``nreal`` masks the pads out of probe hits (a genuine INT32_MAX key
    still matches — it sorts before the pads, searchsorted finds it
    first)."""
    bk, bv = _sorted_build(build_keys, build_values, schema, probe_col)
    dp = mesh.shape["dp"]
    part = (key_hash32(bk) % np.uint32(dp)).astype(np.int64)
    sizes = np.bincount(part, minlength=dp)
    cap = max(1, int(sizes.max()))
    keys_p = np.full((dp, cap), _I32_MAX, np.int32)
    vals_p = np.zeros((dp, cap), np.int32)
    for p in range(dp):
        sel = part == p
        n = int(sizes[p])
        keys_p[p, :n] = bk[sel]   # bk already sorted -> slices stay sorted
        vals_p[p, :n] = bv[sel]
    nreal = sizes.astype(np.int32).reshape(dp, 1)
    sh2 = NamedSharding(mesh, P("dp", None))
    # make_array_from_callback: every process computes the identical
    # partition tables from the (replicated) host build side and places
    # only its ADDRESSABLE rows.  device_put with a global sharding also
    # works (jax replicates host data across processes); this form just
    # states the per-process placement explicitly, matching the
    # checkpoint harness's pattern.
    return tuple(
        jax.make_array_from_callback(a.shape, sh2, lambda i, a=a: a[i])
        for a in (keys_p, vals_p, nreal))


def make_partitioned_join_step(mesh: Mesh, schema: HeapSchema,
                               probe_col: int, build_keys, build_values, *,
                               predicate: Optional[Callable] = None):
    """Build ``step(global_pages) -> dict`` for
    :func:`..parallel.stream.distributed_scan_filter`: the partitioned
    join over one dp-sharded page batch.  Result contract matches
    :func:`..ops.join.make_join_fn` (``matched``/``sums``/``payload_sum``,
    ``step.sum_cols``), so the two strategies are drop-in comparable."""
    dp = mesh.shape["dp"]
    keys_dev, vals_dev, nreal_dev = partition_build_sharded(
        build_keys, build_values, mesh, schema, probe_col)
    sum_cols = [c for c in range(schema.n_cols)
                if schema.col_dtype(c) == np.dtype(np.int32)]
    width = 1 + len(sum_cols)

    def _local(pages, keys_row, vals_row, nreal_row):
        cols, valid = decode_pages(pages, schema)
        sel = valid if predicate is None else valid & predicate(cols)
        probe = cols[probe_col].reshape(-1)
        sel_flat = sel.reshape(-1)
        rows = jnp.stack(
            [probe] + [cols[c].reshape(-1) for c in sum_cols], axis=-1)
        bucket = (key_hash32(probe) % jnp.uint32(dp)).astype(jnp.int32)
        n = probe.shape[0]
        # capacity = the full local batch: the exchange can never drop a
        # row, whatever the key skew (worst case: every row one owner)
        recv, recv_counts, _keep = bucket_dispatch(
            rows, bucket, sel_flat, dp, n)
        slot = jnp.arange(dp * n)
        rvalid = (slot % n) < recv_counts[slot // n]
        k = keys_row.reshape(-1)
        v = vals_row.reshape(-1)
        rk = recv[:, 0]
        idx = jnp.clip(jnp.searchsorted(k, rk), 0, k.shape[0] - 1)
        hit = rvalid & (idx < nreal_row[0]) & (k[idx] == rk)
        matched = jax.lax.psum(jnp.sum(hit.astype(jnp.int32)), "dp")
        sums = jax.lax.psum(
            jnp.stack([jnp.sum(jnp.where(hit, recv[:, 1 + i], 0))
                       for i in range(len(sum_cols))]), "dp")
        payload = jax.lax.psum(jnp.sum(jnp.where(hit, v[idx], 0)), "dp")
        return {"matched": matched, "sums": sums, "payload_sum": payload}

    shard_mapped = jax.shard_map(
        _local, mesh=mesh,
        in_specs=(P("dp", None), P("dp", None), P("dp", None),
                  P("dp", None)),
        out_specs={"matched": P(), "sums": P(), "payload_sum": P()})
    jitted = jax.jit(shard_mapped)

    def step(global_pages):
        return jitted(global_pages, keys_dev, vals_dev, nreal_dev)

    step.sum_cols = sum_cols
    return step
