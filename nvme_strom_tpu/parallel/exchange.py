"""All-to-all bucket exchange: repartition scanned rows by key over ICI.

The third collective pattern the framework supplies (after psum
aggregation in :mod:`.dscan` and ppermute ring streaming in :mod:`.ring`):
**all-to-all repartitioning**, the Ulysses/expert-parallel data movement.
Use case here: distributed GROUP BY / bucketed sort where each device must
end up owning *all* rows whose key falls in its bucket range — after a
dp-sharded scan, rows live wherever their page landed, so they must be
exchanged.

XLA needs static shapes, so the exchange uses **fixed per-bucket
capacity** with counts + padding — exactly the MoE token-dispatch
discipline (capacity-factor drops are reported, never silent:
``n_dropped`` comes back with the result).

Layout contract: each device presents ``(n_buckets, capacity, width)``
send slabs (slot ``b`` = rows bound for device ``b``);
``jax.lax.all_to_all`` over ``dp`` swaps slab *b* to device *b*, giving
every device one slab from each peer.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ._compat import shard_map
from .mesh import make_scan_mesh

__all__ = ["make_bucket_exchange", "bucket_dispatch"]


def bucket_dispatch(rows, bucket, ok, dp: int, capacity: int, *,
                    fill_value: int = 0):
    """Shard-local MoE-style dispatch + all_to_all (shared by the bucket
    exchange and :mod:`.sort`; call inside shard_map over a ``dp`` axis).

    ``rows (N, width) int32``, ``bucket (N,) int32`` owner device ids,
    ``ok (N,) bool`` rows eligible to send.  Rows rank within their
    (device, bucket); rank ≥ *capacity* is dropped.  Returns

    * ``recv (dp*capacity, width)`` — this device's bucket, one
      capacity-slab per sender, padded with *fill_value*,
    * ``recv_counts (dp,)`` — valid rows per sender slab,
    * ``keep (N,) bool`` — which local rows were actually sent (drop
      accounting is the caller's: ``sum(valid) - sum(keep)``).
    """
    onehot = (bucket[:, None] == jnp.arange(dp)[None, :]) & ok[:, None]
    oh32 = onehot.astype(jnp.int32)
    # rank = number of earlier same-bucket rows (the MoE dispatch rank)
    rank = jnp.cumsum(oh32, axis=0) - oh32              # (N, dp)
    pos = jnp.sum(rank * oh32, axis=1)                  # (N,)
    keep = ok & (pos < capacity)

    # scatter into the (dp, capacity, width) send slab; rejected rows are
    # routed out of bounds so mode="drop" discards them instead of
    # clobbering slot (0, 0)
    width = rows.shape[1]
    slab = jnp.full((dp, capacity, width), fill_value, jnp.int32)
    slot_b = jnp.where(keep, bucket, dp)
    slot_c = jnp.where(keep, pos, capacity)
    slab = slab.at[slot_b, slot_c].set(rows, mode="drop")
    sent = jnp.sum(oh32 * keep[:, None].astype(jnp.int32), axis=0)

    # the collective: slab axis 0 splits across dp, the local batch axis
    # concatenates — every device receives its own bucket from every peer
    recv = jax.lax.all_to_all(slab[None], "dp", split_axis=1,
                              concat_axis=0, tiled=False)
    recv = recv.reshape(dp * capacity, width)
    recv_counts = jax.lax.all_to_all(sent[None, :, None], "dp",
                                     split_axis=1, concat_axis=0,
                                     tiled=False).reshape(dp)
    return recv, recv_counts, keep


def make_bucket_exchange(devices: Optional[Sequence[jax.Device]] = None, *,
                         capacity: int, width: int,
                         fill_value: int = 0):
    """Build the jitted exchange over a 1-D ``dp`` mesh.

    Returns ``(run, mesh)``.  ``run(rows, keys, valid)`` with

    * ``rows`` — ``(N, width)`` int32, dp-sharded on the leading axis,
    * ``keys`` — ``(N,)`` int32 owner bucket in ``[0, dp)``,
    * ``valid`` — ``(N,)`` bool row mask,

    yields per device (stacked to global ``(dp, ...)`` arrays):

    * ``rows`` — ``(dp, dp*capacity, width)``: all rows whose key names
      this device, padded with ``fill_value``,
    * ``count`` — ``(dp,)`` received-row count,
    * ``n_dropped`` — scalar, rows lost to the capacity bound (MoE-style
      capacity overflow, reported for the caller to resize and rerun).
    """
    mesh = make_scan_mesh(devices, sp=1)
    dp = mesh.shape["dp"]

    def _local(rows, keys, valid):
        # out-of-range keys are drops, never silent (and never allowed to
        # reach the scatter, where a negative index would wrap)
        ok = valid & (keys >= 0) & (keys < dp)
        recv, recv_counts, keep = bucket_dispatch(
            rows, keys, ok, dp, capacity, fill_value=fill_value)
        # counts capacity overflow AND bad-key rows the caller marked valid
        n_dropped = jnp.sum(valid) - jnp.sum(keep)
        count = jnp.sum(recv_counts)
        return {"rows": recv[None], "count": count[None],
                "n_dropped": jax.lax.psum(n_dropped, "dp")}

    shard_mapped = shard_map(
        _local, mesh=mesh,
        in_specs=(P("dp", None), P("dp"), P("dp")),
        out_specs={"rows": P("dp", None, None), "count": P("dp"),
                   "n_dropped": P()})
    step = jax.jit(shard_mapped)

    def run(rows_np, keys_np, valid_np=None):
        n = len(keys_np)
        if valid_np is None:
            valid_np = np.ones(n, bool)
        rows_np = np.asarray(rows_np, np.int32)
        keys_np = np.asarray(keys_np, np.int32)
        valid_np = np.asarray(valid_np, bool)
        pad = (-n) % dp
        if pad:
            # scan outputs are rarely dp-divisible: pad with invalid rows
            rows_np = np.concatenate(
                [rows_np, np.zeros((pad, width), np.int32)])
            keys_np = np.concatenate([keys_np, np.zeros(pad, np.int32)])
            valid_np = np.concatenate([valid_np, np.zeros(pad, bool)])
        sh = NamedSharding(mesh, P("dp"))
        rows = jax.device_put(rows_np, NamedSharding(mesh, P("dp", None)))
        keys = jax.device_put(keys_np, sh)
        valid = jax.device_put(valid_np, sh)
        return step(rows, keys, valid)

    return run, mesh
