from .dscan import make_distributed_scan_step, shard_pages
from .mesh import make_scan_mesh, pages_sharding
from .ring import (make_ring_multi_query_scan, permute_backend,
                   ring_all_gather, ring_permute_step)
from .shardload import load_pages_multihost, shard_ownership
from .sort import make_distributed_distinct, make_distributed_sort
from .stream import (ShardedBatchStream, distributed_scan_filter,
                     load_pages_sharded)

__all__ = ["make_distributed_scan_step", "shard_pages", "make_scan_mesh",
           "pages_sharding", "make_ring_multi_query_scan",
           "make_distributed_sort", "make_distributed_distinct",
           "load_pages_sharded", "load_pages_multihost", "shard_ownership",
           "permute_backend", "ring_permute_step", "ring_all_gather",
           "ShardedBatchStream", "distributed_scan_filter"]
