from .dscan import make_distributed_scan_step

__all__ = ["make_distributed_scan_step"]
