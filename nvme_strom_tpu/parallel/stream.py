"""Sharded direct loading: stripe a source across the device mesh.

The RAID-0 fan-out analog over the mesh (SURVEY.md SS5.8c): where the
reference stripes one logical stream across NVMe members in-kernel
(`kmod/nvme_strom.c:823-910`), here the *destination* is striped — every
device owns a disjoint page range of the global array, and each process
direct-loads only the ranges of its **addressable** devices, so the loader
is multi-host correct by construction (each host reads its own shard from
its own storage; no cross-host data moves at load time — the collectives
that later consume the array ride ICI/DCN).

The global array is assembled with
``jax.make_array_from_single_device_arrays`` — no host ever materializes
the full table.
"""

from __future__ import annotations

import errno as _errno
import time
from typing import List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..api import StromError
from ..engine import Session, Source, reorder_chunks
from ..hbm.staging import safe_device_put
from ..scan.heap import PAGE_SIZE
from ..stats import stats
from ..trace import recorder

__all__ = ["load_pages_sharded", "ShardedBatchStream", "distributed_scan_filter"]


def load_pages_sharded(source: Source, mesh: Mesh, *,
                       session: Optional[Session] = None,
                       axis: str = "dp") -> jax.Array:
    """Direct-load a page-formatted source into a (n_pages, PAGE_SIZE)
    global array sharded over *axis* of *mesh*.

    Each addressable device's row range is read through the engine's
    direct path (page-granular chunks) into a pinned buffer and placed on
    that device; the returned global array is sharded ``P(axis, None)``.
    ``n_pages`` must divide evenly by the axis size.
    """
    if source.size % PAGE_SIZE:
        raise StromError(22, f"source size {source.size} not page-aligned")
    n_pages = source.size // PAGE_SIZE
    n_shards = mesh.shape[axis]
    if n_pages % n_shards:
        raise StromError(22, f"{n_pages} pages not divisible by {n_shards} "
                             f"'{axis}' shards; pad the source")
    sharding = NamedSharding(mesh, P(axis, None))
    global_shape = (n_pages, PAGE_SIZE)
    idx_map = sharding.addressable_devices_indices_map(global_shape)

    own_session = session is None
    sess = session or Session()
    shards = []
    try:
        for dev, idx in idx_map.items():
            rows = idx[0]
            r0 = rows.start or 0
            r1 = rows.stop if rows.stop is not None else n_pages
            nbytes = (r1 - r0) * PAGE_SIZE
            handle, buf = sess.alloc_dma_buffer(nbytes)
            try:
                want = list(range(r0, r1))
                res = sess.memcpy_ssd2ram(source, handle, want, PAGE_SIZE)
                sess.memcpy_wait(res.dma_task_id)
                host = reorder_chunks(
                    np.frombuffer(buf.view()[:nbytes], np.uint8),
                    PAGE_SIZE, res.chunk_ids, want).reshape(r1 - r0,
                                                            PAGE_SIZE)
                shards.append(safe_device_put(host, dev))
            finally:
                sess.unmap_buffer(handle)
                buf.close()
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, shards)
    finally:
        if own_session:
            sess.close()


class ShardedBatchStream:
    """Stream fixed-size page batches to the mesh with submit-ahead DMA.

    The distributed form of the executor's async ring (`pgsql/nvme_strom.c:
    862-936`): while the consumer's step runs on batch *b*, batch *b+1*'s
    SSD DMAs are already in flight into a second set of pinned buffers
    (one double-buffer pair per addressable device).  Buffer reuse is
    fenced on the previous batch's device arrays being ready — the H2D
    read must complete before the SSD engine overwrites the pinned pages.

    Yields ``(first_page, global_array)`` with the array sharded
    ``P(axis, None)`` over *mesh* — ready for a shard_map'ed step.
    ``batch_pages`` must divide by the axis size; the final partial batch
    is dropped if it cannot fill every shard evenly (callers scan tails
    separately, as with the executor's tail path).
    """

    def __init__(self, source: Source, mesh: Mesh, *, batch_pages: int,
                 session: Optional[Session] = None, axis: str = "dp"):
        n_shards = mesh.shape[axis]
        if batch_pages <= 0 or batch_pages % n_shards:
            raise StromError(22, f"batch_pages {batch_pages} must divide by "
                                 f"{n_shards} '{axis}' shards")
        if source.size % PAGE_SIZE:
            raise StromError(22, "source size not page-aligned")
        self.source = source
        self.mesh = mesh
        self.axis = axis
        self.batch_pages = batch_pages
        self.n_pages = source.size // PAGE_SIZE
        self.n_batches = self.n_pages // batch_pages
        self.sharding = NamedSharding(mesh, P(axis, None))
        self._own_session = session is None
        self.session = session or Session()
        self._shape = (batch_pages, PAGE_SIZE)
        self._idx = list(self.sharding.addressable_devices_indices_map(
            self._shape).items())
        per_shard = batch_pages // n_shards * PAGE_SIZE
        # double buffering: ring of 2 pinned buffers per addressable shard
        self._bufs = [[self.session.alloc_dma_buffer(per_shard)
                       for _ in range(2)] for _ in self._idx]
        self._fence: List[Optional[jax.Array]] = [None, None]

    def _submit(self, b: int):
        from ..hbm.staging import bounded_fence
        ring = b % 2
        if self._fence[ring] is not None:
            # bounded: a dead backend fails the stream with ENODEV
            # instead of hanging the double-buffer rotation
            bounded_fence(self._fence[ring], "mesh-h2d")
            self._fence[ring] = None
        tasks = []
        base = b * self.batch_pages
        for k, (dev, idx) in enumerate(self._idx):
            rows = idx[0]
            r0 = base + (rows.start or 0)
            r1 = base + (rows.stop if rows.stop is not None else self.batch_pages)
            handle, _buf = self._bufs[k][ring]
            res = self.session.memcpy_ssd2ram(
                self.source, handle, list(range(r0, r1)), PAGE_SIZE)
            # submit timestamp rides with the task: the fan-in loop below
            # turns it into the per-shard wait distribution
            tasks.append((dev, res, time.monotonic_ns()))
        return ring, tasks

    def _collect(self, ring, tasks) -> jax.Array:
        shards: List[Optional[jax.Array]] = [None] * len(tasks)

        def account(k) -> None:
            # straggler attribution (ISSUE 17): the batch is gated on its
            # SLOWEST shard, so record each shard's submit->completion
            # wait where the aggregate histogram can't smear it — one
            # log2-ns histogram per mesh shard plus a flight-recorder span
            t1 = time.monotonic_ns()
            stats.shard_wait(k, t1 - tasks[k][2])
            if recorder.active:
                recorder.span("shard_wait", tasks[k][2], t1,
                              args={"shard": k})

        def place(k, done) -> None:
            _handle, buf = self._bufs[k][ring]
            # slot i holds chunk chunk_ids[i]: with a partially cached
            # source the engine fronts direct-I/O chunks and tails
            # write-back chunks, so restore file order before placement
            host = reorder_chunks(np.frombuffer(buf.view(), np.uint8),
                                  PAGE_SIZE, done.chunk_ids,
                                  sorted(done.chunk_ids)).reshape(-1, PAGE_SIZE)
            shards[k] = safe_device_put(host, tasks[k][0])

        # completion fan-in (PR 5): with per-member engine lanes the
        # shards' SSD DMAs finish independently, so start each device's
        # H2D as soon as ITS shard lands instead of serializing the whole
        # batch behind shard 0's lane
        remaining = list(range(len(tasks)))
        while remaining:
            progressed = False
            for k in list(remaining):
                try:
                    done = self.session.memcpy_wait(
                        tasks[k][1].dma_task_id, timeout=0.0)
                except StromError as e:
                    if e.errno == _errno.ETIMEDOUT:
                        continue
                    raise
                account(k)
                place(k, done)
                remaining.remove(k)
                progressed = True
            if remaining and not progressed:
                k = remaining.pop(0)
                done = self.session.memcpy_wait(tasks[k][1].dma_task_id)
                account(k)
                place(k, done)
        arr = jax.make_array_from_single_device_arrays(
            self._shape, self.sharding, shards)
        self._fence[ring] = arr
        return arr

    def __iter__(self):
        if self.n_batches == 0:
            return
        pending = self._submit(0)
        for b in range(self.n_batches):
            nxt = self._submit(b + 1) if b + 1 < self.n_batches else None
            arr = self._collect(*pending)
            yield b * self.batch_pages, arr
            pending = nxt

    def close(self) -> None:
        for ring in self._bufs:
            for handle, buf in ring:
                try:
                    self.session.unmap_buffer(handle)
                except StromError:
                    pass
                buf.close()
        self._bufs = []
        if self._own_session:
            self.session.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def distributed_scan_filter(source: Source, mesh: Mesh, step, *,
                            batch_pages: int,
                            session: Optional[Session] = None,
                            combine=None) -> dict:
    """Fold a shard_map'ed *step* over the source, streamed batch-wise.

    ``step(global_pages, ...)``-style callables from
    :func:`..parallel.dscan.make_distributed_scan_step` take the threshold
    positionally; here *step* is ``step(global_pages) -> dict`` (bind any
    parameters with a lambda).  Results are summed per key (or folded with
    *combine*).  This is the pgsql parallel SeqScan shape at mesh scale:
    bounded memory (2 pinned buffers per shard + 1 resident batch per
    device), SSD DMA / H2D / device compute all overlapped.
    """
    from ..scan.executor import fold_results

    acc = None
    with ShardedBatchStream(source, mesh, batch_pages=batch_pages,
                            session=session) as stream:
        for _first, arr in stream:
            acc = fold_results(acc, step(arr), combine)
    # per-leaf: heterogeneous list leaves keep their acc dtypes
    return {} if acc is None else jax.tree.map(np.asarray, acc)
