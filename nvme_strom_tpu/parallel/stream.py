"""Sharded direct loading: stripe a source across the device mesh.

The RAID-0 fan-out analog over the mesh (SURVEY.md SS5.8c): where the
reference stripes one logical stream across NVMe members in-kernel
(`kmod/nvme_strom.c:823-910`), here the *destination* is striped — every
device owns a disjoint page range of the global array, and each process
direct-loads only the ranges of its **addressable** devices, so the loader
is multi-host correct by construction (each host reads its own shard from
its own storage; no cross-host data moves at load time — the collectives
that later consume the array ride ICI/DCN).

The global array is assembled with
``jax.make_array_from_single_device_arrays`` — no host ever materializes
the full table.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..api import StromError
from ..engine import Session, Source
from ..scan.heap import PAGE_SIZE

__all__ = ["load_pages_sharded"]


def load_pages_sharded(source: Source, mesh: Mesh, *,
                       session: Optional[Session] = None,
                       axis: str = "dp") -> jax.Array:
    """Direct-load a page-formatted source into a (n_pages, PAGE_SIZE)
    global array sharded over *axis* of *mesh*.

    Each addressable device's row range is read through the engine's
    direct path (page-granular chunks) into a pinned buffer and placed on
    that device; the returned global array is sharded ``P(axis, None)``.
    ``n_pages`` must divide evenly by the axis size.
    """
    if source.size % PAGE_SIZE:
        raise StromError(22, f"source size {source.size} not page-aligned")
    n_pages = source.size // PAGE_SIZE
    n_shards = mesh.shape[axis]
    if n_pages % n_shards:
        raise StromError(22, f"{n_pages} pages not divisible by {n_shards} "
                             f"'{axis}' shards; pad the source")
    sharding = NamedSharding(mesh, P(axis, None))
    global_shape = (n_pages, PAGE_SIZE)
    idx_map = sharding.addressable_devices_indices_map(global_shape)

    own_session = session is None
    sess = session or Session()
    shards = []
    try:
        for dev, idx in idx_map.items():
            rows = idx[0]
            r0 = rows.start or 0
            r1 = rows.stop if rows.stop is not None else n_pages
            nbytes = (r1 - r0) * PAGE_SIZE
            handle, buf = sess.alloc_dma_buffer(nbytes)
            try:
                res = sess.memcpy_ssd2ram(source, handle,
                                          list(range(r0, r1)), PAGE_SIZE)
                sess.memcpy_wait(res.dma_task_id)
                # chunk granularity == page, so reordering cannot occur
                # across pages; still, land pages at their true slots
                host = np.frombuffer(buf.view()[:nbytes], np.uint8).reshape(
                    r1 - r0, PAGE_SIZE)
                if res.chunk_ids != list(range(r0, r1)):
                    order = np.argsort(np.asarray(res.chunk_ids))
                    host = host[order]
                shards.append(jax.device_put(np.ascontiguousarray(host), dev))
            finally:
                sess.unmap_buffer(handle)
                buf.close()
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, shards)
    finally:
        if own_session:
            sess.close()
