"""Distributed sample sort: a full ORDER BY at mesh scale.

Completes the scan-compute tier's ordering story: :mod:`..ops.topk` covers
``ORDER BY .. LIMIT k`` with a streaming fold, this module sorts the whole
key set across the ``dp`` mesh — the capability a CUDA framework would
build on multi-GPU radix sort and the reference (a storage engine) leaves
to PostgreSQL's executor.

TPU-native shape (everything static, one jitted shard_map):

1. **local sort** per device (``lax.sort`` — bitonic on TPU),
2. **splitter election**: every device contributes ``dp`` local quantile
   samples; an ``all_gather`` + sort of the ``dp²`` samples yields the
   ``dp-1`` global splitters (classic sample sort — splitters balance the
   buckets to ~N/dp each with high probability),
3. **bucket exchange**: ``searchsorted(splitters, v)`` names each
   element's owner device; a fixed-capacity ``all_to_all`` slab exchange
   moves them (the same MoE token-dispatch discipline as
   :mod:`.exchange` — capacity drops are counted, never silent),
4. **local sort of the received bucket** → device *b* holds the *b*-th
   globally-ordered key range; concatenating the per-device prefixes in
   mesh order is the sorted sequence.

Values may be int32 or float32 (floats ride the slab as an
order-irrelevant bitcast and are restored before the final sort); an
optional int32 payload (e.g. global row positions from the scan)
permutes with the keys.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ._compat import shard_map
from .mesh import make_scan_mesh

__all__ = ["make_distributed_sort", "make_distributed_distinct",
           "distributed_sort_u64"]

_I32_MAX = np.int32((1 << 31) - 1)


def make_distributed_sort(devices: Optional[Sequence[jax.Device]] = None, *,
                          capacity: int, dtype=np.int32,
                          descending: bool = False,
                          with_payload: bool = True):
    """Build the jitted distributed sort over a 1-D ``dp`` mesh.

    ``capacity`` — received-elements bound per (sender, receiver) pair;
    a bucket can absorb up to ``dp * capacity`` elements, so ``capacity ≳
    (N/dp²) · safety`` keeps drops at zero for near-uniform data (drops
    are reported via ``n_dropped``, resize and rerun on overflow).

    Returns ``(run, mesh)``.  ``run(values, payload=None, valid=None)``
    with ``values (N,)`` dp-sharded yields global ``(dp, dp*capacity)``
    arrays:

    * ``values`` — device *b*'s row sorted (descending if requested),
      padded at the tail with the dtype's worst value,
    * ``payload`` — int32, permuted with values (-1 padding),
    * ``count`` — ``(dp,)`` valid elements per device row,
    * ``n_dropped`` — scalar capacity-overflow count.

    Global order = concatenation of row ``b``'s first ``count[b]``
    elements for ``b = 0..dp-1``.

    ``with_payload=False`` drops the payload column from the all_to_all
    slab (halves exchange bytes; ``payload`` is then absent from the
    result) — for value-only consumers like COUNT(DISTINCT).
    """
    mesh = make_scan_mesh(devices, sp=1)
    dp = mesh.shape["dp"]
    dt = np.dtype(dtype)
    if dt not in (np.dtype(np.int32), np.dtype(np.uint32),
                  np.dtype(np.float32)):
        raise ValueError(f"sort supports int32/uint32/float32 values, "
                         f"got {dt}")
    is_f = dt.kind == "f"
    if is_f:
        worst = np.array(-np.inf if descending else np.inf, dt)
    else:
        info = np.iinfo(dt)
        worst = np.array(info.min if descending else info.max, dt)
    # the all_to_all slab is int32; float AND uint values ride it as an
    # order-free bitcast (restored on receive)
    rebit = dt != np.dtype(np.int32)

    def key_of(v):
        # order-reversing transforms that cannot overflow (ops/topk.py)
        if not descending:
            return v
        return -v if is_f else ~v

    def _local(values, payload, valid):
        n = values.shape[0]
        # 1+2. splitter election: sort the local keys (invalid ride as the
        # worst key, i.e. to the tail), take dp quantiles of the valid
        # prefix, all_gather them, and cut the dp-1 global splitters — all
        # in key space, so descending order works unchanged
        v = jnp.where(valid, values, worst)
        nvalid = jnp.sum(valid.astype(jnp.int32))
        sorted_keys = jnp.sort(key_of(v))
        qpos = ((jnp.arange(dp) + 1) * nvalid) // (dp + 1)
        qpos = jnp.clip(qpos, 0, n - 1)
        local_samples = sorted_keys[qpos]
        all_samples = jax.lax.all_gather(local_samples, "dp").reshape(-1)
        all_samples = jnp.sort(all_samples)
        splitters = all_samples[(jnp.arange(dp - 1) + 1) * dp]

        # 3. owner bucket per element (key space keeps it monotone);
        # dispatch + all_to_all shared with the bucket exchange
        from .exchange import bucket_dispatch
        bucket = jnp.searchsorted(splitters, key_of(values),
                                  side="right").astype(jnp.int32)
        vbits = jax.lax.bitcast_convert_type(values, jnp.int32) \
            if rebit else values
        cols = [vbits, payload] if with_payload else [vbits]
        recv, counts, keep = bucket_dispatch(
            jnp.stack(cols, -1), bucket, valid, dp, capacity)
        n_dropped = jnp.sum(valid) - jnp.sum(keep)

        # 4. local sort of the received bucket; pad slots (slot >= its
        # sub-slab's count) sort to the tail
        slot = jnp.arange(dp * capacity) % capacity
        src = jnp.arange(dp * capacity) // capacity
        got = slot < counts[src]
        rv = recv[:, 0]
        if rebit:
            rv = jax.lax.bitcast_convert_type(rv, jnp.dtype(dt))
        rv = jnp.where(got, rv, worst)
        out = {"count": jnp.sum(counts)[None],
               "n_dropped": jax.lax.psum(n_dropped, "dp")}
        # secondary pad-flag key: a REAL key equal to the worst value
        # (e.g. uint32 max in a packed composite word) must sort before
        # the pad slots sharing that value, or the count-prefix read
        # would swallow pads and drop real rows
        padflag = (~got).astype(jnp.int32)
        if with_payload:
            rp = jnp.where(got, recv[:, 1], -1)
            _, _, sv, sp = jax.lax.sort((key_of(rv), padflag, rv, rp),
                                        num_keys=2)
            out["values"], out["payload"] = sv[None], sp[None]
        else:
            sv = jax.lax.sort((key_of(rv), padflag, rv), num_keys=2)[2]
            out["values"] = sv[None]
        return out

    out_specs = {"values": P("dp", None), "count": P("dp"),
                 "n_dropped": P()}
    if with_payload:
        out_specs["payload"] = P("dp", None)
    shard_mapped = shard_map(
        _local, mesh=mesh,
        in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=out_specs)
    step = jax.jit(shard_mapped)

    def run(values_np, payload_np=None, valid_np=None):
        values_np = np.asarray(values_np, dt)
        n = len(values_np)
        if payload_np is None:
            payload_np = np.arange(n, dtype=np.int32)
        payload_np = np.asarray(payload_np, np.int32)
        if valid_np is None:
            valid_np = np.ones(n, bool)
        valid_np = np.asarray(valid_np, bool)
        # zero-length shards break the in-kernel gathers: an empty input
        # still ships one invalid row per shard
        pad = (-n) % dp if n else dp
        if pad:
            values_np = np.concatenate([values_np, np.zeros(pad, dt)])
            payload_np = np.concatenate(
                [payload_np, np.full(pad, -1, np.int32)])
            valid_np = np.concatenate([valid_np, np.zeros(pad, bool)])
        sh = NamedSharding(mesh, P("dp"))
        out = step(jax.device_put(values_np, sh),
                   jax.device_put(payload_np, sh),
                   jax.device_put(valid_np, sh))
        return out

    return run, mesh


def distributed_sort_u64(mesh, values: np.ndarray,
                         payload: np.ndarray):
    """STABLE distributed sort of uint64 keys over the mesh — LSD radix
    riding the uint32 sample sort twice (VERDICT r3 #4: composite-index
    packed keys scale through the same machinery as single-column ORDER
    BY, no host argsort).

    Two stable passes: sort by the low word carrying the row index, then
    sort by the high word in low-sorted order.  Stability end-to-end
    (rank-preserving dispatch + sender-major slabs over contiguous input
    ranges + ``is_stable`` local sorts) makes the result permutation
    bit-identical to ``np.argsort(values, kind="stable")`` — duplicate
    keys keep physical order, the sidecar contract.

    Returns ``(sorted_values, payload_permuted)`` as host arrays.
    *payload* may be any dtype (it is permuted host-side; only the int32
    row index rides the exchange, so ``len(values)`` must fit int32)."""
    values = np.ascontiguousarray(values, np.uint64)
    payload = np.asarray(payload)
    n = len(values)
    if n == 0:
        return values.copy(), payload.copy()
    if n > np.iinfo(np.int32).max:
        raise ValueError("distributed_sort_u64: row index exceeds int32")
    devices = list(mesh.devices.reshape(-1))
    dp = len(devices)
    hi = (values >> np.uint64(32)).astype(np.uint32)
    lo = (values & np.uint64(0xFFFFFFFF)).astype(np.uint32)

    def one_pass(keys32: np.ndarray, pay: np.ndarray) -> np.ndarray:
        # same 2.5x-slack + double-on-overflow capacity loop as the
        # ORDER BY family (scan/query.py _mesh_sort_loop)
        capacity = max(64, -(-n * 5 // (2 * dp * dp)))
        while True:
            run, _ = make_distributed_sort(devices, capacity=capacity,
                                           dtype=np.uint32)
            out = run(keys32, pay)
            if int(out["n_dropped"]) == 0:
                counts = np.asarray(out["count"])
                pays = np.asarray(out["payload"])
                return np.concatenate(
                    [pays[b][:counts[b]] for b in range(dp)])
            capacity *= 2

    perm1 = one_pass(lo, np.arange(n, dtype=np.int32))
    perm = one_pass(hi[perm1], perm1)
    return values[perm], payload[perm]


def make_distributed_distinct(devices=None, *, capacity: int,
                              dtype=np.int32):
    """COUNT(DISTINCT col) over the mesh: distributed sample sort, then an
    on-device adjacent-diff per bucket, reduced with psum.

    No cross-device boundary handling is needed — bucket assignment is
    ``searchsorted`` on the VALUE, so every copy of an equal key lands in
    the same bucket by construction; a run can never span devices.  (A
    ppermute "dedup" here would only ever misfire, e.g. on a sentinel
    collision with an empty predecessor bucket.)

    NaNs count individually (IEEE ``!=`` semantics — each NaN is its own
    value, as the local path also implements).

    Returns ``(run, mesh)``; ``run(values, valid=None)`` yields
    ``{"distinct": scalar int32, "n_dropped": scalar}``."""
    import jax

    sort_run, mesh = make_distributed_sort(devices, capacity=capacity,
                                           dtype=dtype, with_payload=False)

    def _local(vals_row, count_row):
        v = vals_row.reshape(-1)                  # (dp*capacity,) sorted,
        n = count_row.reshape(())                 # first n valid
        idx = jnp.arange(v.shape[0])
        valid = idx < n
        prev_ok = valid & (idx > 0)
        new_run = valid & jnp.where(
            prev_ok, v != jnp.roll(v, 1), True)   # first valid starts a run
        return jax.lax.psum(jnp.sum(new_run.astype(jnp.int32)), "dp")[None]

    counted = jax.jit(shard_map(
        _local, mesh=mesh,
        in_specs=(P("dp", None), P("dp")),
        out_specs=P()))

    def run(values_np, valid_np=None):
        out = sort_run(values_np, valid_np=valid_np)
        distinct = counted(out["values"], out["count"])
        return {"distinct": np.asarray(distinct).reshape(())[()],
                "n_dropped": np.asarray(out["n_dropped"])}

    return run, mesh
