"""Mesh construction and sharding rules for the distributed scan engine.

The reference's scale-out story is storage-side: RAID-0 striping across
NVMe devices (`kmod/nvme_strom.c:823-910`) and process-parallel scans over
a shared cursor (`pgsql/nvme_strom.c:1057-1112`).  The TPU rebuild scales
compute-side with one idiom: pick a `jax.sharding.Mesh`, annotate shardings,
let XLA insert the collectives (SURVEY.md SS5.8).

Axes used by this framework:

* ``dp`` — data parallel: page batches are split along their leading axis
  (the atomic-cursor analog; each device scans a disjoint page subset).
* ``sp`` — schema/column parallel: wide schemas split their column set so
  each lane decodes and aggregates only its columns (the tensor-parallel
  analog for tabular scans).

``dp`` is laid out on the fastest-varying (innermost, ICI-contiguous)
device dimension so page streaming collectives ride ICI; ``sp`` lanes see
replicated pages, so their only collective is the tiny aggregate psum.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_scan_mesh", "pages_sharding", "replicated"]


def make_scan_mesh(devices: Optional[Sequence[jax.Device]] = None, *,
                   sp: int = 1) -> Mesh:
    """Build a ``(dp, sp)`` mesh over *devices* (default: all devices).

    ``sp`` must divide the device count; ``dp`` is the remainder of the
    factorization.  ``sp == 1`` gives the pure data-parallel mesh.
    """
    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if sp <= 0 or n % sp:
        raise ValueError(f"sp={sp} must divide the device count {n}")
    grid = np.asarray(devs).reshape(sp, n // sp)
    # dp innermost: adjacent devices (ICI neighbours on TPU) differ in dp
    return Mesh(grid, axis_names=("sp", "dp"))


def pages_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a page batch (B, PAGE_SIZE): split over dp, replicated
    over sp lanes."""
    return NamedSharding(mesh, P("dp", None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(pages_np: np.ndarray, mesh: Mesh) -> jax.Array:
    """Place a host page batch across the mesh's dp axis (sp-replicated)."""
    return jax.device_put(pages_np, pages_sharding(mesh))
