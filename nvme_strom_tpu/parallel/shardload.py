"""Multi-host sharded loading: per-host local reads + on-fabric shard moves.

The scale-out story (ISSUE 17, ROADMAP item 3): the reference saturates
one host's PCIe by giving every SSD's DMA engine a direct lane into
device memory; the TPU analog of "add another SSD" is "add another
host".  Here the file's chunk grid is split by a host→member ownership
map derived from the stripe config (:func:`..engine.plan_shard_ownership`
over :func:`..stripe.host_of` — the userspace mirror of the reference's
md-RAID-0 member math, ``kmod/nvme_strom.c:823-910``), each host's
engine session reads ONLY the extent shards its local NVMe set holds,
lands them in per-host device memory via the existing zero-copy landing
path, and the shards then move **device-to-device over ICI** with the
generalized ring permute (:func:`..parallel.ring.ring_permute_step`:
Pallas ``make_async_remote_copy`` on TPU, ``ppermute`` elsewhere) —
aggregate GB/s divides the file across per-host NVMe queues, and the
redistribution never bounces through host exchange.

Emulation note: a "host" here is a planning unit — on a real multi-host
mesh it is one process (``jax.process_index()``) with its own NVMe set;
on the virtual single-process mesh the loader runs one reader thread +
engine session per virtual host, which is also exactly what the
multichip gate scales (per-host submission windows are the bound on the
latency-injected synthetic, so wall time divides by host count).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..api import StromError
from ..config import config
from ..engine import Session, Source, plan_shard_ownership, reorder_chunks
from ..hbm.staging import safe_device_put
from ..scan.heap import PAGE_SIZE
from ..stats import stats
from ..trace import recorder as _trace
from ._compat import shard_map
from .ring import _mark_varying, permute_backend, ring_all_gather, \
    ring_permute_step

__all__ = ["load_pages_multihost", "shard_ownership"]


def shard_ownership(source: Source, n_hosts: int,
                    *, chunk_size: int = PAGE_SIZE) -> Dict[int, List[int]]:
    """Host → owned chunk ids for the whole of *source* (planner entry
    the tests assert partition correctness against): disjoint,
    exhaustive, member-aligned on striped sources, contiguous-range on
    single-member ones."""
    n_chunks = source.size // chunk_size
    return plan_shard_ownership(source, range(n_chunks), chunk_size, n_hosts)


def _read_host_shard(host: int, ids: List[int], source: Source,
                     session: Optional[Session]) -> np.ndarray:
    """One host's local read: submit the owned chunk grid through this
    host's OWN engine session, wait, restore caller order.  Returns an
    owned (len(ids), PAGE_SIZE) array (copied out before the pinned
    buffer unmaps)."""
    if not ids:
        return np.empty((0, PAGE_SIZE), np.uint8)
    own = session is None
    sess = session or Session()
    ts = time.monotonic_ns()
    try:
        nbytes = len(ids) * PAGE_SIZE
        handle, buf = sess.alloc_dma_buffer(nbytes)
        try:
            res = sess.memcpy_ssd2ram(source, handle, ids, PAGE_SIZE)
            sess.memcpy_wait(res.dma_task_id)
            host_rows = np.array(reorder_chunks(
                np.frombuffer(buf.view()[:nbytes], np.uint8),
                PAGE_SIZE, res.chunk_ids, ids)).reshape(len(ids), PAGE_SIZE)
        finally:
            sess.unmap_buffer(handle)
            buf.close()
    finally:
        if own:
            sess.close()
    stats.add("nr_shard_load")
    stats.add("bytes_shard_load", len(ids) * PAGE_SIZE)
    if _trace.active:
        _trace.span("shard_load", ts, time.monotonic_ns(),
                    length=len(ids) * PAGE_SIZE,
                    args={"host": host, "chunks": len(ids)})
    return host_rows


#: compiled redistribution programs keyed by (mesh, axis, rows_max,
#: rows_per_dev, transport) — a fresh jit closure per load would retrace
#: the ring scan every batch, and on the latency-bound gate the retrace
#: dwarfs the I/O being measured.  Meshes hash by value.
_redistribute_cache: dict = {}


def _make_redistribute(mesh: Mesh, axis: str, rows_max: int,
                       rows_per_dev: int, backend: Optional[str]):
    """Jit the ring redistribution: each device starts with one padded
    (data, idx) block of its host's locally-read pages, rotates it all
    the way around the *axis* ring, and scatters the rows whose file
    position lands in its own output range — after ``ring`` steps every
    page has visited its destination, so the output is the row-sharded
    file-order array, byte-identical to a single-host load."""
    ring = mesh.shape[axis]
    backend = permute_backend(backend)
    key = (mesh, axis, rows_max, rows_per_dev, backend)
    cached = _redistribute_cache.get(key)
    if cached is not None:
        return cached

    def _local(data, idx):
        me = jax.lax.axis_index(axis)
        # +1 dummy row: rows owned by other devices (and -1 padding)
        # scatter there and are dropped, so the write stays dense
        out = jnp.zeros((rows_per_dev + 1, PAGE_SIZE), jnp.uint8)

        def body(carry, _):
            data, idx, out = carry
            dest = idx - me * rows_per_dev
            ok = (idx >= 0) & (dest >= 0) & (dest < rows_per_dev)
            slot = jnp.where(ok, dest, rows_per_dev)
            out = out.at[slot].set(data)
            data = ring_permute_step(data, axis=axis, ring=ring,
                                     backend=backend)
            idx = ring_permute_step(idx, axis=axis, ring=ring,
                                    backend=backend)
            return (data, idx, out), None

        (_d, _i, out), _ = jax.lax.scan(
            body, (data, idx, _mark_varying(out, axis)), None, length=ring)
        return out[:rows_per_dev]

    fn = jax.jit(shard_map(
        _local, mesh=mesh,
        in_specs=(P(axis, None), P(axis)),
        out_specs=P(axis, None), check_rep=False))
    _redistribute_cache[key] = fn
    return fn


def load_pages_multihost(source: Source, mesh: Mesh, *,
                         hosts: Optional[int] = None,
                         axis: str = "dp",
                         session: Optional[Session] = None,
                         source_factory: Optional[Callable[[int], Source]]
                         = None,
                         backend: Optional[str] = None,
                         gather: bool = False) -> jax.Array:
    """Load a page-formatted source through *hosts* sharded engine
    sessions and redistribute over the fabric.

    Phase 1 (per-host NVMe): the chunk grid is split by the
    host-ownership map; each host's reader thread submits only its own
    chunks through its own session (``source_factory(h)`` opens that
    host's local view of the source — default: share *source*, which is
    the single-filesystem emulation).  Phase 2 (ICI): the landed shards
    rotate around the mesh ring (``config ici_permute`` transport) and
    every device keeps the rows of its final file-order range.

    Returns the ``(n_pages, PAGE_SIZE)`` global array sharded
    ``P(axis, None)`` — byte-identical to
    :func:`..parallel.stream.load_pages_sharded` of the same source —
    or, with ``gather=True``, the fully-replicated gathered array (the
    cold-start all-gather shape).
    """
    if source.size % PAGE_SIZE:
        raise StromError(22, f"source size {source.size} not page-aligned")
    n_pages = source.size // PAGE_SIZE
    n_dev = mesh.shape[axis]
    if n_pages % n_dev:
        raise StromError(22, f"{n_pages} pages not divisible by {n_dev} "
                             f"'{axis}' shards; pad the source")
    hosts = int(hosts or config.get("shard_hosts") or 1)
    if hosts < 1 or n_dev % hosts:
        raise StromError(22, f"host count {hosts} must divide the {n_dev}"
                             f"-device '{axis}' axis")
    rows_per_dev = n_pages // n_dev
    dev_per_host = n_dev // hosts

    owned = shard_ownership(source, hosts)

    # -- phase 1: per-host local reads, one engine session each --------
    host_rows: List[Optional[np.ndarray]] = [None] * hosts
    errors: List[BaseException] = []

    def _run(h: int) -> None:
        src = source_factory(h) if source_factory else source
        try:
            host_rows[h] = _read_host_shard(
                h, owned[h], src,
                session if (session is not None and hosts == 1) else None)
        except BaseException as e:  # noqa: BLE001 - re-raised below
            errors.append(e)
        finally:
            if source_factory:
                src.close()

    if hosts == 1:
        _run(0)
    else:
        threads = [threading.Thread(target=_run, args=(h,),
                                    name=f"strom-shardload-{h}")
                   for h in range(hosts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    if errors:
        raise errors[0]

    # -- split each host's rows across its device group ----------------
    per_dev: List[tuple] = []      # axis position -> (pages, ids)
    for h in range(hosts):
        rows, ids = host_rows[h], owned[h]
        q, r = divmod(len(ids), dev_per_host)
        pos = 0
        for k in range(dev_per_host):
            take = q + (1 if k < r else 0)
            per_dev.append((rows[pos:pos + take], ids[pos:pos + take]))
            pos += take
    rows_max = max(1, max(len(ids) for _, ids in per_dev))

    data_shape = (n_dev * rows_max, PAGE_SIZE)
    idx_shape = (n_dev * rows_max,)
    data_sharding = NamedSharding(mesh, P(axis, None))
    idx_sharding = NamedSharding(mesh, P(axis))
    data_map = data_sharding.addressable_devices_indices_map(data_shape)
    idx_map = idx_sharding.addressable_devices_indices_map(idx_shape)

    data_shards = []
    idx_shards = {}
    for dev, sl in data_map.items():
        p = (sl[0].start or 0) // rows_max
        pages, ids = per_dev[p]
        block = np.zeros((rows_max, PAGE_SIZE), np.uint8)
        block[:len(ids)] = pages
        index = np.full((rows_max,), -1, np.int32)
        index[:len(ids)] = ids
        data_shards.append(safe_device_put(block, dev))
        idx_shards[dev] = safe_device_put(index, dev)
    data_g = jax.make_array_from_single_device_arrays(
        data_shape, data_sharding, data_shards)
    idx_g = jax.make_array_from_single_device_arrays(
        idx_shape, idx_sharding,
        [idx_shards[dev] for dev in idx_map])

    # -- phase 2: on-fabric redistribution ------------------------------
    step = _make_redistribute(mesh, axis, rows_max, rows_per_dev, backend)
    ts = time.monotonic_ns()
    out = step(data_g, idx_g)
    out.block_until_ready()
    n_addr = len(data_map)
    moved = n_dev * n_addr * rows_max * (PAGE_SIZE + 4)
    stats.add("nr_ici_permute", n_dev)
    stats.add("bytes_ici", moved)
    if _trace.active:
        _trace.span("ici_permute", ts, time.monotonic_ns(), length=moved,
                    args={"steps": n_dev, "ring": n_dev,
                          "backend": permute_backend(backend),
                          "hosts": hosts})
    if gather:
        ts = time.monotonic_ns()
        gathered = ring_all_gather(out, mesh, axis=axis, backend=backend)
        gathered.block_until_ready()
        moved = n_dev * n_addr * rows_per_dev * PAGE_SIZE
        stats.add("nr_ici_permute", n_dev)
        stats.add("bytes_ici", moved)
        if _trace.active:
            _trace.span("ici_permute", ts, time.monotonic_ns(),
                        length=moved,
                        args={"steps": n_dev, "ring": n_dev,
                              "backend": permute_backend(backend),
                              "hosts": hosts, "gather": True})
        return gathered
    return out
