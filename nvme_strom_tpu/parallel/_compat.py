"""jax API compatibility shims for the parallel layer.

``shard_map`` moved between jax releases: new enough versions export it as
``jax.shard_map``; older ones only ship the experimental spelling
``jax.experimental.shard_map.shard_map``.  Resolve it exactly once here so
every call site (dscan/sort/ring/exchange/pjoin/stream consumers) stays
version-agnostic — this is the project's only tolerated feature probe on
the jax surface (stromlint pins the rest to literal APIs).
"""

from __future__ import annotations

try:                                    # jax >= 0.4.34 public spelling
    from jax import shard_map           # type: ignore[attr-defined]
except ImportError:                     # older jax: experimental namespace
    from jax.experimental.shard_map import shard_map  # type: ignore

__all__ = ["shard_map"]
